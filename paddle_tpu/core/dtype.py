"""Dtype system for paddle_tpu.

TPU-native equivalent of the reference's ``phi::DataType`` / dtype promotion
(``paddle/phi/common/data_type.h``, ``paddle/fluid/eager/type_promotion_utils.h``):
we lean on jax/numpy dtypes directly and expose paddle-style names
(``paddle.float32`` etc.), with promotion delegated to jnp's weak-type aware
``result_type`` so python scalars do not upcast arrays.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype objects (numpy dtype instances; jax accepts these directly).
bool_ = jnp.dtype("bool")
uint8 = jnp.dtype("uint8")
int8 = jnp.dtype("int8")
int16 = jnp.dtype("int16")
int32 = jnp.dtype("int32")
int64 = jnp.dtype("int64")
float16 = jnp.dtype("float16")
bfloat16 = jnp.dtype(jnp.bfloat16)
float32 = jnp.dtype("float32")
float64 = jnp.dtype("float64")
complex64 = jnp.dtype("complex64")
complex128 = jnp.dtype("complex128")

_NAME_TO_DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
}

FLOATING = {float16, bfloat16, float32, float64}
INTEGER = {uint8, int8, int16, int32, int64}
COMPLEX = {complex64, complex128}

_default_dtype = float32


def set_default_dtype(d):
    global _default_dtype
    _default_dtype = convert_dtype(d)


def get_default_dtype():
    return _default_dtype


def convert_dtype(d):
    """Normalize any dtype-like (str, np.dtype, python type, paddle name) to np.dtype."""
    if d is None:
        return None
    if isinstance(d, str):
        name = d
        if name.startswith("paddle."):
            name = name[len("paddle."):]
        if name in _NAME_TO_DTYPE:
            return _NAME_TO_DTYPE[name]
        return jnp.dtype(name)
    if d is bool:
        return bool_
    if d is int:
        return int64
    if d is float:
        return _default_dtype
    return jnp.dtype(d)


def is_floating(d) -> bool:
    return convert_dtype(d) in FLOATING


def is_integer(d) -> bool:
    d = convert_dtype(d)
    return d in INTEGER or d == bool_


def is_complex(d) -> bool:
    return convert_dtype(d) in COMPLEX


def promote_types(a, b):
    return jnp.promote_types(convert_dtype(a), convert_dtype(b))


def np_to_default(x: np.ndarray) -> np.ndarray:
    """Paddle-style defaulting: python floats / float64 numpy arrays become the
    default float dtype (float32) on tensor creation, int stays int64->int32 on TPU?
    Paddle keeps int64; we keep int32 for TPU friendliness unless explicitly asked."""
    if x.dtype == np.float64:
        return x.astype(_default_dtype)
    return x
