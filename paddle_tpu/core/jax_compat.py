"""Version-compatibility shims for the installed jax.

The codebase targets the current jax surface (top-level ``jax.shard_map``
with the ``check_vma`` kwarg). Older runtimes (<= 0.4.x) only ship
``jax.experimental.shard_map.shard_map`` with the equivalent kwarg named
``check_rep``. Rather than scattering try/except at every import site,
``install()`` (called once from ``paddle_tpu/__init__``) publishes a
top-level alias that adapts the kwarg — so ``from jax import shard_map``
works everywhere against either runtime. No-op on a modern jax.
"""
from __future__ import annotations

import functools

import jax


def install() -> None:
    if not hasattr(jax.lax, "pcast"):
        # varying-manual-axes (VMA) annotation; pre-VMA runtimes have no
        # such type distinction, so the value-level identity is exact
        jax.lax.pcast = lambda x, axes=None, *, to=None: x
    if not hasattr(jax, "enable_x64"):
        # the x64 context manager was promoted out of jax.experimental;
        # the pallas kernels use it to drop to i32 index arithmetic
        from jax.experimental import enable_x64 as _enable_x64
        jax.enable_x64 = _enable_x64
    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(f, /, mesh=None, *, in_specs=None, out_specs=None,
                  check_vma=None, **kwargs):
        if check_vma is not None and "check_rep" not in kwargs:
            kwargs["check_rep"] = check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kwargs)

    jax.shard_map = shard_map
