"""Eager op dispatch — the TPU-native replacement for the PHI dispatch path.

Reference hot path (SURVEY §3.1): python → generated pybind → ad_func → kernel-key
dispatch → PHI kernel (paddle/phi/api/lib/kernel_dispatch.h:53). Here an eager op is
one :func:`apply` call: unwrap ``jax.Array``s, run the jnp/lax implementation (XLA
dispatches to the current device — kernel selection, data transform, and the kernel
registry of the reference all collapse into PjRt), and, when autograd is live, record
the ``jax.vjp`` pullback on the tape (replacing generated GradNodes).
"""
from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from . import autograd
from .dtype import is_complex, is_floating


def _is_diff(t) -> bool:
    # complex counts: fft/complex-op chains carry gradients in the
    # reference (jax.vjp handles the conjugate conventions)
    from .tensor import Tensor
    return (isinstance(t, Tensor) and not t.stop_gradient
            and (is_floating(t.dtype) or is_complex(t.dtype)))


def _unwrap(t):
    from .tensor import Tensor
    return t._data if isinstance(t, Tensor) else t


_amp_dtype_for = None


def _amp_cast(name, inputs):
    """AMP autocast hook (reference: eager_amp_auto_cast.h placement in the
    generated ad_func). Casts floating Tensor inputs per the active
    auto_cast white/black lists."""
    global _amp_dtype_for
    if _amp_dtype_for is None:
        from ..amp.auto_cast import amp_dtype_for as _f
        _amp_dtype_for = _f
    from .tensor import Tensor
    target = _amp_dtype_for(name)
    if target is None:
        return inputs
    out = []
    for t in inputs:
        if isinstance(t, Tensor) and is_floating(t.dtype) \
                and t.dtype != target and t.dtype != jnp.float64:
            out.append(t.astype(target))
        else:
            out.append(t)
    return out


_op_profiler = None  # set by paddle_tpu.profiler to record per-op timing
_cf_recorder = None  # set by jit.control_flow during branch discovery
_static_graph_hook = None  # set by static.program under enable_static

# observability: per-op dispatch-latency histogram, resolved lazily from
# the env-gated metrics registry (PADDLE_TPU_METRICS=1). metrics.enable/
# disable invalidate the cache through sys.modules so a later gate change
# takes effect; when metrics are off the steady-state cost is one global
# read + None check per op.
_op_metrics = None
_op_metrics_resolved = False


def _resolve_op_metrics():
    global _op_metrics, _op_metrics_resolved
    _op_metrics_resolved = True
    try:
        from ..observability import metrics as _obs
        reg = _obs.get_registry()
        _op_metrics = reg.histogram("eager_dispatch_us") \
            if reg is not None else None
    except Exception:
        _op_metrics = None
    return _op_metrics


def apply(name: str, fwd: Callable, inputs: Sequence[Any], nout: int = 1,
          has_aux: bool = False):
    if _static_graph_hook is not None:
        recorded = _static_graph_hook(name, fwd, inputs, nout, has_aux)
        if recorded is not None:
            return recorded
    hook = _op_profiler
    om = _op_metrics if _op_metrics_resolved else _resolve_op_metrics()
    if hook is None and om is None:
        result = _apply_impl(name, fwd, inputs, nout, has_aux)
        if _cf_recorder is not None:
            _cf_recorder.note(inputs, result)
        return result
    import time
    t0 = time.perf_counter()
    result = None
    try:
        result = _apply_impl(name, fwd, inputs, nout, has_aux)
        if _cf_recorder is not None:
            _cf_recorder.note(inputs, result)
        return result
    finally:
        t1 = time.perf_counter()
        if om is not None:
            om.observe((t1 - t0) * 1e6)
        if hook is not None:
            hook(name, t0, t1, inputs, result)


def _apply_impl(name: str, fwd: Callable, inputs: Sequence[Any],
                nout: int = 1, has_aux: bool = False):
    """Execute an eager op through the autograd tape.

    fwd operates on raw jax arrays. Convention:
      - nout==1, has_aux=False: fwd returns one array
      - nout>1,  has_aux=False: fwd returns a tuple of nout arrays (all differentiable)
      - has_aux=True: fwd returns (primal_or_tuple, aux_list) where aux outputs are
        non-differentiable (e.g. argmax indices).
    Returns Tensor or tuple of Tensors (diff outputs first, then aux).
    """
    from .tensor import Tensor

    inputs = _amp_cast(name, inputs)
    arrs = [_unwrap(t) for t in inputs]
    grad_on = autograd.is_grad_enabled()
    diff_idx = [i for i, t in enumerate(inputs) if _is_diff(t)] if grad_on else []

    try:
        if not diff_idx:
            out = fwd(*arrs)
            if has_aux:
                primal, aux = out
                primals = primal if isinstance(primal, tuple) else (primal,)
                results = [Tensor(p, stop_gradient=True) for p in primals]
                results += [Tensor(a, stop_gradient=True) for a in aux]
                if _check_nan_inf:
                    _nan_check(name, results)
                return results[0] if len(results) == 1 else tuple(results)
            if nout == 1 and not isinstance(out, tuple):
                res = Tensor(out, stop_gradient=True)
                if _check_nan_inf:
                    _nan_check(name, [res])
                return res
            results = tuple(Tensor(o, stop_gradient=True) for o in out)
            if _check_nan_inf:
                _nan_check(name, results)
            return results

        # hot path (SURVEY §3.1): run ONLY the forward now; the pullback
        # is deferred to backward (autograd._materialize_vjp) — jax.vjp
        # here would trace+execute the op a second time, ~40x the cost of
        # the forward itself
        out = fwd(*arrs)
        if has_aux:
            primal, aux = out
        else:
            primal, aux = out, ()
    except Exception as e:
        if isinstance(e, _passthrough_errors()):
            raise
        raise _enrich_error(name, arrs, e) from e

    primals = primal if isinstance(primal, tuple) else (primal,)
    diff_outputs = [Tensor(p, stop_gradient=False) for p in primals]
    diff_tensors = [inputs[i] for i in diff_idx]
    autograd.record_op(name, diff_tensors, None, diff_outputs,
                       fwd=fwd, const_arrs=arrs, diff_idx=diff_idx,
                       has_aux=has_aux, lazy=True)
    results = diff_outputs + [Tensor(a, stop_gradient=True) for a in aux]
    if _check_nan_inf:
        _nan_check(name, results)
    return results[0] if len(results) == 1 else tuple(results)


_check_nan_inf = False  # toggled by FLAGS_check_nan_inf (framework/flags.py)


def _nan_check(name, tensors):
    """Reference: FLAGS_check_nan_inf hook (eager/nan_inf_utils.h). Skipped
    under tracing (tracers have no concrete values; use jax debug nans
    for staged programs)."""
    for t in tensors:
        if isinstance(t._data, jax.core.Tracer):
            return
        if is_floating(t.dtype) and not bool(jnp.all(jnp.isfinite(t._data))):
            raise FloatingPointError(
                f"(NaN/Inf) op '{name}' produced non-finite values "
                f"(shape {t.shape}, dtype {t.dtype}); set "
                "FLAGS_check_nan_inf=False to disable this check")


def _passthrough_errors():
    from .enforce import InvalidArgumentError
    return (InvalidArgumentError, FloatingPointError, KeyboardInterrupt,
            NotImplementedError)


def _enrich_error(name, arrs, e):
    """Wrap raw jax/XLA failures with op name + input signatures (the
    dispatch-level slice of the reference's enforce error stack)."""
    sigs = ", ".join(
        f"{tuple(a.shape)}:{a.dtype}" if hasattr(a, "shape") else repr(a)[:40]
        for a in arrs)
    cls = type(e) if isinstance(e, (ValueError, TypeError)) else RuntimeError
    try:
        return cls(f"(op:{name}) {e}\n  inputs: [{sigs}]")
    except Exception:
        return RuntimeError(f"(op:{name}) {e}\n  inputs: [{sigs}]")
