"""Eager op dispatch — the TPU-native replacement for the PHI dispatch path.

Reference hot path (SURVEY §3.1): python → generated pybind → ad_func → kernel-key
dispatch → PHI kernel (paddle/phi/api/lib/kernel_dispatch.h:53). Here an eager op is
one :func:`apply` call: unwrap ``jax.Array``s, run the jnp/lax implementation (XLA
dispatches to the current device — kernel selection, data transform, and the kernel
registry of the reference all collapse into PjRt), and, when autograd is live, record
the ``jax.vjp`` pullback on the tape (replacing generated GradNodes).

Fast path (ROADMAP item 4, the O(10 µs) target): with every instrumentation
hook off, one taped op is

* one read of each hook global (no imports, no registry resolution — the
  Tensor class, AMP state and metrics handle are resolved once per process),
* ONE dict lookup in the persistent compiled-callable cache
  (:data:`_jit_cache`, keyed per (op name, fwd code identity, closure
  constants, static-arg positions)), and
* ONE call into the cached ``jax.jit`` wrapper — jax's C++ pjit fast path
  keys on shape/dtype/device internally, so a shape, dtype or device change
  retraces exactly that signature and nothing else.

Python scalars in the input list are baked as jit static arguments, so a
chained ``r * 1.0001`` loop ships NO per-op host constants to the device —
this is what fixes the chained-dispatch row being slower than the single-op
row (each chained op used to re-transfer its scalar operand). Ops are
compiled on their SECOND occurrence (``_jit_seen``): one-shot signatures
(sweeps over distinct closure constants) never pay a compile. Anything the
cache cannot prove safe — unhashable closure cells, tracer inputs (an outer
``to_static`` trace is already staging), zero-array creation ops, a fwd
that needs concrete values — falls back to the direct eager call, which is
exactly the pre-cache behavior.

The NaN check (``FLAGS_check_nan_inf``) is evaluated OUTSIDE the compiled
callable and can be batched: ``FLAGS_check_nan_inf_window=N`` defers the
blocking device→host flag fetch until N results are pending (one stacked
fetch instead of one sync per op), at the cost of the error surfacing up to
N-1 ops late. The default window of 1 keeps the reference's raise-at-the-op
semantics. Toggling the flag takes effect immediately — the check is not
part of the compiled program, so cached entries survive the toggle.
"""
from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd
from .dtype import is_complex, is_floating

_Tensor = None  # resolved once (core.tensor imports ops which import us)


def _tensor_cls():
    global _Tensor
    if _Tensor is None:
        from .tensor import Tensor
        _Tensor = Tensor
    return _Tensor


def _is_diff(t) -> bool:
    # complex counts: fft/complex-op chains carry gradients in the
    # reference (jax.vjp handles the conjugate conventions)
    T = _Tensor or _tensor_cls()
    return (isinstance(t, T) and not t.stop_gradient
            and (is_floating(t.dtype) or is_complex(t.dtype)))


def _unwrap(t):
    T = _Tensor or _tensor_cls()
    return t._data if isinstance(t, T) else t


_amp_state = None  # the (threading.local) amp state object, resolved once
_amp_dtype_for = None


def _amp_enabled():
    global _amp_state
    if _amp_state is None:
        from ..amp.auto_cast import _state
        _amp_state = _state
    return _amp_state.enabled


def _amp_cast(name, inputs):
    """AMP autocast hook (reference: eager_amp_auto_cast.h placement in the
    generated ad_func). Casts floating Tensor inputs per the active
    auto_cast white/black lists."""
    global _amp_dtype_for
    if _amp_dtype_for is None:
        from ..amp.auto_cast import amp_dtype_for as _f
        _amp_dtype_for = _f
    T = _Tensor or _tensor_cls()
    target = _amp_dtype_for(name)
    if target is None:
        return inputs
    out = []
    for t in inputs:
        if isinstance(t, T) and is_floating(t.dtype) \
                and t.dtype != target and t.dtype != jnp.float64:
            out.append(t.astype(target))
        else:
            out.append(t)
    return out


_op_profiler = None  # set by paddle_tpu.profiler to record per-op timing
_cf_recorder = None  # set by jit.control_flow during branch discovery
_static_graph_hook = None  # set by static.program under enable_static

# observability: per-op dispatch-latency histogram, resolved lazily from
# the env-gated metrics registry (PADDLE_TPU_METRICS=1). metrics.enable/
# disable invalidate the cache through sys.modules so a later gate change
# takes effect; when metrics are off the steady-state cost is one global
# read + None check per op.
_op_metrics = None
_op_metrics_resolved = False


def _resolve_op_metrics():
    global _op_metrics, _op_metrics_resolved
    _op_metrics_resolved = True
    try:
        from ..observability import metrics as _obs
        reg = _obs.get_registry()
        _op_metrics = reg.histogram("eager_dispatch_us") \
            if reg is not None else None
    except Exception:
        _op_metrics = None
    return _op_metrics


def apply(name: str, fwd: Callable, inputs: Sequence[Any], nout: int = 1,
          has_aux: bool = False):
    if _static_graph_hook is not None:
        recorded = _static_graph_hook(name, fwd, inputs, nout, has_aux)
        if recorded is not None:
            return recorded
    hook = _op_profiler
    om = _op_metrics if _op_metrics_resolved else _resolve_op_metrics()
    if hook is None and om is None:
        result = _apply_impl(name, fwd, inputs, nout, has_aux)
        if _cf_recorder is not None:
            _cf_recorder.note(inputs, result)
        return result
    import time
    t0 = time.perf_counter()
    result = None
    try:
        result = _apply_impl(name, fwd, inputs, nout, has_aux)
        if _cf_recorder is not None:
            _cf_recorder.note(inputs, result)
        return result
    finally:
        t1 = time.perf_counter()
        if om is not None:
            om.observe((t1 - t0) * 1e6)
        if hook is not None:
            hook(name, t0, t1, inputs, result)


# ---- persistent compiled-callable cache ------------------------------------

_jit_cache: dict = {}       # key -> (jitted fn, keepalive object)
_jit_seen: set = set()      # keys seen once; compiled on 2nd occurrence
_jit_blacklist: set = set()
_jit_keepalive: dict = {}   # key -> keyed object, for seen/blacklisted
# keys too: an id()-based key whose object was freed could be recycled by
# a NEW callable and wrongly inherit the old key's seen/blacklist fate
_JIT_CACHE_MAX = 1024
_JIT_SEEN_MAX = 8192
_STATIC_OK = (int, float, bool, str, bytes)
_ARRAY_TYPES = (jax.Array, np.ndarray, np.generic)
_TRACER = jax.core.Tracer


def _reset_jit_cache():
    """Drop every cached compiled callable (tests / debugging)."""
    _jit_cache.clear()
    _jit_seen.clear()
    _jit_blacklist.clear()
    _jit_keepalive.clear()


def _fwd_key(name, fwd):
    """(cache key, keepalive) for a fwd callable, or (None, None) when the
    callable cannot be safely keyed: the key is the code object's identity
    plus the closure's immutable-scalar constants — a per-call lambda built
    from the same source with the same constants hits the same entry. The
    keepalive pins the keyed object so its id can never be recycled."""
    code = getattr(fwd, "__code__", None)
    if code is None:
        # builtin / ufunc (e.g. jnp.multiply): module-level, identity-keyed
        # tpu-lint: ok[RC002] returned keepalive pins fwd for the entry's lifetime (_jit_keepalive) so its id cannot be recycled
        return (name, id(fwd)), fwd
    if getattr(fwd, "__self__", None) is not None:
        # bound method: the receiver's state is neither in the code id nor
        # the closure — two instances would collide on one entry
        return None, None
    cells = fwd.__closure__
    if not cells:
        # tpu-lint: ok[RC002] returned keepalive pins the code object so its id cannot be recycled
        return (name, id(code)), code
    vals = []
    for cell in cells:
        try:
            v = cell.cell_contents
        except ValueError:  # empty cell
            return None, None
        if v is None or type(v) in _STATIC_OK:
            # key by (type, repr): plain values would collide across
            # numerically-equal types (1 == 1.0 == True) and signed zeros
            # (0.0 == -0.0), silently serving a program traced with the
            # other constant
            vals.append((type(v).__name__, repr(v)))
        else:  # arrays, Tensors, functions, mutables: not value-keyable
            return None, None
    # tpu-lint: ok[RC002] returned keepalive pins the code object so its id cannot be recycled
    return (name, id(code), tuple(vals)), code


def _run_fwd(name, fwd, arrs):
    """Execute an op forward through the compiled-callable cache (ONE dict
    lookup + ONE pjit call on the steady-state path), falling back to the
    plain eager call wherever caching cannot be proven safe."""
    statics = None
    for i, a in enumerate(arrs):
        if isinstance(a, _TRACER):
            return fwd(*arrs)  # outer trace in flight: it stages the op
        if isinstance(a, _ARRAY_TYPES):
            continue
        if a is not None and not isinstance(a, _STATIC_OK):
            return fwd(*arrs)  # unhashable operand: direct
        if statics is None:
            statics = [i]
        else:
            statics.append(i)
    if statics is not None and len(statics) == len(arrs):
        # creation-style op (no array operands): closure/static constants
        # vary per call site — caching would churn compiles
        return fwd(*arrs)
    key, keep = _fwd_key(name, fwd)
    if key is None:
        return fwd(*arrs)
    if statics is not None:
        # static VALUES are keyed by jax.jit internally by ==/hash, which
        # collides 1 with 1.0 with True and +0.0 with -0.0 — key the
        # wrapper on (type, repr) per static so numerically-equal-but-
        # distinct operands never share a traced program (repr splits the
        # signed zeros; a loop reusing ONE scalar still hits one entry)
        key = (key, tuple(statics),
               tuple((type(arrs[i]).__name__, repr(arrs[i]))
                     for i in statics))
    entry = _jit_cache.get(key)
    if entry is None:
        if key in _jit_blacklist:
            return fwd(*arrs)
        if key not in _jit_seen:
            if len(_jit_seen) < _JIT_SEEN_MAX:
                _jit_seen.add(key)
                _jit_keepalive[key] = keep
            return fwd(*arrs)  # compile only on the 2nd occurrence
        if len(_jit_cache) >= _JIT_CACHE_MAX:
            return fwd(*arrs)
        entry = (jax.jit(fwd, static_argnums=tuple(statics or ())), keep)
        _jit_cache[key] = entry
    try:
        return entry[0](*arrs)
    except Exception:
        # anything the jitted wrapper cannot express (concrete-value
        # control flow, unhashable static, jit-only tracing error) —
        # drop the entry and re-run eagerly so real user errors surface
        # from the exact code path they always did
        _jit_cache.pop(key, None)
        if len(_jit_blacklist) < _JIT_SEEN_MAX:
            _jit_blacklist.add(key)
            _jit_keepalive[key] = keep
        return fwd(*arrs)


def _apply_impl(name: str, fwd: Callable, inputs: Sequence[Any],
                nout: int = 1, has_aux: bool = False):
    """Execute an eager op through the autograd tape.

    fwd operates on raw jax arrays. Convention:
      - nout==1, has_aux=False: fwd returns one array
      - nout>1,  has_aux=False: fwd returns a tuple of nout arrays (all differentiable)
      - has_aux=True: fwd returns (primal_or_tuple, aux_list) where aux outputs are
        non-differentiable (e.g. argmax indices).
    Returns Tensor or tuple of Tensors (diff outputs first, then aux).
    """
    Tensor = _Tensor or _tensor_cls()

    st = _amp_state
    if st.enabled if st is not None else _amp_enabled():
        inputs = _amp_cast(name, inputs)
    arrs = [t._data if isinstance(t, Tensor) else t for t in inputs]
    grad_on = autograd.is_grad_enabled()
    diff_idx = [i for i, t in enumerate(inputs) if _is_diff(t)] if grad_on else []

    try:
        if not diff_idx:
            out = _run_fwd(name, fwd, arrs)
            if has_aux:
                primal, aux = out
                primals = primal if isinstance(primal, tuple) else (primal,)
                results = [Tensor(p, stop_gradient=True) for p in primals]
                results += [Tensor(a, stop_gradient=True) for a in aux]
                if _check_nan_inf:
                    _nan_queue(name, results)
                return results[0] if len(results) == 1 else tuple(results)
            if nout == 1 and not isinstance(out, tuple):
                res = Tensor(out, stop_gradient=True)
                if _check_nan_inf:
                    _nan_queue(name, [res])
                return res
            results = tuple(Tensor(o, stop_gradient=True) for o in out)
            if _check_nan_inf:
                _nan_queue(name, results)
            return results

        # hot path (SURVEY §3.1): run ONLY the forward now; the pullback
        # is deferred to backward (autograd._materialize_vjp) — jax.vjp
        # here would trace+execute the op a second time, ~40x the cost of
        # the forward itself
        out = _run_fwd(name, fwd, arrs)
        if has_aux:
            primal, aux = out
        else:
            primal, aux = out, ()
    except Exception as e:
        if isinstance(e, _passthrough_errors()):
            raise
        raise _enrich_error(name, arrs, e) from e

    primals = primal if isinstance(primal, tuple) else (primal,)
    diff_outputs = [Tensor(p, stop_gradient=False) for p in primals]
    diff_tensors = [inputs[i] for i in diff_idx]
    autograd.record_op(name, diff_tensors, None, diff_outputs,
                       fwd=fwd, const_arrs=arrs, diff_idx=diff_idx,
                       has_aux=has_aux, lazy=True)
    results = diff_outputs + [Tensor(a, stop_gradient=True) for a in aux]
    if _check_nan_inf:
        _nan_queue(name, results)
    return results[0] if len(results) == 1 else tuple(results)


# ---- NaN/Inf check (FLAGS_check_nan_inf) -----------------------------------

_check_nan_inf = False  # toggled by FLAGS_check_nan_inf (framework/flags.py)
_nan_window = 1         # FLAGS_check_nan_inf_window: results per host sync
_nan_pending: list = []  # (op name, tensor, device-side finite flag)


def _nan_queue(name, tensors):
    """Reference: FLAGS_check_nan_inf hook (eager/nan_inf_utils.h). The
    finite reduction is issued asynchronously per op; the BLOCKING flag
    fetch is deferred until ``_nan_window`` results are pending (window 1 =
    the reference's raise-at-the-op semantics). Skipped under tracing
    (tracers have no concrete values; use jax debug nans for staged
    programs)."""
    pend = _nan_pending
    for t in tensors:
        if isinstance(t._data, _TRACER):
            return
        if is_floating(t.dtype):
            pend.append((name, t, jnp.all(jnp.isfinite(t._data))))
    if len(pend) >= _nan_window:
        flush_nan_checks()


def flush_nan_checks():
    """Fetch every pending finite flag in ONE host sync and raise on the
    first non-finite result (in issue order). No-op when nothing pends."""
    global _nan_pending
    if not _nan_pending:
        return
    pending, _nan_pending = _nan_pending, []
    if len(pending) > 1:
        if bool(jnp.all(jnp.stack([f for _, _, f in pending]))):
            return
    for name, t, flag in pending:
        if not bool(flag):
            raise FloatingPointError(
                f"(NaN/Inf) op '{name}' produced non-finite values "
                f"(shape {t.shape}, dtype {t.dtype}); set "
                "FLAGS_check_nan_inf=False to disable this check")


def _nan_check(name, tensors):
    """Back-compat alias: queue + flush immediately."""
    _nan_queue(name, tensors)
    flush_nan_checks()


def _passthrough_errors():
    from .enforce import InvalidArgumentError
    return (InvalidArgumentError, FloatingPointError, KeyboardInterrupt,
            NotImplementedError)


def _enrich_error(name, arrs, e):
    """Wrap raw jax/XLA failures with op name + input signatures (the
    dispatch-level slice of the reference's enforce error stack)."""
    sigs = ", ".join(
        f"{tuple(a.shape)}:{a.dtype}" if hasattr(a, "shape") else repr(a)[:40]
        for a in arrs)
    cls = type(e) if isinstance(e, (ValueError, TypeError)) else RuntimeError
    try:
        return cls(f"(op:{name}) {e}\n  inputs: [{sigs}]")
    except Exception:
        return RuntimeError(f"(op:{name}) {e}\n  inputs: [{sigs}]")
