"""Shape/dtype preflight checks with paddle-style error messages.

Reference: PADDLE_ENFORCE_* (paddle/phi/core/enforce.h) + per-op InferMeta
(paddle/phi/infermeta/binary.cc etc.). A wrong-shape call raises ONE
actionable line before jax traces anything; everything else is caught by the
dispatch-level error enricher in core/dispatch.py.
"""
from __future__ import annotations

import numpy as np

__all__ = ["InvalidArgumentError", "check_matmul", "check_linear",
           "check_concat", "check_reshape", "check_conv2d",
           "check_embedding", "check_cross_entropy"]


class InvalidArgumentError(ValueError):
    """Analog of phi::errors::InvalidArgument."""


def _fail(op, msg):
    raise InvalidArgumentError(f"(InvalidArgument) {op}: {msg}")


def check_matmul(x_shape, y_shape, transpose_x=False, transpose_y=False):
    """Reference: MatmulInferMeta (phi/infermeta/binary.cc)."""
    if len(x_shape) == 0 or len(y_shape) == 0:
        _fail("matmul", f"inputs must be at least 1-D, got x={list(x_shape)} "
              f"y={list(y_shape)}")
    kx = x_shape[-1] if not transpose_x or len(x_shape) == 1 else x_shape[-2]
    ky = y_shape[0] if len(y_shape) == 1 else (
        y_shape[-1] if transpose_y else y_shape[-2])
    if kx != ky:
        _fail("matmul",
              f"inner dimensions must match, got x{list(x_shape)} "
              f"(K={kx}) @ y{list(y_shape)} (K={ky}); "
              f"transpose_x={transpose_x}, transpose_y={transpose_y}")


def check_linear(x_shape, w_shape, b_shape=None):
    if x_shape[-1] != w_shape[0]:
        _fail("linear",
              f"input's last dim ({x_shape[-1]}) must equal weight's first "
              f"dim ({w_shape[0]}); weight layout is [in_features, "
              f"out_features], x{list(x_shape)} w{list(w_shape)}")
    if b_shape is not None and tuple(b_shape) != (w_shape[1],):
        _fail("linear", f"bias shape {list(b_shape)} must be "
              f"[{w_shape[1]}] (out_features)")


def check_concat(shapes, axis):
    if not shapes:
        _fail("concat", "needs at least one input tensor")
    rank = len(shapes[0])
    ax = axis % rank if rank else 0
    for i, s in enumerate(shapes[1:], 1):
        if len(s) != rank:
            _fail("concat", f"all inputs must have the same rank; input 0 "
                  f"has rank {rank}, input {i} has rank {len(s)}")
        for d in range(rank):
            if d == ax:
                continue
            if s[d] != shapes[0][d]:
                _fail("concat",
                      f"non-concat dim {d} must match: input 0 is "
                      f"{list(shapes[0])}, input {i} is {list(s)} "
                      f"(axis={axis})")


def check_reshape(shape, new_shape):
    n = int(np.prod(shape)) if shape else 1
    unknown = [i for i, d in enumerate(new_shape) if d == -1]
    if len(unknown) > 1:
        _fail("reshape", f"only one dim may be -1, got {list(new_shape)}")
    known = int(np.prod([d for d in new_shape if d != -1])) \
        if new_shape else 1
    if unknown:
        if known == 0 or n % known != 0:
            _fail("reshape", f"cannot infer -1: {n} elements do not divide "
                  f"into shape {list(new_shape)}")
    elif known != n:
        _fail("reshape", f"cannot reshape {n} elements (shape "
              f"{list(shape)}) into {list(new_shape)} ({known} elements)")


def check_conv2d(x_shape, w_shape, groups=1, data_format="NCHW"):
    """Reference: ConvInferMeta."""
    if len(x_shape) != 4:
        _fail("conv2d", f"input must be 4-D {data_format}, got "
              f"{list(x_shape)}")
    if len(w_shape) != 4:
        _fail("conv2d", f"weight must be 4-D [out_c, in_c/groups, kh, kw], "
              f"got {list(w_shape)}")
    c_in = x_shape[1] if data_format[1] == "C" else x_shape[-1]
    if c_in != w_shape[1] * groups:
        _fail("conv2d",
              f"input channels ({c_in}) must equal weight's in_c/groups * "
              f"groups ({w_shape[1]} * {groups}); x{list(x_shape)} "
              f"w{list(w_shape)}")
    if w_shape[0] % groups != 0:
        _fail("conv2d", f"out_channels ({w_shape[0]}) must be divisible by "
              f"groups ({groups})")


def check_embedding(ids_dtype, w_shape):
    if len(w_shape) != 2:
        _fail("embedding", f"weight must be 2-D [num_embeddings, dim], got "
              f"{list(w_shape)}")
    if np.dtype(ids_dtype).kind not in "iu":
        _fail("embedding", f"ids must be an integer tensor, got "
              f"{ids_dtype}")


def check_cross_entropy(logits_shape, label_shape, soft_label, axis):
    if soft_label:
        if list(logits_shape) != list(label_shape):
            _fail("cross_entropy",
                  f"with soft_label=True, label shape {list(label_shape)} "
                  f"must equal logits shape {list(logits_shape)}")
        return
    rank = len(logits_shape)
    ax = axis % rank
    expect = [d for i, d in enumerate(logits_shape) if i != ax]
    got = list(label_shape)
    if got not in (expect, list(logits_shape[:ax]) + [1]
                   + list(logits_shape[ax + 1:])):
        _fail("cross_entropy",
              f"hard labels must have shape {expect} (logits "
              f"{list(logits_shape)} minus class axis {axis}), got {got}")
