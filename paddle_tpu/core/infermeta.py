"""InferMeta preflights — Paddle-style shape/dtype errors BEFORE XLA.

Reference: paddle/phi/infermeta/{unary,binary,ternary,multiary}.cc — every
op validates its inputs and emits a one-line InvalidArgument message; the
user never sees a raw backend traceback for a shape mistake. Here a rule
registry covers the top-ops by family; :func:`install` wraps the public
op functions (root namespace, op modules and Tensor methods) so the check
runs at the python boundary — the dispatch-level error enricher
(core/dispatch.py) remains the net for everything else.

Rules fail OPEN on signature drift (a TypeError applying a rule skips the
check rather than breaking a valid call) and never inspect values — only
shapes/dtypes, exactly like the reference's InferMeta contract.
"""
from __future__ import annotations

import functools

import numpy as np

from .enforce import InvalidArgumentError, _fail

__all__ = ["install", "RULES", "preflight_names"]


def _shape(t):
    return tuple(getattr(t, "shape", ()) or ())


def _is_tensor(t):
    from .tensor import Tensor
    return isinstance(t, Tensor)


def _rank(t):
    return len(_shape(t))


def _norm_axis(op, axis, rank, extra=0):
    """Validate one axis value against rank (+extra for insert ops)."""
    hi = rank + extra
    if not (-hi <= axis < hi) and not (rank == 0 and axis in (0, -1)):
        _fail(op, f"axis {axis} is out of range for rank-{rank} input "
                  f"(expected {-hi} <= axis < {hi}) "
                  f"[reference: phi/infermeta unary.cc axis checks]")
    return axis % hi if hi else 0


def _check_axis_arg(op, x, axis, extra=0):
    if axis is None or not _is_tensor(x):
        return
    r = _rank(x)
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    for a in axes:
        if isinstance(a, int):
            _norm_axis(op, a, r, extra)


# -- rule builders --------------------------------------------------------

def _axis_rule(op, extra=0, axis_pos=0):
    """axis_pos: positional index of ``axis`` AFTER x (ops like
    repeat_interleave/quantile carry another argument first)."""
    def check(x, *args, **kwargs):
        axis = kwargs.get("axis",
                          args[axis_pos] if len(args) > axis_pos else None)
        if isinstance(axis, bool):  # e.g. sum(x, keepdim) misuse — skip
            return
        _check_axis_arg(op, x, axis, extra)
    return check


def _broadcast_rule(op):
    def check(x, y=None, *args, **kwargs):
        if not (_is_tensor(x) and _is_tensor(y)):
            return
        try:
            np.broadcast_shapes(_shape(x), _shape(y))
        except ValueError:
            _fail(op, f"inputs could not be broadcast together: "
                      f"x{list(_shape(x))} vs y{list(_shape(y))} "
                      f"[reference: phi/infermeta binary.cc "
                      f"ElementwiseInferMeta]")
    return check


def _square_rule(op):
    def check(x, *args, **kwargs):
        s = _shape(x)
        if len(s) < 2:
            _fail(op, f"input must be at least 2-D, got {list(s)}")
        if s[-1] != s[-2]:
            _fail(op, f"input must be square in its last two dims, got "
                      f"{list(s)} [reference: phi/infermeta unary.cc "
                      f"CholeskyInferMeta et al.]")
    return check


def _min2d_rule(op):
    def check(x, *args, **kwargs):
        if _rank(x) < 2:
            _fail(op, f"input must be at least 2-D, got "
                      f"{list(_shape(x))}")
    return check


def _int_index_rule(op, index_pos=1):
    def check(*args, **kwargs):
        idx = kwargs.get("index", args[index_pos]
                         if len(args) > index_pos else None)
        if _is_tensor(idx) and np.dtype(str(idx.dtype)).kind not in "iu":
            _fail(op, f"index must be an integer tensor, got {idx.dtype} "
                      f"[reference: phi/infermeta GatherInferMeta]")
        x = args[0] if args else kwargs.get("x")
        axis = kwargs.get("axis", None)
        if axis is not None and isinstance(axis, int):
            _check_axis_arg(op, x, axis)
    return check


# -- per-op rules ---------------------------------------------------------

def _r_matmul(x, y, transpose_x=False, transpose_y=False, **kw):
    from .enforce import check_matmul
    if _is_tensor(x) and _is_tensor(y):
        check_matmul(_shape(x), _shape(y), transpose_x, transpose_y)


def _r_bmm(x, y, **kw):
    sx, sy = _shape(x), _shape(y)
    if len(sx) != 3 or len(sy) != 3:
        _fail("bmm", f"inputs must be 3-D, got x{list(sx)} y{list(sy)}")
    if sx[0] != sy[0]:
        _fail("bmm", f"batch sizes must match: x{list(sx)} vs y{list(sy)}")
    if sx[2] != sy[1]:
        _fail("bmm", f"inner dims must match: x{list(sx)} (K={sx[2]}) @ "
                     f"y{list(sy)} (K={sy[1]})")


def _r_dot(x, y, **kw):
    sx, sy = _shape(x), _shape(y)
    if len(sx) not in (1, 2) or len(sy) not in (1, 2):
        _fail("dot", f"inputs must be 1-D or 2-D, got x{list(sx)} "
                     f"y{list(sy)}")
    if sx[-1] != sy[-1]:
        _fail("dot", f"last dims must match: x{list(sx)} vs y{list(sy)}")


def _r_where(cond, x=None, y=None, **kw):
    if not (_is_tensor(x) and _is_tensor(y) and _is_tensor(cond)):
        return
    if np.dtype(str(cond.dtype)) != np.bool_:
        _fail("where", f"condition must be a bool tensor, got "
                       f"{cond.dtype}")
    try:
        np.broadcast_shapes(_shape(cond), _shape(x), _shape(y))
    except ValueError:
        _fail("where", f"condition{list(_shape(cond))}, x{list(_shape(x))}"
                       f" and y{list(_shape(y))} could not be broadcast "
                       f"together")


def _r_topk(x, k, axis=-1, **kw):
    if not isinstance(k, int) or _is_tensor(k):
        return
    if k < 1:
        _fail("topk", f"k must be >= 1, got {k}")
    r = _rank(x)
    if r:
        ax = _norm_axis("topk", axis if isinstance(axis, int) else -1, r)
        if k > _shape(x)[ax]:
            _fail("topk", f"k ({k}) exceeds dim {ax} size "
                          f"({_shape(x)[ax]}) of input {list(_shape(x))}")


def _r_kthvalue(x, k, axis=-1, keepdim=False, **kw):
    _r_topk(x, k, axis)


def _r_split(x, num_or_sections, axis=0, **kw):
    r = _rank(x)
    ax = _norm_axis("split", axis if isinstance(axis, int) else 0, r)
    if isinstance(num_or_sections, int):
        d = _shape(x)[ax]
        if num_or_sections <= 0 or d % num_or_sections != 0:
            _fail("split", f"dim {ax} (size {d}) is not divisible into "
                           f"{num_or_sections} equal sections "
                           f"[reference: SplitInferMeta]")


def _r_chunk(x, chunks, axis=0, **kw):
    if isinstance(chunks, int) and chunks <= 0:
        _fail("chunk", f"chunks must be positive, got {chunks}")
    _check_axis_arg("chunk", x, axis)


def _r_stack(x, axis=0, **kw):
    if not isinstance(x, (list, tuple)) or not x:
        return
    shapes = [_shape(t) for t in x if _is_tensor(t)]
    for i, s in enumerate(shapes[1:], 1):
        if s != shapes[0]:
            _fail("stack", f"all inputs must have the same shape; input 0 "
                           f"is {list(shapes[0])}, input {i} is {list(s)}")
    _check_axis_arg("stack", x[0], axis, extra=1)


def _r_expand(x, shape, **kw):
    s = _shape(x)
    tgt = list(shape)
    if len(tgt) < len(s):
        _fail("expand", f"target rank {len(tgt)} is smaller than input "
                        f"rank {len(s)} ({list(s)} -> {tgt})")
    for xd, td in zip(s[::-1], tgt[::-1]):
        if xd != 1 and td != -1 and xd != td:
            _fail("expand", f"cannot expand dim of size {xd} to {td} "
                            f"({list(s)} -> {tgt}) [reference: "
                            f"ExpandInferMeta]")


def _r_transpose(x, perm=None, **kw):
    if perm is None or not _is_tensor(x):
        return
    r = _rank(x)
    if sorted(int(p) % max(r, 1) for p in perm) != list(range(r)):
        _fail("transpose", f"perm {list(perm)} is not a permutation of "
                           f"rank-{r} input {list(_shape(x))}")


def _r_solve(x, y, **kw):
    sx, sy = _shape(x), _shape(y)
    if len(sx) < 2 or sx[-1] != sx[-2]:
        _fail("solve", f"coefficient matrix must be square, got "
                       f"{list(sx)}")
    if sy and sx[-1] != sy[-2 if len(sy) >= 2 else -1]:
        _fail("solve", f"dimension mismatch: A{list(sx)} vs b{list(sy)}")


def _r_pad(x, pad=None, *args, **kw):
    if pad is None or _is_tensor(pad):
        return
    p = list(pad)
    if len(p) % 2 != 0 or len(p) > 2 * _rank(x):
        _fail("pad", f"pad must hold an even number of entries covering "
                     f"at most every dim (rank {_rank(x)}), got {p}")


def _r_clip(x, min=None, max=None, **kw):  # noqa: A002
    if isinstance(min, (int, float)) and isinstance(max, (int, float)) \
            and min > max:
        _fail("clip", f"min ({min}) must be <= max ({max})")


def _r_cross(x, y, axis=None, **kw):
    sx = _shape(x)
    if axis is None:
        if 3 not in sx:
            _fail("cross", f"no dim of size 3 in input {list(sx)}")
    else:
        ax = _norm_axis("cross", axis, len(sx))
        if sx[ax] != 3:
            _fail("cross", f"dim {axis} must have size 3, got {list(sx)}")


def _r_one_hot(x, num_classes, **kw):
    if isinstance(num_classes, int) and num_classes <= 0:
        _fail("one_hot", f"num_classes must be positive, got "
                         f"{num_classes}")


def _r_masked(x, mask, *args, **kw):
    if _is_tensor(mask) and np.dtype(str(mask.dtype)) != np.bool_:
        _fail("masked_select", f"mask must be a bool tensor, got "
                               f"{mask.dtype}")


def _r_gather_nd(x, index, **kw):
    if _is_tensor(index):
        if np.dtype(str(index.dtype)).kind not in "iu":
            _fail("gather_nd", f"index must be integer, got {index.dtype}")
        if _shape(index) and _shape(index)[-1] > _rank(x):
            _fail("gather_nd", f"index depth {_shape(index)[-1]} exceeds "
                               f"input rank {_rank(x)}")


def _r_linspace(start, stop, num, *args, **kw):
    if isinstance(num, int) and num <= 0:
        _fail("linspace", f"num must be positive, got {num}")


def _r_diag(x, *args, **kw):
    if _rank(x) > 2:
        _fail("diag", f"input must be 1-D or 2-D, got {list(_shape(x))}")


_AXIS_OPS = """sum mean max min prod all any argmax argmin cumsum cumprod
logsumexp amax amin nansum nanmean squeeze softmax log_softmax argsort
sort flip cummax cummin median nanmedian unstack unbind mode
count_nonzero""".split()

# axis is the SECOND argument after x for these
_AXIS_POS1_OPS = "repeat_interleave quantile nanquantile".split()

_BROADCAST_OPS = """add subtract multiply divide floor_divide remainder
mod maximum minimum fmax fmin atan2 hypot copysign nextafter heaviside
logaddexp logaddexp2 lcm gcd equal not_equal less_than less_equal
greater_than greater_equal logical_and logical_or logical_xor bitwise_and
bitwise_or bitwise_xor""".split()

_SQUARE_OPS = """cholesky inverse matrix_power slogdet eig eigvals
cholesky_solve lu_unpack""".split()

_MIN2D_OPS = """tril triu qr lu svd matrix_rank pinv lstsq
eigh eigvalsh""".split()

_INT_INDEX_OPS = """gather index_select take_along_axis put_along_axis
index_sample scatter index_add index_put""".split()


def _build_rules():
    rules = {}
    for op in _AXIS_OPS:
        rules[op] = _axis_rule(op)
    for op in _AXIS_POS1_OPS:
        rules[op] = _axis_rule(op, axis_pos=1)
    rules["unsqueeze"] = _axis_rule("unsqueeze", extra=1)
    for op in _BROADCAST_OPS:
        rules[op] = _broadcast_rule(op)
    for op in _SQUARE_OPS:
        rules[op] = _square_rule(op)
    for op in _MIN2D_OPS:
        rules[op] = _min2d_rule(op)
    for op in _INT_INDEX_OPS:
        rules[op] = _int_index_rule(op)
    rules.update({
        "matmul": _r_matmul, "mm": _r_matmul, "bmm": _r_bmm,
        "dot": _r_dot, "where": _r_where, "topk": _r_topk,
        "kthvalue": _r_kthvalue, "split": _r_split, "chunk": _r_chunk,
        "stack": _r_stack, "expand": _r_expand,
        "broadcast_to": _r_expand, "transpose": _r_transpose,
        "solve": _r_solve, "triangular_solve": _r_solve, "pad": _r_pad,
        "clip": _r_clip, "cross": _r_cross, "one_hot": _r_one_hot,
        "masked_select": _r_masked, "masked_fill": _r_masked,
        "gather_nd": _r_gather_nd, "linspace": _r_linspace,
        "diag": _r_diag,
    })
    return rules


RULES = _build_rules()


def preflight_names():
    """Ops with a codegen-layer preflight (the inline enforce checks in
    ops/linalg.py, manipulation.py and nn/functional/common.py count —
    same mechanism, installed at authoring time)."""
    inline = ["reshape", "concat", "linear", "conv2d", "embedding",
              "cross_entropy"]
    return sorted(set(RULES) | set(inline))


def _wrap(name, fn):
    rule = RULES[name]

    @functools.wraps(fn)
    def guarded(*args, **kwargs):
        try:
            rule(*args, **kwargs)
        except InvalidArgumentError:
            raise
        except TypeError:
            pass  # signature drift: fail open, never block a valid call
        return fn(*args, **kwargs)

    guarded.__pd_infermeta__ = True
    return guarded


def install():
    """Wrap every registered op across the public namespaces + Tensor
    methods. Idempotent."""
    import types

    import paddle_tpu as paddle
    from ..core.tensor import Tensor
    from ..nn import functional as F
    from ..nn.functional import common as _F_common
    from ..nn.functional import extra as _F_extra
    from ..ops import (
        creation, generated_root, linalg, logic, manipulation, math,
        search,
    )
    spaces = [paddle, paddle.linalg, creation, generated_root, linalg,
              logic, manipulation, math, search, F, _F_common, _F_extra]
    for name in RULES:
        for ns in spaces:
            fn = getattr(ns, name, None)
            if isinstance(fn, types.FunctionType) and \
                    not getattr(fn, "__pd_infermeta__", False):
                setattr(ns, name, _wrap(name, fn))
        m = getattr(Tensor, name, None)
        if isinstance(m, types.FunctionType) and \
                not getattr(m, "__pd_infermeta__", False):
            setattr(Tensor, name, _wrap(name, m))
