"""paddle_tpu.Tensor — eager tensor over jax.Array with dygraph autograd semantics.

Reference equivalents: public ``paddle::Tensor`` (paddle/phi/api/include/tensor.h:82),
eager AutogradMeta/hooks (paddle/fluid/eager/autograd_meta.h), python method patches
(python/paddle/base/dygraph/tensor_patch_methods.py). The tensor transparently holds
either a concrete ``jax.Array`` or a JAX tracer, so the same eager code path can be
staged under ``jax.jit`` (this replaces dy2static/SOT for the compile story).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd
from . import dispatch
from .dtype import convert_dtype, get_default_dtype, is_floating

_tensor_counter = [0]
_ops_mod = None  # paddle_tpu.ops, resolved once by _binop (circular import)


class Tensor:
    __slots__ = ("_data", "_grad", "_grad_fn", "_output_index", "_grad_hooks",
                 "stop_gradient", "name", "persistable", "is_leaf_", "__weakref__",
                 "trainable", "_pp_meta")

    def __init__(self, data, dtype=None, stop_gradient: bool = True, name=None):
        dtype = convert_dtype(dtype)
        if isinstance(data, Tensor):
            data = data._data
        if isinstance(data, (jax.Array,)) or _is_tracer(data):
            self._data = data if dtype is None else data.astype(dtype)
        else:
            arr = np.asarray(data)
            if dtype is None:
                if arr.dtype == np.float64:
                    dtype = get_default_dtype()
                elif arr.dtype == np.int64 and arr.size and np.all(
                        np.abs(arr) < 2**31):
                    dtype = jnp.dtype("int64")  # keep paddle's int64 default
            self._data = jnp.asarray(arr, dtype=dtype)
        self._grad = None
        self._grad_fn = None
        self._output_index = 0
        self._grad_hooks = []
        self.stop_gradient = stop_gradient
        if name is None:
            _tensor_counter[0] += 1
            name = f"generated_tensor_{_tensor_counter[0]}"
        self.name = name
        self.persistable = False
        self.trainable = not stop_gradient

    # ---- metadata ----
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self):
        from .device import get_place
        return get_place()

    @property
    def is_leaf(self):
        return self._grad_fn is None

    def numel(self):
        return Tensor(jnp.asarray(self.size), stop_gradient=True)

    def element_size(self):
        return self._data.dtype.itemsize

    # ---- value access ----
    def numpy(self) -> np.ndarray:
        if dispatch._nan_pending:
            # a widened FLAGS_check_nan_inf_window defers the NaN flag
            # fetch; a host read is a sync point anyway, so surface the
            # pending error here instead of dropping it in forward-only
            # runs that never reach backward()
            dispatch.flush_nan_checks()
        return np.asarray(self._data)

    def item(self):
        if dispatch._nan_pending:
            dispatch.flush_nan_checks()
        return self._data.item()

    def tolist(self):
        return np.asarray(self._data).tolist()

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __float__(self):
        return float(self._data)

    def __int__(self):
        return int(self._data)

    def __bool__(self):
        return bool(self._data)

    def __index__(self):
        return int(self._data)

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype}"
                f"{grad_info},\n       {self._data})")

    def __hash__(self):
        return id(self)

    # ---- autograd ----
    @property
    def grad(self) -> Optional["Tensor"]:
        if self._grad is None:
            return None
        return Tensor(self._grad, stop_gradient=True)

    @grad.setter
    def grad(self, value):
        self._grad = None if value is None else (
            value._data if isinstance(value, Tensor) else jnp.asarray(value))

    def _accumulate_grad(self, g):
        self._grad = g if self._grad is None else self._grad + g

    def backward(self, grad_tensor: Optional["Tensor"] = None,
                 retain_graph: bool = False):
        """Reference: tensor_patch_methods.py:255 → eager/backward.cc:428."""
        autograd.backward([self], [grad_tensor] if grad_tensor is not None else None,
                          retain_graph=retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def register_hook(self, hook):
        """Gradient hook, fired during backward (reference: eager hooks)."""
        self._grad_hooks.append(hook)

        class _Handle:
            def remove(_self):
                if hook in self._grad_hooks:
                    self._grad_hooks.remove(hook)
        return _Handle()

    def detach(self) -> "Tensor":
        t = Tensor(self._data, stop_gradient=True)
        return t

    def detach_(self):
        self._grad_fn = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from .dispatch import apply
        return apply("clone", lambda x: x + 0, [self])

    # ---- dtype/shape sugar (full op surface is bound by ops.registry) ----
    def astype(self, dtype) -> "Tensor":
        from .dispatch import apply
        dt = convert_dtype(dtype)
        if is_floating(self.dtype) and is_floating(dt):
            return apply("cast", lambda x: x.astype(dt), [self])
        t = Tensor(self._data.astype(dt),
                   stop_gradient=True if not is_floating(dt) else self.stop_gradient)
        return t

    cast = astype

    def to(self, *args, **kwargs):
        # to(dtype) / to(device) / to(device, dtype)
        dtype = kwargs.get("dtype")
        device = kwargs.get("device")
        for a in args:
            if isinstance(a, str) and (a in ("cpu", "tpu", "gpu", "cuda",
                                             "xla") or ":" in a):
                device = a
            else:
                dtype = a
        out = self
        if device is not None:
            kind = device.split(":")[0]
            from .device import _platform_of
            if kind in ("tpu", "gpu", "cuda", "xla"):
                want = "tpu"  # accelerator strings route to the TPU backend
            elif kind == "cpu":
                want = "cpu"
            else:
                raise ValueError(
                    f"Tensor.to({device!r}): unknown device kind {kind!r} "
                    "(supported: tpu/gpu/cuda/xla → TPU, cpu)")
            targets = [d for d in jax.devices() if _platform_of(d) == want]
            if not targets and want == "cpu":
                try:
                    targets = jax.devices("cpu")
                except RuntimeError:
                    targets = []
            if not targets:
                raise RuntimeError(
                    f"Tensor.to({device!r}): no such device is attached "
                    f"(available: {[d.platform for d in jax.devices()]})")
            idx = int(device.split(":")[1]) if ":" in device else 0
            if idx >= len(targets):
                raise RuntimeError(
                    f"Tensor.to({device!r}): device index {idx} out of "
                    f"range — only {len(targets)} {want} device(s) attached")
            out = Tensor(jax.device_put(out._data, targets[idx]),
                         stop_gradient=out.stop_gradient)
        return out if dtype is None else out.astype(dtype)

    def cpu(self):
        return Tensor(jax.device_get(self._data), stop_gradient=self.stop_gradient)

    def tpu(self):
        return self

    cuda = tpu

    def pin_memory(self):
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    # ---- in-place value ops (tape-aware adopt pattern) ----
    def _snapshot(self) -> "Tensor":
        """Detached-identity copy carrying this tensor's current grad history.
        Used as the tape input of in-place ops so adopting the result doesn't
        sever the chain (the producing node's output slot is re-pointed here)."""
        import weakref
        t = Tensor(self._data, stop_gradient=self.stop_gradient)
        t._grad_fn = self._grad_fn
        t._output_index = self._output_index
        if t._grad_fn is not None:
            t._grad_fn.outputs[t._output_index] = weakref.ref(t)
        return t

    def _inplace(self, fn, *args, **kwargs):
        """Run fn on a snapshot of self and adopt the result (tape-aware)."""
        from . import autograd as _ag
        if (_ag.is_grad_enabled() and self._grad_fn is None
                and not self.stop_gradient):
            # matches the reference's eager engine: in-place on a leaf that
            # requires grad would silently divert gradient accumulation
            raise RuntimeError(
                "a leaf Tensor that requires grad is being used in an "
                "in-place operation; wrap the update in paddle.no_grad()")
        return self._adopt(fn(self._snapshot(), *args, **kwargs))

    def _adopt(self, new_tensor: "Tensor"):
        """In-place semantics: this tensor takes over new value + grad history."""
        import weakref
        self._data = new_tensor._data
        self._grad_fn = new_tensor._grad_fn
        self._output_index = new_tensor._output_index
        if self._grad_fn is not None:
            # re-point the tape node's output slot at the surviving tensor
            self._grad_fn.outputs[self._output_index] = weakref.ref(self)
        if not new_tensor.stop_gradient:
            self.stop_gradient = False
        return self

    def set_value(self, value):
        value = value._data if isinstance(value, Tensor) else jnp.asarray(value)
        self._data = value.astype(self.dtype) if value.dtype != self.dtype else value
        return self

    def copy_(self, other, blocking=True):
        return self.set_value(other)

    def fill_(self, value):
        self._data = jnp.full_like(self._data, value)
        return self

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        return self

    # ---- indexing ----
    def __getitem__(self, idx):
        from .dispatch import apply
        idx = _unwrap_index(idx)
        return apply("getitem", lambda x: x[idx], [self])

    def __setitem__(self, idx, value):
        from . import autograd as _ag
        from .dispatch import apply
        if (_ag.is_grad_enabled() and self._grad_fn is None
                and not self.stop_gradient):
            raise RuntimeError(
                "a leaf Tensor that requires grad is being used in an "
                "in-place operation; wrap the update in paddle.no_grad()")
        idx = _unwrap_index(idx)
        snap = self._snapshot()
        if isinstance(value, Tensor):
            out = apply("setitem", lambda x, v: x.at[idx].set(
                v.astype(x.dtype) if v.dtype != x.dtype else v), [snap, value])
        else:
            out = apply("setitem", lambda x: x.at[idx].set(value), [snap])
        self._adopt(out)

    # ---- arithmetic operators (delegate to ops.math through the tape) ----
    def _binop(self, other, opname, reverse=False):
        # the ops module is resolved ONCE (a per-op `from .. import ops`
        # runs the import machinery on every arithmetic operator — the
        # dispatch fast path budget is O(10 µs), imports don't fit)
        global _ops_mod
        if _ops_mod is None:
            from .. import ops as _ops_mod_local
            _ops_mod = _ops_mod_local
        fn = getattr(_ops_mod, opname)
        return fn(other, self) if reverse else fn(self, other)

    def __add__(self, o):
        return self._binop(o, "add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "subtract")

    def __rsub__(self, o):
        return self._binop(o, "subtract", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "multiply")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "divide")

    def __rtruediv__(self, o):
        return self._binop(o, "divide", reverse=True)

    def __floordiv__(self, o):
        return self._binop(o, "floor_divide")

    def __mod__(self, o):
        return self._binop(o, "remainder")

    def __pow__(self, o):
        return self._binop(o, "pow")

    def __rpow__(self, o):
        return self._binop(o, "pow", reverse=True)

    def __matmul__(self, o):
        return self._binop(o, "matmul")

    def __neg__(self):
        from .. import ops
        return ops.neg(self)

    def __abs__(self):
        from .. import ops
        return ops.abs(self)

    def __eq__(self, o):  # noqa: A003 - paddle returns elementwise tensor
        return self._binop(o, "equal")

    def __ne__(self, o):
        return self._binop(o, "not_equal")

    def __lt__(self, o):
        return self._binop(o, "less_than")

    def __le__(self, o):
        return self._binop(o, "less_equal")

    def __gt__(self, o):
        return self._binop(o, "greater_than")

    def __ge__(self, o):
        return self._binop(o, "greater_equal")

    def __invert__(self):
        from .. import ops
        return ops.logical_not(self)

    def __and__(self, o):
        return self._binop(o, "logical_and" if self.dtype == jnp.dtype("bool")
                           else "bitwise_and")

    def __or__(self, o):
        return self._binop(o, "logical_or" if self.dtype == jnp.dtype("bool")
                           else "bitwise_or")

    @property
    def T(self):
        from .. import ops
        return ops.transpose(self, list(range(self.ndim))[::-1])

    # numpy protocol: let np.asarray(tensor) work
    def __array__(self, dtype=None):
        arr = np.asarray(self._data)
        return arr.astype(dtype) if dtype is not None else arr


class Parameter(Tensor):
    """Trainable tensor (reference: python/paddle/base/framework.py Parameter)."""

    __slots__ = ("optimize_attr", "regularizer", "need_clip", "is_distributed")

    def __init__(self, data, dtype=None, trainable=True, name=None):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _unwrap_index(idx):
    """Convert Tensor indices inside (possibly nested) index tuples to jax arrays."""
    if isinstance(idx, Tensor):
        return idx._data
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, list):
        return [_unwrap_index(i) for i in idx]
    return idx


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """paddle.to_tensor (reference: python/paddle/tensor/creation.py)."""
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)
