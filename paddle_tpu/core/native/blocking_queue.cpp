// Native bounded blocking queue for DataLoader prefetch.
//
// Reference capability: paddle/fluid/operators/reader/
// lod_tensor_blocking_queue.h (the C++ BlockingQueue under
// use_buffer_reader=True double buffering) and the reader thread of
// io/dataloader/dataloader_iter.py. TPU-native deployment keeps samples
// as host byte blobs (pickled numpy batches) handed across threads
// without the GIL; ctypes binds this C API (no pybind11).
//
// Build: g++ -O2 -shared -fPIC -o libpd_bqueue.so blocking_queue.cpp -lpthread
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>

namespace {

struct Blob {
  char* data;
  size_t len;
};

struct BlockingQueue {
  std::deque<Blob> items;
  size_t capacity;
  bool closed = false;
  std::mutex mu;
  std::condition_variable not_full;
  std::condition_variable not_empty;

  explicit BlockingQueue(size_t cap) : capacity(cap == 0 ? 1 : cap) {}
};

}  // namespace

extern "C" {

void* pd_bq_create(uint64_t capacity) {
  return new BlockingQueue(static_cast<size_t>(capacity));
}

void pd_bq_destroy(void* h) {
  auto* q = static_cast<BlockingQueue*>(h);
  {
    std::lock_guard<std::mutex> lk(q->mu);
    for (auto& b : q->items) delete[] b.data;
    q->items.clear();
  }
  delete q;
}

// 0 ok, -1 timeout, -2 closed
int pd_bq_push(void* h, const char* buf, uint64_t len, int64_t timeout_ms) {
  auto* q = static_cast<BlockingQueue*>(h);
  std::unique_lock<std::mutex> lk(q->mu);
  auto pred = [q] { return q->closed || q->items.size() < q->capacity; };
  if (timeout_ms < 0) {
    q->not_full.wait(lk, pred);
  } else if (!q->not_full.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                   pred)) {
    return -1;
  }
  if (q->closed) return -2;
  Blob b;
  b.len = static_cast<size_t>(len);
  b.data = new char[b.len];
  std::memcpy(b.data, buf, b.len);
  q->items.push_back(b);
  q->not_empty.notify_one();
  return 0;
}

// 0 ok (out blob owned by caller; free with pd_bq_free), -1 timeout,
// -2 closed-and-drained
int pd_bq_pop(void* h, char** out, uint64_t* out_len, int64_t timeout_ms) {
  auto* q = static_cast<BlockingQueue*>(h);
  std::unique_lock<std::mutex> lk(q->mu);
  auto pred = [q] { return q->closed || !q->items.empty(); };
  if (timeout_ms < 0) {
    q->not_empty.wait(lk, pred);
  } else if (!q->not_empty.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                    pred)) {
    return -1;
  }
  if (q->items.empty()) return -2;  // closed and drained
  Blob b = q->items.front();
  q->items.pop_front();
  *out = b.data;
  *out_len = b.len;
  q->not_full.notify_one();
  return 0;
}

void pd_bq_free(char* blob) { delete[] blob; }

void pd_bq_close(void* h) {
  auto* q = static_cast<BlockingQueue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  q->closed = true;
  q->not_empty.notify_all();
  q->not_full.notify_all();
}

uint64_t pd_bq_size(void* h) {
  auto* q = static_cast<BlockingQueue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->items.size();
}

uint64_t pd_bq_capacity(void* h) {
  return static_cast<BlockingQueue*>(h)->capacity;
}

}  // extern "C"
