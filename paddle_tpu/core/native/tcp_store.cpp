// TCPStore — native host-side bootstrap KV store.
//
// Reference: paddle/phi/core/distributed/store/tcp_store.h:121 (+
// tcp_utils.cc, socket.cpp): rank-0 hosts a TCP master; every rank can
// set/get/add/wait keys; barriers are add+wait. The reference uses it to
// bootstrap NCCL communicators; here it bootstraps multi-host jax jobs,
// backs elastic membership, and feeds the collective watchdog
// (comm_task_manager.cc analog below).
//
// Protocol (length-prefixed, all ints little-endian int64):
//   request : op(1 byte) keylen keybytes [vallen valbytes | delta | timeout]
//   response: status(1 byte) [vallen valbytes | value]
// Ops: 1=SET 2=GET(blocking, timeout ms) 3=ADD 4=CHECK 5=DELETE
//
// Built as a shared library; Python binds via ctypes
// (paddle_tpu/distributed/tcp_store.py).

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Server {
  int listen_fd = -1;
  std::thread accept_thread;
  std::atomic<bool> running{false};
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::vector<uint8_t>> kv;
  std::map<std::string, int64_t> counters;
  std::vector<std::thread> workers;
  std::mutex fds_mu;
  std::vector<int> client_fds;  // shut down on stop so recv() unblocks
};

bool read_full(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::recv(fd, p + got, n - got, 0);
    if (r <= 0) return false;
    got += static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  size_t sent = 0;
  while (sent < n) {
    ssize_t r = ::send(fd, p + sent, n - sent, MSG_NOSIGNAL);
    if (r <= 0) return false;
    sent += static_cast<size_t>(r);
  }
  return true;
}

bool read_i64(int fd, int64_t* v) { return read_full(fd, v, 8); }
bool write_i64(int fd, int64_t v) { return write_full(fd, &v, 8); }

void serve_client(Server* s, int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  {
    std::lock_guard<std::mutex> lk(s->fds_mu);
    s->client_fds.push_back(fd);
  }
  while (s->running.load()) {
    uint8_t op;
    if (!read_full(fd, &op, 1)) break;
    int64_t keylen;
    if (!read_i64(fd, &keylen) || keylen < 0 || keylen > (1 << 20)) break;
    std::string key(static_cast<size_t>(keylen), '\0');
    if (!read_full(fd, key.data(), key.size())) break;

    if (op == 1) {  // SET
      int64_t vallen;
      if (!read_i64(fd, &vallen) || vallen < 0 || vallen > (64 << 20)) break;
      std::vector<uint8_t> val(static_cast<size_t>(vallen));
      if (!read_full(fd, val.data(), val.size())) break;
      {
        std::lock_guard<std::mutex> lk(s->mu);
        s->kv[key] = std::move(val);
      }
      s->cv.notify_all();
      uint8_t ok = 0;
      if (!write_full(fd, &ok, 1)) break;
    } else if (op == 2) {  // GET (blocking up to timeout ms)
      int64_t timeout_ms;
      if (!read_i64(fd, &timeout_ms)) break;
      std::vector<uint8_t> out;
      bool found = false;
      {
        std::unique_lock<std::mutex> lk(s->mu);
        auto pred = [&] { return s->kv.count(key) > 0 || !s->running; };
        if (timeout_ms < 0) {
          s->cv.wait(lk, pred);
        } else {
          s->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred);
        }
        auto it = s->kv.find(key);
        if (it != s->kv.end()) {
          out = it->second;
          found = true;
        }
      }
      uint8_t status = found ? 0 : 1;
      if (!write_full(fd, &status, 1)) break;
      if (found) {
        if (!write_i64(fd, static_cast<int64_t>(out.size()))) break;
        if (!write_full(fd, out.data(), out.size())) break;
      }
    } else if (op == 3) {  // ADD (atomic counter)
      int64_t delta;
      if (!read_i64(fd, &delta)) break;
      int64_t now;
      {
        std::lock_guard<std::mutex> lk(s->mu);
        now = (s->counters[key] += delta);
        // mirror the counter into kv so WAIT/GET can see it
        std::string as_str = std::to_string(now);
        s->kv[key].assign(as_str.begin(), as_str.end());
      }
      s->cv.notify_all();
      uint8_t ok = 0;
      if (!write_full(fd, &ok, 1)) break;
      if (!write_i64(fd, now)) break;
    } else if (op == 4) {  // CHECK (non-blocking existence)
      uint8_t status;
      {
        std::lock_guard<std::mutex> lk(s->mu);
        status = s->kv.count(key) ? 0 : 1;
      }
      if (!write_full(fd, &status, 1)) break;
    } else if (op == 5) {  // DELETE
      {
        std::lock_guard<std::mutex> lk(s->mu);
        s->kv.erase(key);
        s->counters.erase(key);
      }
      uint8_t ok = 0;
      if (!write_full(fd, &ok, 1)) break;
    } else {
      break;
    }
  }
  {
    // deregister before closing so server_stop never shuts down a reused fd
    std::lock_guard<std::mutex> lk(s->fds_mu);
    for (auto it = s->client_fds.begin(); it != s->client_fds.end(); ++it) {
      if (*it == fd) {
        s->client_fds.erase(it);
        break;
      }
    }
  }
  ::close(fd);
}

struct Client {
  int fd = -1;
  std::mutex mu;  // one request/response in flight at a time
};

}  // namespace

extern "C" {

// ---- server ----
void* pd_store_server_start(int port) {
  auto* s = new Server();
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  ::setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr),
             sizeof(addr)) != 0 ||
      ::listen(s->listen_fd, 128) != 0) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  s->running = true;
  s->accept_thread = std::thread([s] {
    while (s->running.load()) {
      int fd = ::accept(s->listen_fd, nullptr, nullptr);
      if (fd < 0) break;
      s->workers.emplace_back(serve_client, s, fd);
    }
  });
  return s;
}

void pd_store_server_stop(void* handle) {
  auto* s = static_cast<Server*>(handle);
  if (!s) return;
  s->running = false;
  s->cv.notify_all();
  {
    std::lock_guard<std::mutex> lk(s->fds_mu);
    for (int fd : s->client_fds) ::shutdown(fd, SHUT_RDWR);
  }
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  for (auto& t : s->workers)
    if (t.joinable()) t.join();
  delete s;
}

// ---- client ----
void* pd_store_client_connect(const char* host, int port, int timeout_ms) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    // not an IPv4 literal: resolve the hostname (reference tcp_utils.cc
    // resolves via getaddrinfo too)
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    if (::getaddrinfo(host, nullptr, &hints, &res) != 0 || res == nullptr) {
      ::close(fd);
      return nullptr;
    }
    addr.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
    ::freeaddrinfo(res);
  }
  // retry-connect loop (master may start slightly later — reference
  // tcp_utils.cc connect-with-retry behavior)
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
         0) {
    if (std::chrono::steady_clock::now() > deadline) {
      ::close(fd);
      return nullptr;
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
    ::close(fd);
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Client();
  c->fd = fd;
  return c;
}

void pd_store_client_close(void* handle) {
  auto* c = static_cast<Client*>(handle);
  if (!c) return;
  ::close(c->fd);
  delete c;
}

int pd_store_set(void* handle, const char* key, const uint8_t* val,
                 int64_t vallen) {
  auto* c = static_cast<Client*>(handle);
  std::lock_guard<std::mutex> lk(c->mu);
  uint8_t op = 1;
  int64_t keylen = static_cast<int64_t>(strlen(key));
  if (!write_full(c->fd, &op, 1) || !write_i64(c->fd, keylen) ||
      !write_full(c->fd, key, keylen) || !write_i64(c->fd, vallen) ||
      !write_full(c->fd, val, vallen))
    return -1;
  uint8_t status;
  return read_full(c->fd, &status, 1) ? status : -1;
}

// returns value length (>=0) into out (caller buffer of cap bytes);
// -1 timeout/missing, -2 io error, -3 buffer too small
int64_t pd_store_get(void* handle, const char* key, int64_t timeout_ms,
                     uint8_t* out, int64_t cap) {
  auto* c = static_cast<Client*>(handle);
  std::lock_guard<std::mutex> lk(c->mu);
  uint8_t op = 2;
  int64_t keylen = static_cast<int64_t>(strlen(key));
  if (!write_full(c->fd, &op, 1) || !write_i64(c->fd, keylen) ||
      !write_full(c->fd, key, keylen) || !write_i64(c->fd, timeout_ms))
    return -2;
  uint8_t status;
  if (!read_full(c->fd, &status, 1)) return -2;
  if (status != 0) return -1;
  int64_t vallen;
  if (!read_i64(c->fd, &vallen)) return -2;
  if (vallen > cap) {
    // drain to keep the stream consistent
    std::vector<uint8_t> tmp(static_cast<size_t>(vallen));
    read_full(c->fd, tmp.data(), tmp.size());
    return -3;
  }
  if (!read_full(c->fd, out, static_cast<size_t>(vallen))) return -2;
  return vallen;
}

int64_t pd_store_add(void* handle, const char* key, int64_t delta) {
  auto* c = static_cast<Client*>(handle);
  std::lock_guard<std::mutex> lk(c->mu);
  uint8_t op = 3;
  int64_t keylen = static_cast<int64_t>(strlen(key));
  if (!write_full(c->fd, &op, 1) || !write_i64(c->fd, keylen) ||
      !write_full(c->fd, key, keylen) || !write_i64(c->fd, delta))
    return INT64_MIN;
  uint8_t status;
  int64_t value;
  if (!read_full(c->fd, &status, 1) || !read_i64(c->fd, &value))
    return INT64_MIN;
  return value;
}

int pd_store_check(void* handle, const char* key) {
  auto* c = static_cast<Client*>(handle);
  std::lock_guard<std::mutex> lk(c->mu);
  uint8_t op = 4;
  int64_t keylen = static_cast<int64_t>(strlen(key));
  if (!write_full(c->fd, &op, 1) || !write_i64(c->fd, keylen) ||
      !write_full(c->fd, key, keylen))
    return -1;
  uint8_t status;
  return read_full(c->fd, &status, 1) ? (status == 0 ? 1 : 0) : -1;
}

int pd_store_delete(void* handle, const char* key) {
  auto* c = static_cast<Client*>(handle);
  std::lock_guard<std::mutex> lk(c->mu);
  uint8_t op = 5;
  int64_t keylen = static_cast<int64_t>(strlen(key));
  if (!write_full(c->fd, &op, 1) || !write_i64(c->fd, keylen) ||
      !write_full(c->fd, key, keylen))
    return -1;
  uint8_t status;
  return read_full(c->fd, &status, 1) ? status : -1;
}

// ---- collective watchdog (CommTaskManager analog) ----
// A heartbeat-armed timer: if pd_watchdog_beat is not called within
// timeout_ms, flag trips (reference: comm_task_manager.cc:153 timeout scan).
struct Watchdog {
  std::atomic<int64_t> last_beat_ms{0};
  std::atomic<bool> tripped{false};
  std::atomic<bool> running{true};
  bool abort_on_trip{false};
  int64_t timeout_ms;
  std::thread th;
};

static int64_t now_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// abort_on_trip: a collective hung past the timeout cannot be unwound from
// Python (the controller thread is blocked inside the runtime), so the
// watchdog thread kills the process — the launcher's restart loop plus
// checkpoint-resume is the recovery path (reference: comm_task_manager.cc
// aborts comms and tears down, nccl_comm_task.cc:233).
void* pd_watchdog_start2(int64_t timeout_ms, int abort_on_trip) {
  auto* w = new Watchdog();
  w->timeout_ms = timeout_ms;
  w->abort_on_trip = abort_on_trip != 0;
  w->last_beat_ms = now_ms();
  w->th = std::thread([w] {
    while (w->running.load()) {
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
      if (now_ms() - w->last_beat_ms.load() > w->timeout_ms) {
        w->tripped = true;
        if (w->abort_on_trip) {
          fprintf(stderr,
                  "[pd_watchdog] no heartbeat within %lld ms - collective "
                  "presumed hung, aborting process\n",
                  (long long)w->timeout_ms);
          fflush(stderr);
          _exit(17);
        }
      }
    }
  });
  return w;
}

void* pd_watchdog_start(int64_t timeout_ms) {
  return pd_watchdog_start2(timeout_ms, 0);
}

void pd_watchdog_beat(void* handle) {
  auto* w = static_cast<Watchdog*>(handle);
  w->last_beat_ms = now_ms();
  w->tripped = false;
}

int pd_watchdog_tripped(void* handle) {
  return static_cast<Watchdog*>(handle)->tripped.load() ? 1 : 0;
}

void pd_watchdog_stop(void* handle) {
  auto* w = static_cast<Watchdog*>(handle);
  w->running = false;
  if (w->th.joinable()) w->th.join();
  delete w;
}

}  // extern "C"
