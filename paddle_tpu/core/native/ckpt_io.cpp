// Native async checkpoint IO worker pool.
//
// Reference analog: the sharded-checkpoint save path of
// python/paddle/distributed/checkpoint/save_state_dict.py backed by the
// framework's C++ IO (fluid/framework data IO + the async save threads the
// reference uses for large PS tables). TPU-native role (SURVEY §7 step 5):
// training steps keep running while the previous snapshot's shards stream
// to disk — a fixed worker pool drains a job queue of (path, buffer) pairs,
// fsyncs, and atomically renames, so a crash never leaves a torn shard.
//
// C ABI (ctypes, no pybind in the image):
//   pd_ckpt_create(n_threads)            -> pool*
//   pd_ckpt_submit(pool, path, buf, n)   -> job id (buffer is COPIED; the
//                                           caller may free immediately)
//   pd_ckpt_pending(pool)                -> jobs not yet durable
//   pd_ckpt_wait(pool, timeout_ms)       -> 0 when drained, 1 on timeout
//   pd_ckpt_errors(pool, buf, cap)       -> newline-joined failed paths
//   pd_ckpt_destroy(pool)                   (drains first)
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

namespace {

struct Job {
  std::string path;
  std::vector<char> data;
};

struct Pool {
  std::deque<Job> jobs;
  std::mutex mu;
  std::condition_variable cv;       // signals workers: job available/stop
  std::condition_variable done_cv;  // signals waiters: pending changed
  std::vector<std::thread> workers;
  std::atomic<int64_t> submitted{0};
  int64_t completed = 0;  // guarded by mu
  std::string errors;     // guarded by mu
  bool stop = false;

  void worker() {
    for (;;) {
      Job job;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [&] { return stop || !jobs.empty(); });
        if (jobs.empty()) {
          if (stop) return;
          continue;
        }
        job = std::move(jobs.front());
        jobs.pop_front();
      }
      bool ok = write_one(job);
      {
        std::lock_guard<std::mutex> lk(mu);
        completed++;
        if (!ok) {
          errors += job.path;
          errors += "\n";
        }
      }
      done_cv.notify_all();
    }
  }

  // write to <path>.tmp<pid>, fsync, rename — atomic publication
  static bool write_one(const Job& job) {
    std::string tmp = job.path + ".tmp" + std::to_string(::getpid());
    int fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) return false;
    size_t off = 0;
    while (off < job.data.size()) {
      ssize_t n = ::write(fd, job.data.data() + off, job.data.size() - off);
      if (n < 0) {
        ::close(fd);
        ::unlink(tmp.c_str());
        return false;
      }
      off += static_cast<size_t>(n);
    }
    if (::fsync(fd) != 0) {
      ::close(fd);
      ::unlink(tmp.c_str());
      return false;
    }
    ::close(fd);
    if (::rename(tmp.c_str(), job.path.c_str()) != 0) {
      ::unlink(tmp.c_str());
      return false;
    }
    return true;
  }
};

}  // namespace

extern "C" {

void* pd_ckpt_create(uint64_t n_threads) {
  auto* p = new Pool();
  if (n_threads == 0) n_threads = 2;
  for (uint64_t i = 0; i < n_threads; i++) {
    p->workers.emplace_back([p] { p->worker(); });
  }
  return p;
}

int64_t pd_ckpt_submit(void* pool, const char* path, const char* buf,
                       uint64_t nbytes) {
  auto* p = static_cast<Pool*>(pool);
  Job job;
  job.path = path;
  job.data.assign(buf, buf + nbytes);
  int64_t id;
  {
    // submitted must advance under the SAME lock as the queue push, or a
    // concurrent wait() can observe submitted==completed with this job
    // already in a worker's hands and report "drained" early
    std::lock_guard<std::mutex> lk(p->mu);
    p->jobs.push_back(std::move(job));
    id = ++p->submitted;
  }
  p->cv.notify_one();
  return id;
}

int64_t pd_ckpt_pending(void* pool) {
  auto* p = static_cast<Pool*>(pool);
  std::lock_guard<std::mutex> lk(p->mu);
  return p->submitted.load() - p->completed;
}

int pd_ckpt_wait(void* pool, int64_t timeout_ms) {
  auto* p = static_cast<Pool*>(pool);
  std::unique_lock<std::mutex> lk(p->mu);
  auto drained = [&] { return p->submitted.load() == p->completed; };
  if (timeout_ms < 0) {
    p->done_cv.wait(lk, drained);
    return 0;
  }
  bool ok = p->done_cv.wait_for(
      lk, std::chrono::milliseconds(timeout_ms), drained);
  return ok ? 0 : 1;
}

uint64_t pd_ckpt_errors(void* pool, char* buf, uint64_t cap, int clear) {
  auto* p = static_cast<Pool*>(pool);
  std::lock_guard<std::mutex> lk(p->mu);
  uint64_t n = p->errors.size();
  if (buf != nullptr && cap > 0) {
    uint64_t c = n < cap - 1 ? n : cap - 1;
    std::memcpy(buf, p->errors.data(), c);
    buf[c] = '\0';
  }
  if (clear && buf != nullptr) p->errors.clear();  // read-and-clear
  return n;
}

void pd_ckpt_destroy(void* pool) {
  auto* p = static_cast<Pool*>(pool);
  {
    std::unique_lock<std::mutex> lk(p->mu);
    p->done_cv.wait(lk, [&] { return p->submitted.load() == p->completed; });
    p->stop = true;
  }
  p->cv.notify_all();
  for (auto& t : p->workers) t.join();
  delete p;
}

}  // extern "C"
