"""Multi-rank profile merger CLI (reference: tools/CrossStackProfiler —
merges per-node timelines into one chrome trace).

    python -m paddle_tpu.tools.merge_profiles rank0.json rank1.json \
        -o merged.json
"""
from __future__ import annotations

import argparse
import sys

__all__ = ["main"]


def main(argv=None):
    ap = argparse.ArgumentParser(prog="paddle_tpu.tools.merge_profiles")
    ap.add_argument("traces", nargs="+", help="per-rank chrome traces")
    ap.add_argument("-o", "--out", required=True)
    args = ap.parse_args(argv)
    from ..profiler import merge_profiler_results
    merged = merge_profiler_results(args.traces, out_path=args.out)
    print(f"merged {len(args.traces)} traces -> {args.out} "
          f"({len(merged['traceEvents'])} events)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
