"""Multi-rank / host+device profile merger CLI (reference:
tools/CrossStackProfiler — merges per-node timelines into one chrome
trace).

Inputs may be chrome-trace JSON files (a rank's ``Profiler.export`` or an
``observability.tracing`` host-span export) OR xplane log directories
(``jax.profiler`` trace dirs) — the latter are converted device-side via
``profiler.xplane.to_chrome_trace``, so one merged timeline shows host
spans (step/fwd/bwd/opt/collective) above the device execution lanes::

    python -m paddle_tpu.tools.merge_profiles trace.0.json /tmp/xplane_dir \
        -o merged.json
"""
from __future__ import annotations

import argparse
import os
import sys

__all__ = ["main", "load_input"]


def load_input(path):
    """-> (chrome-trace dict, lane label) for a JSON file or xplane dir."""
    if os.path.isdir(path):
        from ..profiler.xplane import to_chrome_trace
        base = os.path.basename(os.path.normpath(path))
        return (to_chrome_trace(path, label=f"device:{base}"),
                f"device:{base}")
    from ..profiler import load_profiler_result
    return load_profiler_result(path), os.path.basename(path)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="paddle_tpu.tools.merge_profiles")
    ap.add_argument("traces", nargs="+",
                    help="per-rank chrome traces (.json) and/or xplane "
                         "log directories")
    ap.add_argument("-o", "--out", required=True)
    ap.add_argument("--align", action="store_true",
                    help="shift xplane device lanes onto the host-span "
                         "wall clock when their clock domains disagree, "
                         "so merged Perfetto lanes line up")
    args = ap.parse_args(argv)
    from ..profiler import merge_profiler_results
    loaded = [load_input(p) for p in args.traces]
    merged = merge_profiler_results([d for d, _ in loaded],
                                    out_path=args.out,
                                    labels=[l for _, l in loaded],
                                    align=args.align)
    print(f"merged {len(args.traces)} traces -> {args.out} "
          f"({len(merged['traceEvents'])} events)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
