"""paddle_tpu.tools — CLI utilities.

Reference: tools/ — op-benchmark hooks (ci_op_benchmark.sh +
check_op_benchmark_result.py) and the CrossStackProfiler multi-node
timeline merger. Exposed as python -m entry points:

    python -m paddle_tpu.tools.op_benchmark --op matmul --shapes 256x256,256x256
    python -m paddle_tpu.tools.merge_profiles rank*.json -o merged.json
    python -m paddle_tpu.tools.slowest_tests /tmp/_t1.log --budget 870
    python -m paddle_tpu.tools.analyze            # tpu-lint static analysis

Nothing here may import jax at module level: the tpu-lint CLI boots this
package with paddle_tpu's framework init SKIPPED (see the boot guard in
paddle_tpu/__init__) so a full-tree scan stays parse-time only.
"""
from . import merge_profiles, op_benchmark  # noqa: F401
