"""paddle_tpu.tools — CLI utilities.

Reference: tools/ — op-benchmark hooks (ci_op_benchmark.sh +
check_op_benchmark_result.py) and the CrossStackProfiler multi-node
timeline merger. Exposed as python -m entry points:

    python -m paddle_tpu.tools.op_benchmark --op matmul --shapes 256x256,256x256
    python -m paddle_tpu.tools.merge_profiles rank*.json -o merged.json
"""
from . import merge_profiles, op_benchmark  # noqa: F401
