"""Pass 1 of the project analysis — per-file summaries + the summary DB.

A :class:`FileSummary` is everything the project-level (pass-2) rules need
from one file, extracted in a single walk over the shared node index and
fully JSON-serializable: defs and call edges (with the lock/branch context
of each call site), lock acquisitions with nesting context, store-key
string literals, ``jax.jit``/``pjit`` install sites, signal/atexit
handler registrations, identity-keyed cache sites, and the hot-path
marker.  Suppression tables are NOT summarized: scoped scans report
findings only for files that were parsed this run, so suppression
application always has a live :class:`~.engine.FileContext`.

The summary DB (:func:`load_db` / :func:`save_db`) caches summaries keyed
by (mtime, size) so ``--changed-only`` rebuilds only what the working tree
actually touched.  A corrupt or stale DB is silently discarded — the cache
is an accelerator, never a correctness input.
"""
from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field

from .astutil import (COLLECTIVES, P2P, STORE_OPS, STORE_WRITE_OPS,
                      branch_context, dotted, enclosing_class_name,
                      enclosing_function, is_store_chain,
                      joined_leading_text, parent, parents, terminal_name)

SUMMARY_VERSION = 3

# store-key roots the SK family knows (the families consolidated into
# distributed/keyspace.py); a literal starting "<root>/" is a store key
KEY_ROOTS = ("__wal", "__fence", "elastic", "serving", "pshare", "rpc",
             "dlinalg")

# the one module where raw key literals are legal
KEYSPACE_FILE = "distributed/keyspace.py"

# name fragments that mark a key expression as funneled through a
# builder/prefix/scope helper (SK003 exempts these)
_FUNNEL_FRAGMENTS = ("prefix", "scope", "key", "_k")

_JIT_WRAPPERS = {"jit", "pjit"}

_BLOCKING_TERMS = {"result"}  # future.result() while holding a lock


@dataclass
class FileSummary:
    relpath: str
    pkg_relpath: str
    mtime: float = 0.0
    size: int = 0
    hot_file: bool = False
    # qualname -> {"line": int, "class": str}
    defs: dict = field(default_factory=dict)
    # [{caller, callee, term, line, col, text, held, rank_gated}]
    calls: list = field(default_factory=list)
    # [{fn, lock, line, col, text, held}]
    locks: list = field(default_factory=list)
    # [{fn, kind, chain, line, col, text, held}] — lexical blocking ops
    blocking: list = field(default_factory=list)
    # [{fn, name, line}] — direct collective issue sites
    collectives: list = field(default_factory=list)
    # [{fn, root, text, line, col, write}]
    store_keys: list = field(default_factory=list)
    # [{fn, op, line, col, text, funneled, root}] — mutating store ops
    store_writes: list = field(default_factory=list)
    # [{fn, wrapper, line, col, text}]
    jit_sites: list = field(default_factory=list)
    # qualnames that call _note_program / on_compile
    notes_compile: list = field(default_factory=list)
    # [{kind: "signal"|"atexit", handler, line}]
    handlers: list = field(default_factory=list)
    # [{fn, line, col, text}] — id()-keyed cache key sites
    idkey_sites: list = field(default_factory=list)
    # builder name -> key root ("" outside the keyspace module)
    key_builders: dict = field(default_factory=dict)

    @property
    def subsystem(self) -> str:
        """Coarse ownership unit for SK002: the top-level package dir
        (outside the package: the file's immediate parent dir)."""
        if self.pkg_relpath:
            rel = self.pkg_relpath
            return rel.split("/", 1)[0] if "/" in rel else "<root>"
        head = os.path.dirname(self.relpath)
        return os.path.basename(head) or "<root>"

    def to_json(self) -> dict:
        return {
            "version": SUMMARY_VERSION,
            "relpath": self.relpath, "pkg_relpath": self.pkg_relpath,
            "mtime": self.mtime, "size": self.size,
            "hot_file": self.hot_file,
            "defs": self.defs, "calls": self.calls, "locks": self.locks,
            "blocking": self.blocking, "collectives": self.collectives,
            "store_keys": self.store_keys,
            "store_writes": self.store_writes,
            "jit_sites": self.jit_sites,
            "notes_compile": self.notes_compile,
            "handlers": self.handlers, "idkey_sites": self.idkey_sites,
            "key_builders": self.key_builders,
        }

    @classmethod
    def from_json(cls, data: dict):
        if data.get("version") != SUMMARY_VERSION:
            raise ValueError("summary version mismatch")
        kw = {k: v for k, v in data.items() if k != "version"}
        return cls(**kw)


# ---- extraction ------------------------------------------------------------


def _canonical_lock(ctx, node) -> str:
    """Stable project-wide id for a lock expression: ``self.X`` becomes
    ``Class.X`` (the same lock object on every instance path through the
    class); module-level names are file-scoped."""
    chain = dotted(node)
    if not chain:
        return ""
    cls = enclosing_class_name(node)
    if chain.startswith("self."):
        rest = chain[len("self."):]
        return f"{cls}.{rest}" if cls else rest
    if "." not in chain:
        return f"{ctx.pkg_relpath or ctx.relpath}::{chain}"
    return chain


def is_lock_name(name: str) -> bool:
    return "lock" in name.lower()


def lock_is_exempt(lock_id: str) -> bool:
    """Store-serialization locks exist precisely to bracket blocking store
    round-trips — LK002 exempts them (``_store_lock`` attrs and any lock
    owned by a ``*Store*`` class)."""
    return "store" in lock_id.lower()


def _held_locks(ctx, node):
    """Canonical ids of the lock ``with``-blocks lexically enclosing
    ``node`` (innermost last)."""
    held = []
    for p in parents(node):
        if isinstance(p, (ast.With, ast.AsyncWith)):
            for item in p.items:
                expr = item.context_expr
                # unwrap  with lock:   /   with lock.acquire_timeout(..):
                target = expr.func if isinstance(expr, ast.Call) else expr
                if isinstance(target, (ast.Name, ast.Attribute)) \
                        and is_lock_name(terminal_name(target)):
                    lid = _canonical_lock(ctx, target)
                    if lid:
                        held.append(lid)
    held.reverse()
    return held


def _fn_qualname(ctx, node) -> str:
    fn = enclosing_function(node)
    while fn is not None and isinstance(fn, ast.Lambda):
        fn = enclosing_function(fn)
    if fn is None:
        return "<module>"
    return ctx.qualnames.get(fn, "<module>")


def _store_write_funneled(key_arg) -> bool:
    """True when a mutating store op's key expression visibly routes
    through a builder/prefix/scope funnel (SK003's sanctioned shapes)."""
    if isinstance(key_arg, ast.Call):
        return True  # keyspace builder / self._k(...) funnel
    if isinstance(key_arg, (ast.Name, ast.Attribute)):
        return True  # a variable: built elsewhere, assumed funneled
    if isinstance(key_arg, ast.JoinedStr):
        for part in key_arg.values:
            if not isinstance(part, ast.FormattedValue):
                continue
            for sub in ast.walk(part.value):
                if isinstance(sub, ast.Call):
                    t = terminal_name(sub.func).lower()
                    if any(f in t for f in _FUNNEL_FRAGMENTS):
                        return True
                elif isinstance(sub, (ast.Name, ast.Attribute)):
                    t = terminal_name(sub).lower() \
                        if isinstance(sub, ast.Attribute) else sub.id.lower()
                    if any(f in t for f in _FUNNEL_FRAGMENTS):
                        return True
    return False


def _key_root(text: str) -> str:
    """The known key-root of a literal's leading text, or "".  Only the
    ``root/...`` spelling counts — a bare word ("elastic" as a mode
    name) or a path string ("serving/engine.py") is not a store key."""
    if text.endswith(".py"):
        return ""
    for root in KEY_ROOTS:
        if text.startswith(root + "/"):
            return root
    return ""


def _builder_roots(ctx):
    """For the keyspace module: builder/constant name -> key root, read
    off each def's returned (or assigned) leading string text."""
    out = {}
    for node in ctx.nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Return) and sub.value is not None:
                    root = _key_root(joined_leading_text(sub.value))
                    if root:
                        out[node.name] = root
                        break
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            root = _key_root(joined_leading_text(node.value))
            if root:
                out[node.targets[0].id] = root
    return out


def summarize(ctx) -> FileSummary:
    """Pass-1 extraction: one FileSummary from a parsed FileContext."""
    try:
        st = os.stat(ctx.path)
        mtime, size = st.st_mtime, st.st_size
    except OSError:
        mtime, size = 0.0, 0
    s = FileSummary(relpath=ctx.relpath, pkg_relpath=ctx.pkg_relpath,
                    mtime=mtime, size=size, hot_file=ctx.hot_file)
    for node, qual in ctx.qualnames.items():
        s.defs[qual] = {"line": node.lineno,
                        "class": enclosing_class_name(node)}
    if ctx.pkg_relpath == KEYSPACE_FILE:
        s.key_builders = _builder_roots(ctx)

    notes = set()
    # cheap pre-filter: rank-gating detection (branch_context walks every
    # ancestor) only matters in files that mention a rank spelling at all
    src_text = "\n".join(ctx.lines)
    has_rank = "rank" in src_text
    for node in ctx.nodes:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            # items of ONE multi-item `with a_lock, b_lock:` acquire in
            # listed order — earlier items are HELD for later ones (the
            # one-line ABBA spelling deadlocks exactly like the nested
            # one; _held_locks only sees enclosing Withs)
            outer = _held_locks(ctx, node)
            stmt_locks = []
            for item in node.items:
                expr = item.context_expr
                target = expr.func if isinstance(expr, ast.Call) else expr
                if isinstance(target, (ast.Name, ast.Attribute)) \
                        and is_lock_name(terminal_name(target)):
                    lid = _canonical_lock(ctx, target)
                    if lid:
                        s.locks.append({
                            "fn": _fn_qualname(ctx, node),
                            "lock": lid, "line": node.lineno,
                            "col": node.col_offset,
                            "text": ctx.src(node),
                            "held": outer + stmt_locks})
                        stmt_locks = stmt_locks + [lid]
            continue
        if not isinstance(node, ast.Call):
            continue
        term = terminal_name(node.func)
        chain = dotted(node.func)
        fn = _fn_qualname(ctx, node)
        held = _held_locks(ctx, node)   # shared by every record below
        rec_base = {"fn": fn, "line": node.lineno, "col": node.col_offset,
                    "text": ctx.src(node)}

        # ---- call edge (resolvable shapes only)
        if chain:
            if has_rank:
                rank_if, _data_if, _exc = branch_context(node)
            else:
                rank_if = None
            s.calls.append(dict(rec_base, caller=fn, callee=chain,
                                term=term, held=held,
                                rank_gated=rank_if is not None))

        # ---- direct collective issue site
        if term in COLLECTIVES or term in P2P:
            s.collectives.append({"fn": fn, "name": term,
                                  "line": node.lineno})

        # ---- lexical blocking ops (LK002 leaves)
        if term in COLLECTIVES or term in _BLOCKING_TERMS \
                or (term in STORE_OPS and is_store_chain(chain)):
            kind = "collective" if term in COLLECTIVES else (
                "store" if term in STORE_OPS and is_store_chain(chain)
                else "result")
            # add(k, 0) is the counter-read idiom — still a network
            # round-trip, still blocking: keep it
            s.blocking.append(dict(rec_base, kind=kind, chain=chain or term,
                                   held=held))

        # ---- mutating store ops (SK002/SK003)
        if term in STORE_WRITE_OPS and is_store_chain(chain) and node.args:
            key_arg = node.args[0]
            is_read = (term == "add" and len(node.args) > 1
                       and isinstance(node.args[1], ast.Constant)
                       and node.args[1].value == 0)
            if not is_read:
                s.store_writes.append(dict(
                    rec_base, op=term,
                    funneled=_store_write_funneled(key_arg),
                    root=_key_root(joined_leading_text(key_arg))))

        # ---- jit install sites (RC001)
        if term in _JIT_WRAPPERS and (node.args or node.keywords):
            s.jit_sites.append(dict(rec_base, wrapper=term))

        # ---- compile-accounting sites
        if term in ("_note_program", "on_compile"):
            notes.add(fn)

        # ---- handler registrations (LK003 roots)
        if chain == "signal.signal" and len(node.args) >= 2:
            h = dotted(node.args[1]) or terminal_name(node.args[1])
            if h:
                s.handlers.append({"kind": "signal", "handler": h,
                                   "line": node.lineno})
        elif chain == "atexit.register" and node.args:
            h = dotted(node.args[0]) or terminal_name(node.args[0])
            if h:
                s.handlers.append({"kind": "atexit", "handler": h,
                                   "line": node.lineno})

        # ---- identity-keyed cache sites (RC002): id() flowing into the
        # key of a cache-named container, or into a tuple built by a
        # *key* helper (dispatch.py's _fwd_key shape).  Plain id()-keyed
        # bookkeeping dicts (parameter maps etc.) hold their objects
        # alive by construction and are not flagged.
        if isinstance(node.func, ast.Name) and node.func.id == "id" \
                and len(node.args) == 1:
            hit = False
            prev = node
            for p in parents(node):
                if isinstance(p, ast.Subscript):
                    if prev is p.slice:  # came up through the index
                        container = terminal_name(p.value) \
                            if isinstance(p.value,
                                          (ast.Name, ast.Attribute)) else ""
                        hit = any(frag in container.lower() for frag in
                                  ("cache", "fns", "programs", "compiled",
                                   "memo", "seen", "blacklist"))
                    break
                if isinstance(p, ast.Tuple):
                    encl = enclosing_function(node)
                    name = getattr(encl, "name", "") or ""
                    if "key" in name.lower():
                        hit = True
                        break
                    prev = p
                    continue
                if isinstance(p, ast.stmt):
                    break
                prev = p
            if hit:
                s.idkey_sites.append(dict(rec_base))

    # ---- store-key literals (SK001), any expression position
    for node in ctx.nodes:
        if isinstance(node, (ast.Constant, ast.JoinedStr)):
            # only the outermost JoinedStr counts (its Constant parts are
            # also in the node index); a bare string STATEMENT (docstring
            # or comment-string) never reaches the wire — documenting the
            # key layout must not trip SK001
            if isinstance(parent(node), (ast.JoinedStr, ast.Expr)):
                continue
            text = joined_leading_text(node)
            root = _key_root(text)
            if root:
                s.store_keys.append({
                    "fn": _fn_qualname(ctx, node), "root": root,
                    "text": ctx.src(node), "line": node.lineno,
                    "col": node.col_offset})
    s.notes_compile = sorted(notes)
    return s


# ---- summary DB ------------------------------------------------------------

DB_VERSION = 2
_ENV_DB = "PADDLE_TPU_LINT_CACHE"


def default_db_path() -> str:
    env = os.environ.get(_ENV_DB)
    if env:
        return env
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".summary_db.json")


def load_db(path: str = None) -> dict:
    """-> {relpath: FileSummary}. Corrupt/stale/missing -> {} (silent
    full rebuild — the cache must never crash a scan)."""
    path = path or default_db_path()
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
        if data.get("version") != DB_VERSION:
            return {}
        out = {}
        for rel, entry in data.get("files", {}).items():
            out[rel] = FileSummary.from_json(entry)
        return out
    except Exception:
        return {}


def save_db(summaries: dict, path: str = None) -> None:
    """Best-effort persist (atomic replace); failure never fails a scan."""
    path = path or default_db_path()
    try:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"version": DB_VERSION,
                       "files": {rel: s.to_json()
                                 for rel, s in summaries.items()}}, fh)
        os.replace(tmp, path)
    except Exception:
        pass


def fresh(summary: FileSummary, path: str) -> bool:
    """mtime+size freshness check for one cached summary."""
    try:
        st = os.stat(path)
    except OSError:
        return False
    return st.st_mtime == summary.mtime and st.st_size == summary.size


def reset_cache_state() -> None:
    """Tests: drop any in-process memo (currently none — the DB is read
    fresh per scan, so there is nothing to clear).  Deliberately does
    NOT delete the file behind the env override: that may be an
    operator's warm cache outside the repo; un-setting the variable is
    what isolates tests (conftest does both)."""
