"""trace-purity (TP) — side effects inside traced/staged program bodies.

A ``@to_static`` body, a function handed to ``jax.jit``/``shard_map``, and a
dispatch-cacheable op forward (the ``fwd`` callable of ``core.dispatch.apply``)
all execute ONCE at trace time and then replay as a compiled program — the
exact hazard PR 7's persistent ``_jit_cache`` turns into silent stale-program
replays: a global mutated at trace time never mutates again, ``numpy.random``
draws become baked constants, wall-clock reads freeze, and a blocking fetch
either aborts the trace or constant-folds a device value.
"""
from __future__ import annotations

import ast

from .engine import Finding, dotted, parents, terminal_name

FAMILY = "trace-purity"

RULES = {
    "TP001": ("error", "global/nonlocal mutation inside a traced body"),
    "TP002": ("error", "numpy global RNG inside a traced body"),
    "TP003": ("warning", "wall-clock read inside a traced body"),
    "TP004": ("error", "blocking fetch inside a traced body"),
}

_TRACE_WRAPPERS = {"jit", "pjit", "shard_map", "to_static", "checkpoint",
                   "remat"}
_CLOCK_CHAINS = {
    "time.time", "time.perf_counter", "time.monotonic", "time.time_ns",
    "time.perf_counter_ns", "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
}
_FETCHES = {"numpy", "item", "block_until_ready", "device_get"}


def _is_to_static_decorator(dec) -> bool:
    t = terminal_name(dec.func) if isinstance(dec, ast.Call) else \
        terminal_name(dec)
    return t in ("to_static", "not_to_static") and t == "to_static"


def _enclosing_scope(node, tree):
    for p in parents(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return p
    return tree


def _traced_regions(ctx):
    """Yield (region node, how) for every statically-detectable traced body:

    * ``@to_static``-decorated defs;
    * local defs/lambdas passed (first arg) to jit/pjit/shard_map/remat;
    * lambdas/local defs passed as the ``fwd`` argument of ``apply(...)``.
    """
    # name -> def, per direct enclosing scope (one pass over the index)
    defs_by_scope = {}
    for node in ctx.nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scope = _enclosing_scope(node, ctx.tree)
            defs_by_scope.setdefault(scope, {}).setdefault(node.name, node)

    def resolve(name_node):
        scope = _enclosing_scope(name_node, ctx.tree)
        while True:
            d = defs_by_scope.get(scope, {})
            if name_node.id in d:
                return d[name_node.id]
            if scope is ctx.tree:
                return None
            scope = _enclosing_scope(scope, ctx.tree)

    seen = set()
    for node in ctx.nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_to_static_decorator(dec) and id(node) not in seen:
                    seen.add(id(node))
                    yield node, "@to_static"
        if not isinstance(node, ast.Call) or not node.args:
            continue
        t = terminal_name(node.func)
        first = node.args[0]
        if t in _TRACE_WRAPPERS and t != "to_static":
            target = None
            if isinstance(first, ast.Lambda):
                target = first
            elif isinstance(first, ast.Name):
                target = resolve(first)
            if target is not None and id(target) not in seen:
                seen.add(id(target))
                yield target, t
        elif t == "apply" and len(node.args) >= 2 \
                and isinstance(first, ast.Constant) \
                and isinstance(first.value, str):
            fwd = node.args[1]
            target = None
            if isinstance(fwd, ast.Lambda):
                target = fwd
            elif isinstance(fwd, ast.Name):
                target = resolve(fwd)
            if target is not None and id(target) not in seen:
                seen.add(id(target))
                yield target, "dispatch fwd"


def _region_body(region):
    if isinstance(region, ast.Lambda):
        return [region.body]
    return region.body


def run(ctx):
    findings = []
    for region, how in _traced_regions(ctx):
        label = region.name if hasattr(region, "name") else "<lambda>"
        for stmt in _region_body(region):
            for node in ast.walk(stmt):
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    findings.append(Finding(
                        file=ctx.relpath, line=node.lineno,
                        col=node.col_offset, rule="TP001", family=FAMILY,
                        severity="error",
                        message=f"{type(node).__name__.lower()} statement "
                                f"inside traced body '{label}' ({how}) — "
                                "the mutation runs once at trace time, then "
                                "the cached program replays without it",
                        hint="thread the value through inputs/outputs or "
                             "host callbacks; traced bodies must be pure",
                        source_line=ctx.src(node)))
                elif isinstance(node, ast.Call):
                    chain = dotted(node.func)
                    t = terminal_name(node.func)
                    if chain.startswith(("np.random.", "numpy.random.")):
                        findings.append(Finding(
                            file=ctx.relpath, line=node.lineno,
                            col=node.col_offset, rule="TP002", family=FAMILY,
                            severity="error",
                            message=f"numpy global RNG `{chain}` inside "
                                    f"traced body '{label}' ({how}) — the "
                                    "draw is baked at trace time and every "
                                    "replay reuses it",
                            hint="use the in-program RNG spec "
                                 "(core.random.derive_key) or pass keys in",
                            source_line=ctx.src(node)))
                    elif chain in _CLOCK_CHAINS:
                        findings.append(Finding(
                            file=ctx.relpath, line=node.lineno,
                            col=node.col_offset, rule="TP003", family=FAMILY,
                            severity="warning",
                            message=f"wall-clock read `{chain}` inside "
                                    f"traced body '{label}' ({how}) — "
                                    "freezes to the trace-time value",
                            hint="time outside the traced body",
                            source_line=ctx.src(node)))
                    elif t in _FETCHES and isinstance(node.func,
                                                      (ast.Attribute,
                                                       ast.Name)):
                        findings.append(Finding(
                            file=ctx.relpath, line=node.lineno,
                            col=node.col_offset, rule="TP004", family=FAMILY,
                            severity="error",
                            message=f"blocking fetch `.{t}()` inside traced "
                                    f"body '{label}' ({how}) — aborts the "
                                    "trace or constant-folds a device value",
                            hint="return the value from the traced body and "
                                 "fetch outside",
                            source_line=ctx.src(node)))
    return findings
