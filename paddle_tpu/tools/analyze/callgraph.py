"""Pass 2 — the project call graph over pass-1 summaries.

Resolution is deliberately *first-order*: a call site resolves to a
project def through its dotted source spelling only —

* ``self.foo(...)`` -> a def ``<CallerClass>.foo`` in the same file, else
  any unique ``*.foo`` in the same file;
* ``foo(...)`` -> a def named ``foo`` in the same file (module level or a
  unique nested one), else a unique project-wide ``foo``;
* ``a.b.foo(...)`` -> project defs whose qualname ends in ``.foo``, kept
  only when at most :data:`MAX_CANDIDATES` candidates exist (a bounded
  stand-in for dynamic dispatch: ``prefix_cache.lookup`` legitimately
  means either PrefixCache.lookup or SharedPrefixCache.lookup).

Anything else (callables held in variables, getattr dispatch, callbacks)
is *unresolved* — the README documents this boundary.  Reachability is
therefore an under-approximation: good for linting (no hallucinated
paths), never a proof of absence.

Nodes are ``(relpath, qualname)`` pairs.
"""
from __future__ import annotations

from collections import defaultdict

MAX_CANDIDATES = 3   # ambiguity bound for dotted-attribute resolution
MAX_DEPTH = 4        # closure depth bound (call edges, not lines)

_SKIP_TERMS = {
    # high-fan-in / stdlib-shadowing names that would connect everything
    # to everything: never resolve a bare/dotted call to these through
    # the suffix map
    "get", "set", "add", "check", "wait", "close", "run", "start", "stop",
    "append", "pop", "items", "keys", "values", "update", "join", "put",
    "flush", "write", "read", "send", "recv", "clear", "copy", "sort",
    "split", "strip", "format", "encode", "decode", "acquire", "release",
    "register", "record", "result", "to_dict", "from_dict", "reset",
    "__init__", "__call__",
}


class CallGraph:
    def __init__(self, summaries: dict):
        """``summaries``: {relpath: FileSummary}."""
        self.summaries = summaries
        # name -> [(relpath, qualname)] by final path component
        self._by_final = defaultdict(list)
        # (relpath, name) -> [qualname] within one file
        self._file_final = defaultdict(list)
        for rel, s in summaries.items():
            for qual in s.defs:
                final = qual.rsplit(".", 1)[-1]
                self._by_final[final].append((rel, qual))
                self._file_final[(rel, final)].append(qual)
        # STRICT adjacency (closures walk only these): a call contributes
        # an edge only when it resolves to exactly ONE project def — the
        # ambiguous (<= MAX_CANDIDATES) resolution is reserved for the
        # FIRST hop at a rule's own call site, where the rule reports the
        # candidate it matched.  Loose suffix matching transitively would
        # connect stdlib calls (``sys.stdout.flush``) to project defs and
        # drown the lock rules in phantom paths.
        self.edges = defaultdict(list)
        for rel, s in summaries.items():
            for call in s.calls:
                targets = self.resolve(rel, call)
                if len(targets) == 1:
                    self.edges[(rel, call["caller"])].append(
                        (targets[0], call))

    # ------------------------------------------------------- resolution
    def resolve(self, relpath: str, call: dict) -> list:
        """-> [(relpath, qualname)] candidate defs for one call record
        (empty when unresolved)."""
        callee, term = call["callee"], call["term"]
        if term in _SKIP_TERMS:
            return []
        s = self.summaries.get(relpath)
        caller_cls = ""
        if s is not None:
            info = s.defs.get(call["caller"])
            if info:
                caller_cls = info.get("class", "")
            elif "." in call["caller"]:
                caller_cls = call["caller"].split(".", 1)[0]
        if callee.startswith("self."):
            rest = callee[len("self."):]
            if "." in rest:   # self.obj.meth: fall through to dotted
                return self._dotted(term)
            if caller_cls:
                qual = f"{caller_cls}.{rest}"
                if s is not None and qual in s.defs:
                    return [(relpath, qual)]
            cands = self._file_final.get((relpath, rest), [])
            if len(cands) == 1:
                return [(relpath, cands[0])]
            return []
        if "." not in callee:
            cands = self._file_final.get((relpath, callee), [])
            # prefer module-level defs over same-named methods
            mod = [q for q in cands if "." not in q]
            if len(mod) == 1:
                return [(relpath, mod[0])]
            if len(cands) == 1:
                return [(relpath, cands[0])]
            globl = self._by_final.get(callee, [])
            if len(globl) == 1:
                return list(globl)
            return []
        return self._dotted(term)

    def _dotted(self, term: str) -> list:
        cands = self._by_final.get(term, [])
        if 0 < len(cands) <= MAX_CANDIDATES:
            return list(cands)
        return []

    # ----------------------------------------------------- reachability
    def reach(self, targets: dict, max_depth: int = MAX_DEPTH) -> dict:
        """Reverse-BFS from target nodes.

        ``targets``: {node: payload} — e.g. every function that lexically
        contains a blocking op, payload describing the op.  Returns
        {node: (payload, path)} for every node that can reach a target
        through resolved edges within ``max_depth``, where ``path`` is a
        witness chain ``[qualname, ..., target_qualname]``.  Target nodes
        themselves are included with a single-element path.
        """
        # build reverse adjacency once
        rev = defaultdict(list)
        for src, outs in self.edges.items():
            for (dst, _call) in outs:
                rev[dst].append(src)
        out = {n: (p, [n[1]]) for n, p in targets.items()}
        frontier = list(targets)
        for _ in range(max_depth):
            nxt = []
            for node in frontier:
                payload, path = out[node]
                for pred in rev.get(node, ()):
                    if pred in out:
                        continue
                    out[pred] = (payload, [pred[1]] + path)
                    nxt.append(pred)
            if not nxt:
                break
            frontier = nxt
        return out

    def callees(self, node, max_depth: int = MAX_DEPTH) -> set:
        """Forward closure: every node reachable FROM ``node``."""
        seen = {node}
        frontier = [node]
        for _ in range(max_depth):
            nxt = []
            for n in frontier:
                for (dst, _call) in self.edges.get(n, ()):
                    if dst not in seen:
                        seen.add(dst)
                        nxt.append(dst)
            if not nxt:
                break
            frontier = nxt
        return seen
