"""host-sync (HS) — blocking device→host fetches on designated hot paths.

The perf arc (PR 7-9) bought the hot paths their throughput precisely by
REMOVING host syncs: one dict lookup + one pjit call per taped op, bucketed
collectives awaited only at backward end, `loss_fetch_every`-amortized loss
fetches, host-side sampling batched per decode round.  A stray ``.numpy()``/
``.item()``/``block_until_ready``/``device_get`` on one of these paths
re-serializes host and device and silently costs ~1 ms per occurrence.

Designation: the built-in :data:`HOT_PATHS` table (paths relative to the
package root, optionally narrowed to function qualnames) plus an in-file
``# tpu-lint: hot-path`` marker for new hot files.  Deliberate syncs (the
amortized flush, the designed sampling fetch) carry suppressions with
reasons — that is the documentation of WHY the sync is allowed.
"""
from __future__ import annotations

import ast

from .engine import Finding, dotted, enclosing_function, terminal_name

FAMILY = "host-sync"

RULES = {
    "HS001": ("error", "blocking fetch on a designated hot path"),
    "HS002": ("warning", "potential host transfer on a designated hot path"),
}

# path (relative to the paddle_tpu package root) -> None for the whole file,
# or a set of function qualnames (the hot region within the file)
HOT_PATHS = {
    "core/dispatch.py": None,
    "serving/scheduler.py": {
        "ContinuousBatchingScheduler.schedule",
        "ContinuousBatchingScheduler.ensure_decode_capacity",
        "ContinuousBatchingScheduler.complete_step",
        # request-trace hook sites (ISSUE 20): stamped inside the
        # scheduling/finish path, so they must never block or transfer
        "ContinuousBatchingScheduler._trace_admit",
        "ContinuousBatchingScheduler._evict",
        "ContinuousBatchingScheduler.readmit",
        "GenerationRequest.finish",
        "GenerationRequest._trace_terminal",
    },
    "serving/engine.py": {
        "ServingEngine.step",
        "ServingEngine._step_ragged",
        "ServingEngine._step_bucketed",
        "ServingEngine._decode_once",
        "ServingEngine._run_chunk_batch",
        "ServingEngine._prefill_batch",
        "ServingEngine._prefill_admitted",
        "ServingEngine._serve_loop",
        "ServingEngine.snapshot_kv",
        "ServingEngine.adopt_request",
        "ServingEngine._finish_prompt",
    },
    # request-trace buffer feeds (ISSUE 20): called from the scheduler
    # round, the serve loop and the router dispatch path
    "observability/tracing.py": {
        "TraceBuffer.add",
        "TraceBuffer.req_add",
        "TraceBuffer.req_finish",
        "req_event",
        "finish_request",
        "mint_context",
    },
    # fleet migration path (router.py designates itself whole-file via
    # the in-file hot-path marker)
    "serving/fleet/disagg.py": {
        "migrate_request",
        "drain_active",
    },
    "distributed/overlap.py": {
        "BucketedGradSync.on_grad_ready",
        "BucketedGradSync.on_backward_begin",
        "BucketedGradSync.on_backward_end",
        "BucketedGradSync._fire",
    },
    # integrity guard per-step hooks (ISSUE 19): run inside the guarded
    # fit loop / backward walk, so any blocking fetch is step latency
    "distributed/integrity.py": {
        "TrainingGuard.observe_loss",
        "TrainingGuard.maybe_poison",
        "GradFingerprints.on_bucket",
        "GradFingerprints.verify",
    },
    "jit/api.py": {
        "StaticFunction.__call__",
        "StaticFunction._exec_whole_step",
    },
}

_BLOCKING = {"numpy", "item", "block_until_ready", "device_get"}
_TRANSFER_CHAINS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array"}


def _hot_qualnames(ctx):
    """None = file not designated; set() = whole file; else the qualnames."""
    spec = HOT_PATHS.get(ctx.pkg_relpath) if ctx.pkg_relpath else None
    if ctx.hot_file:
        return set()
    if ctx.pkg_relpath in HOT_PATHS:
        return set() if spec is None else set(spec)
    return None


def _in_hot_region(ctx, node, hot) -> str:
    """The hot qualname covering ``node``, or "" when outside."""
    fn = enclosing_function(node)
    if not hot:  # whole file designated
        while fn is not None and isinstance(fn, ast.Lambda):
            fn = enclosing_function(fn)
        return ctx.qualnames.get(fn, "<module>") if fn is not None \
            else "<module>"
    while fn is not None:
        q = ctx.qualnames.get(fn)
        if q is not None and q in hot:
            return q
        fn = enclosing_function(fn)
    return ""


def run(ctx):
    hot = _hot_qualnames(ctx)
    if hot is None:
        return []
    findings = []
    for node in ctx.nodes:
        if not isinstance(node, ast.Call):
            continue
        t = terminal_name(node.func)
        chain = dotted(node.func)
        rule = None
        if t in _BLOCKING:
            rule, sev = "HS001", "error"
            what = f"`{chain or t}()`" if chain else f"`.{t}()`"
            msg = (f"blocking fetch {what} on hot path '%s' — serializes "
                   "host and device on the per-step path")
            hint = ("amortize it (loss_fetch_every pattern), batch it per "
                    "round, or move it off the hot path; if this sync IS "
                    "the designed completion point, suppress with the "
                    "reason")
        elif chain in _TRANSFER_CHAINS:
            rule, sev = "HS002", "warning"
            msg = (f"`{chain}(...)` on hot path '%s' — a device operand "
                   "makes this a blocking device→host copy")
            hint = ("keep device values on device; if the operand is "
                    "host-only numpy, suppress with that reason")
        if rule is None:
            continue
        region = _in_hot_region(ctx, node, hot)
        if not region:
            continue
        findings.append(Finding(
            file=ctx.relpath, line=node.lineno, col=node.col_offset,
            rule=rule, family=FAMILY, severity=sev,
            message=msg % region, hint=hint, source_line=ctx.src(node)))
    return findings
