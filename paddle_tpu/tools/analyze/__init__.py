"""tpu-lint — framework-native static analysis for paddle_tpu (ISSUE 12,
project-wide two-pass analysis since ISSUE 15).

Eight pure-AST rule families catch, before a run, the bug classes the
runtime machinery diagnoses after one:

* ``collective-order`` (CO) — collectives under rank-/data-/exception-
  dependent control flow (the desync exit-21 class), including the
  interprocedural CO005 through the project call graph;
* ``trace-purity`` (TP) — side effects baked into traced/cached programs
  (the stale `_jit_cache` replay class);
* ``host-sync`` (HS) — blocking fetches on designated hot paths;
* ``jax-compat`` (JC) — jax surfaces that must route through
  ``core/jax_compat``;
* ``donation`` (DN) — reads of buffers already donated to a jitted call;
* ``locks`` (LK) — ABBA lock order, blocking calls under contended
  locks, signal/atexit-reachable acquisitions;
* ``store-keys`` (SK) — the distributed/keyspace.py key protocol;
* ``bounded-compile`` (RC) — the serving compile-count contract.

CLI::

    python -m paddle_tpu.tools.analyze                 # scan, gate on baseline
    python -m paddle_tpu.tools.analyze --changed-only  # pre-commit loop
    python -m paddle_tpu.tools.analyze --update-baseline
    python -m paddle_tpu.tools.analyze path/to/file.py --no-baseline

Exit codes: 0 clean vs baseline, 7 new findings, 2 usage error.  The CLI
never imports jax (``paddle_tpu/__init__`` skips framework init for this
boot shape), so a full-tree scan is parse-time only.

This package must stay importable with NOTHING but the stdlib — no jax, no
paddle_tpu framework modules.
"""
from .engine import (  # noqa: F401
    EXIT_NEW_FINDINGS, FAMILIES, Finding, all_rules, analyze_file,
    analyze_paths, diff_against_baseline, finding_key, fingerprint,
    format_finding, iter_py_files, load_baseline, package_root,
    save_baseline,
)

DEFAULT_BASELINE = __file__.rsplit("/", 1)[0] + "/baseline.json"
