"""donation (DN) — reads of a buffer after it was donated to a jitted call.

``jax.jit(..., donate_argnums=...)`` invalidates the argument buffer the
moment the call dispatches; a later read of the same binding either raises
a deleted-buffer error on device or silently reads garbage through an alias.
The fused train step and the serving decode step both donate their state
(params, opt state, KV pools) — these rules catch the lexical shape where a
donated binding is still read afterwards.

Scope is deliberately conservative (pure-AST, single function scope, simple
name bindings): a callable whose donated positions are knowable statically
(``f = jax.jit(g, donate_argnums=(1,))`` then ``f(a, b)``) is tracked; a
donation smuggled through returns/containers is not — the runtime error
still covers those.
"""
from __future__ import annotations

import ast

from .engine import Finding, dotted, parents, terminal_name

FAMILY = "donation"

RULES = {
    "DN001": ("error", "binding read after being donated to a jitted call"),
    "DN002": ("warning", "donated binding never rebound inside its loop"),
}


def _donate_positions(call) -> tuple:
    """Constant donate_argnums positions of a jax.jit(...) call, else ()."""
    if terminal_name(call.func) not in ("jit", "pjit"):
        return ()
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for elt in v.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value,
                                                                int):
                    out.append(elt.value)
                else:
                    return ()
            return tuple(out)
        return ()
    return ()


def _direct_walk(scope):
    """Walk a scope without descending into nested function scopes."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _enclosing_scope(node, tree):
    for p in parents(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return p
    return tree


def _name_events_after(scope, name, line):
    """(kind, node) events for ``name`` after ``line``, in lexical order."""
    events = []
    for node in _direct_walk(scope):
        if isinstance(node, ast.Name) and node.id == name \
                and node.lineno > line:
            kind = "store" if isinstance(node.ctx, (ast.Store, ast.Del)) \
                else "load"
            events.append((node.lineno, node.col_offset, kind, node))
    events.sort(key=lambda e: (e[0], e[1]))
    return events


def _call_stmt_targets(call) -> set:
    """Names the donated call's OWN statement rebinds (``x = step(x)``)."""
    stmt = None
    for p in parents(call):
        if isinstance(p, ast.stmt):
            stmt = p
            break
    out = set()
    if isinstance(stmt, ast.Assign):
        for tgt in stmt.targets:
            for sub in ast.walk(tgt):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)) \
            and isinstance(stmt.target, ast.Name):
        out.add(stmt.target.id)
    return out


def _exclusive_branches(call, ev) -> bool:
    """True when ``ev`` sits in the opposite branch of an If from ``call``
    — lexically after it, but on a path that can never execute once the
    donating dispatch has run."""
    child_of = {}
    node = call
    for p in parents(call):
        child_of[id(p)] = node
        node = p
    node = ev
    for p in parents(ev):
        if id(p) in child_of:
            if isinstance(p, ast.If):
                a, b = child_of[id(p)], node

                def branch(c, if_node=p):
                    if any(c is s for s in if_node.body):
                        return "body"
                    if any(c is s for s in if_node.orelse):
                        return "orelse"
                    return "test"

                ba, bb = branch(a), branch(b)
                return ba != bb and "test" not in (ba, bb)
            return False
        node = p
    return False


def _enclosing_loop(call, scope):
    """Innermost for/while between ``call`` and its enclosing scope."""
    for p in parents(call):
        if p is scope:
            return None
        if isinstance(p, (ast.For, ast.While)):
            return p
    return None


def _loop_stores(loop, name) -> bool:
    for node in ast.walk(loop):
        if isinstance(node, ast.Name) and node.id == name \
                and isinstance(node.ctx, ast.Store):
            return True
        if isinstance(node, ast.arg) and node.arg == name:
            return True
    return False


def run(ctx):
    # binding (name or dotted self.attr) -> donated positions, per scope
    callables_by_scope = {}
    for node in ctx.nodes:
        if not isinstance(node, ast.Assign) \
                or not isinstance(node.value, ast.Call):
            continue
        pos = _donate_positions(node.value)
        if not pos:
            continue
        scope = _enclosing_scope(node, ctx.tree)
        for tgt in node.targets:
            key = tgt.id if isinstance(tgt, ast.Name) else dotted(tgt)
            if key:
                callables_by_scope.setdefault(scope, {})[key] = pos

    findings = []
    for node in ctx.nodes:
        if not isinstance(node, ast.Call):
            continue
        scope = _enclosing_scope(node, ctx.tree)
        pos = ()
        if isinstance(node.func, ast.Call):
            # inline form: jax.jit(f, donate_argnums=...)(args)
            pos = _donate_positions(node.func)
        else:
            key = node.func.id if isinstance(node.func, ast.Name) \
                else dotted(node.func)
            pos = callables_by_scope.get(scope, {}).get(key, ())
        if pos:
            donated = [node.args[i] for i in pos if i < len(node.args)]
            donated_names = [a.id for a in donated
                             if isinstance(a, ast.Name)]
            loop = _enclosing_loop(node, scope)
            own = {id(sub) for sub in ast.walk(node)}  # the call's operands
            rebound = _call_stmt_targets(node)
            for name in donated_names:
                if name in rebound:
                    continue  # x = step(x): the result replaces the buffer
                events = _name_events_after(scope, name, node.lineno)
                for _ln, _col, kind, ev in events:
                    if id(ev) in own:
                        continue  # a multi-line call's own argument
                    if _exclusive_branches(node, ev):
                        continue  # sibling if/else branch: unreachable
                    if kind == "store":
                        break
                    findings.append(Finding(
                        file=ctx.relpath, line=ev.lineno, col=ev.col_offset,
                        rule="DN001", family=FAMILY, severity="error",
                        message=f"'{name}' is read after being donated to "
                                f"the jitted call at line {node.lineno} — "
                                "the buffer is invalidated at dispatch",
                        hint="rebind the call's result over the donated "
                             "name, or drop it from donate_argnums",
                        source_line=ctx.src(ev)))
                    break
                if loop is not None and not _loop_stores(loop, name):
                    findings.append(Finding(
                        file=ctx.relpath, line=node.lineno,
                        col=node.col_offset,
                        rule="DN002", family=FAMILY, severity="warning",
                        message=f"'{name}' is donated inside a loop but "
                                "never rebound in the loop body — the next "
                                "iteration passes an invalidated buffer",
                        hint="rebind the donated operand from the call "
                             "result each iteration",
                        source_line=ctx.src(node)))
    return findings
