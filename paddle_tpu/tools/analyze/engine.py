"""tpu-lint engine — pure-AST static analysis over the paddle_tpu tree.

The runtime correctness machinery (flight-recorder desync exit 21, watchdog
hang post-mortem exit 19, the A/B kernel gates, the serving compile
counters) diagnoses bug classes at run time; this engine catches the same
classes BEFORE a run, on every PR, from nothing but the source text: it
never imports jax (or paddle_tpu), so a full scan of the package costs
parse time only and fits inside the tier-1 budget.

Since ISSUE 15 the scan is a TWO-PASS project analysis, not a per-file
lexical one:

* **pass 1** parses each file once into a :class:`FileContext` shared by
  the per-file rule families, and extracts a JSON-serializable
  :class:`~.summary.FileSummary` (defs, call edges with lock/branch
  context, lock acquisitions, store-key literals, jit install sites);
* **pass 2** resolves a project call graph over the summaries
  (:class:`~.callgraph.CallGraph` — first-order dotted calls only) and
  runs the project-level rules: interprocedural collective-order (CO005),
  lock-order/deadlock (LK), store-key protocol (SK) and bounded-compile
  (RC) families.  Pass-2 rules consume summaries only, so a cached,
  unchanged file participates in the graph without being re-parsed —
  that is what makes ``--changed-only`` sub-2s.

Structure:

* every rule family is a module exposing ``FAMILY`` (slug), ``RULES``
  (id -> (severity, title)) and ``run(ctx) -> list[Finding]``; project
  families additionally expose ``run_project(project)``;
* suppressions are ``# tpu-lint: ok[RULE] reason`` comments on the finding
  line or the line above — RULE is a rule id or a family slug.  A
  suppression without a reason is itself a finding (SUP001) and a
  suppression matching nothing is flagged stale (SUP002), so the
  annotation layer ratchets with the code;
* the baseline (:func:`load_baseline` / :func:`diff_against_baseline`)
  fingerprints findings by (file, rule, normalized source line) so line
  drift never invalidates it, while any genuinely new finding does.
"""
from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import tokenize
from collections import Counter
from dataclasses import dataclass, field

from .astutil import (  # noqa: F401  (re-exported for the rule modules)
    COLLECTIVES, P2P, dotted, enclosing_function, index_tree, parent,
    parents, terminal_name,
)
from .callgraph import CallGraph
from .summary import fresh, load_db, save_db, summarize

__all__ = [
    "Finding", "FileContext", "ProjectContext", "analyze_paths",
    "analyze_file", "iter_py_files", "load_baseline", "save_baseline",
    "diff_against_baseline", "finding_key", "fingerprint", "format_finding",
    "FAMILIES", "all_rules", "EXIT_NEW_FINDINGS",
]

# distinct from the launcher's fault contract (17/19/21/43/64/75/76) and
# from slowest_tests' budget gate (3)
EXIT_NEW_FINDINGS = 7

_SUPPRESS_RE = re.compile(
    r"#\s*tpu-lint:\s*ok\[([A-Za-z0-9_,\s-]+)\]\s*(.*?)\s*$")
_HOT_MARK_RE = re.compile(r"#\s*tpu-lint:\s*hot-path\b")


@dataclass
class Finding:
    file: str          # path relative to the repo/package parent when possible
    line: int
    col: int
    rule: str          # e.g. "CO001"
    family: str        # e.g. "collective-order"
    severity: str      # "error" | "warning"
    message: str
    hint: str = ""
    source_line: str = ""
    qualname: str = ""              # enclosing function, when known
    callpath: list = field(default_factory=list)  # interprocedural witness


@dataclass
class Suppression:
    line: int
    rules: tuple
    reason: str


@dataclass
class FileContext:
    path: str
    relpath: str            # stable id used in findings + baseline keys
    pkg_relpath: str        # relative to the paddle_tpu package root, or ""
    tree: ast.AST
    lines: list
    suppressions: dict = field(default_factory=dict)  # line -> Suppression
    hot_file: bool = False
    # FunctionDef/AsyncFunctionDef node -> dotted qualname
    qualnames: dict = field(default_factory=dict)
    nodes: list = field(default_factory=list)  # every AST node, DFS order

    def src(self, node) -> str:
        """One-line source snippet for a node (its first line, stripped)."""
        try:
            return self.lines[node.lineno - 1].strip()
        except Exception:
            return ""

    def line_text(self, lineno: int) -> str:
        try:
            return self.lines[lineno - 1].strip()
        except Exception:
            return ""


@dataclass
class ProjectContext:
    """Everything pass-2 rules see: summaries + the resolved call graph."""
    summaries: dict              # relpath -> FileSummary
    graph: CallGraph


# ---- file parsing -----------------------------------------------------------

def _parse_suppressions(source: str):
    """Suppression table from REAL comment tokens only — a `# tpu-lint:`
    example inside a docstring or string literal never counts."""
    sup = {}
    hot = False
    if "tpu-lint" not in source:
        return sup, hot
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT or "tpu-lint" not in tok.string:
                continue
            i = tok.start[0]
            if _HOT_MARK_RE.search(tok.string):
                hot = True
            m = _SUPPRESS_RE.search(tok.string)
            if m:
                rules = tuple(r.strip() for r in m.group(1).split(",")
                              if r.strip())
                sup[i] = Suppression(line=i, rules=rules,
                                     reason=m.group(2).strip())
    except tokenize.TokenError:
        pass  # the ast parse already produced PARSE001 for real breakage
    return sup, hot


def build_context(path: str, relpath: str, pkg_relpath: str):
    """Parse one file into a FileContext, or (None, error_finding)."""
    try:
        with open(path, "rb") as f:
            source = f.read().decode("utf-8", errors="replace")
        tree = ast.parse(source, filename=path)
    except (SyntaxError, OSError) as e:
        lineno = getattr(e, "lineno", 1) or 1
        return None, Finding(
            file=relpath, line=lineno, col=0, rule="PARSE001",
            family="engine", severity="error",
            message=f"file does not parse: {e}",
            hint="tpu-lint needs parseable sources; fix the syntax error")
    lines = source.splitlines()
    nodes, qualnames = index_tree(tree)
    sup, hot = _parse_suppressions(source)
    ctx = FileContext(path=path, relpath=relpath, pkg_relpath=pkg_relpath,
                      tree=tree, lines=lines, suppressions=sup, hot_file=hot,
                      qualnames=qualnames, nodes=nodes)
    return ctx, None


# ---- rule registry ----------------------------------------------------------

def _families():
    from . import (rules_collective, rules_compile, rules_donation,
                   rules_hostsync, rules_jaxcompat, rules_locks,
                   rules_purity, rules_storekeys)
    return [rules_collective, rules_purity, rules_hostsync,
            rules_jaxcompat, rules_donation, rules_locks,
            rules_storekeys, rules_compile]


FAMILIES = ("collective-order", "trace-purity", "host-sync", "jax-compat",
            "donation", "locks", "store-keys", "bounded-compile")

_SUP_RULES = {
    "SUP001": ("error", "suppression without a reason"),
    "SUP002": ("warning", "stale suppression (matches no finding)"),
}


def all_rules() -> dict:
    """rule id -> (family, severity, title) for every registered rule."""
    out = {}
    for mod in _families():
        for rid, (sev, title) in mod.RULES.items():
            out[rid] = (mod.FAMILY, sev, title)
    for rid, (sev, title) in _SUP_RULES.items():
        out[rid] = ("suppression", sev, title)
    out["PARSE001"] = ("engine", "error", "unparseable file")
    return out


# ---- suppression application ------------------------------------------------

def _ran(ref: str, families) -> bool:
    """Did the rule/family a suppression references actually run?  With a
    family filter active, staleness is only judgeable for refs whose
    family ran — a host-sync suppression is not stale just because a
    collective-order-only scan produced no host-sync findings."""
    if families is None:
        return True
    if ref in families:
        return True
    info = all_rules().get(ref)
    return info is not None and info[0] in families


def _apply_suppressions(findings, table, line_text, relpath, emit_sup,
                        families=None):
    """Apply one file's suppression table to its findings.

    ``table``: {line: Suppression}; ``line_text``: lineno -> stripped
    source (for the SUP findings' own fingerprints); ``emit_sup``: only
    files whose per-file rules ran get SUP001/SUP002 findings (a cached
    file in a --changed-only scan is not judgeable).
    """
    kept = []
    used = set()
    for f in findings:
        suppressed = False
        for ln in (f.line, f.line - 1):
            s = table.get(ln)
            if s and (f.rule in s.rules or f.family in s.rules):
                used.add(ln)
                if s.reason:
                    suppressed = True
                # a reason-less suppression does NOT suppress: the finding
                # stays AND the bare annotation is flagged below
        if not suppressed:
            kept.append(f)
    if not emit_sup:
        return kept
    for ln, s in table.items():
        if not s.reason:
            kept.append(Finding(
                file=relpath, line=ln, col=0, rule="SUP001",
                family="suppression", severity="error",
                message=f"suppression ok[{','.join(s.rules)}] carries no "
                        "reason — bare allowlisting is not allowed",
                hint="append why the site is sanctioned: "
                     "# tpu-lint: ok[RULE] <reason>",
                source_line=line_text(ln)))
        elif ln not in used and all(_ran(r, families) for r in s.rules):
            kept.append(Finding(
                file=relpath, line=ln, col=0, rule="SUP002",
                family="suppression", severity="warning",
                message=f"suppression ok[{','.join(s.rules)}] matches no "
                        "finding on its line — stale, delete it",
                hint="the code it sanctioned changed; remove the comment",
                source_line=line_text(ln)))
    return kept


# ---- walking ----------------------------------------------------------------

_SKIP_DIRS = {"__pycache__", "native", ".git"}


def iter_py_files(root: str):
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
        for name in sorted(filenames):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def package_root() -> str:
    """The paddle_tpu package directory (…/paddle_tpu)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _rel_ids(path: str):
    """(relpath, pkg_relpath) — stable ids for findings + hot-path lookup."""
    ap = os.path.abspath(path)
    pkg = package_root()
    base = os.path.dirname(pkg)
    pkg_rel = ""
    if ap.startswith(pkg + os.sep):
        pkg_rel = os.path.relpath(ap, pkg).replace(os.sep, "/")
    if ap.startswith(base + os.sep):
        rel = os.path.relpath(ap, base).replace(os.sep, "/")
    else:
        rel = path.replace(os.sep, "/")
    return rel, pkg_rel


# ---- the two-pass scan ------------------------------------------------------

def analyze_paths(paths, families=None, changed=None, db_path=None,
                  persist_db=False):
    """Scan ``paths`` with both passes.

    ``changed``: None for a full scan; else a set of relpaths (repo-root
    relative) — only those files are parsed + rule-checked, every other
    file contributes its (cached, or silently re-built) pass-1 summary to
    the project graph.  ``persist_db`` refreshes the summary DB after the
    scan (the CLI does; library/test scans of scratch files do not).
    """
    files = []
    for root in paths:
        for path in iter_py_files(root):
            rel, pkg = _rel_ids(path)
            files.append((path, rel, pkg))
    # the DB is only a READ input for scoped scans; a full scan rebuilds
    # every summary anyway and would parse the multi-MB JSON for nothing
    cached = load_db(db_path) if changed is not None else {}

    contexts = {}     # relpath -> FileContext (files whose rules run)
    summaries = {}    # relpath -> FileSummary (every file)
    scanned = set()   # relpaths whose per-file rules ran
    parse_failed = set()
    findings = []
    for path, rel, pkg in files:
        is_scanned = changed is None or rel in changed or path in changed
        if not is_scanned:
            cs = cached.get(rel)
            if cs is not None and fresh(cs, path):
                summaries[rel] = cs
                continue
        ctx, err = build_context(path, rel, pkg)
        if err is not None:
            if is_scanned:
                findings.append(err)
                parse_failed.add(rel)
            continue
        summaries[rel] = summarize(ctx)
        if is_scanned:
            contexts[rel] = ctx
            scanned.add(rel)

    # pass-1 (per-file) rules
    for rel in scanned:
        ctx = contexts[rel]
        for mod in _families():
            if families and mod.FAMILY not in families:
                continue
            run = getattr(mod, "run", None)  # project-only families skip
            if run is not None:
                findings.extend(run(ctx))

    # pass-2 (project) rules over ALL summaries
    project = ProjectContext(summaries=summaries,
                             graph=CallGraph(summaries))
    for mod in _families():
        if families and mod.FAMILY not in families:
            continue
        runp = getattr(mod, "run_project", None)
        if runp is not None:
            findings.extend(runp(project))
    if changed is not None:
        # scoped scan: only findings landing in the changed files report
        # (PARSE001 files never reach `scanned` but ARE changed work) —
        # filtered BEFORE suppression application, so everything below
        # deals only in files whose suppression tables exist (scanned
        # files have a live ctx; parse-failed files can have none)
        findings = [f for f in findings
                    if f.file in scanned or f.file in parse_failed]

    # suppressions, per file.  Scanned files with ZERO findings still
    # need their SUP001/SUP002 checks, so iterate the union.
    by_file = {}
    for f in findings:
        by_file.setdefault(f.file, []).append(f)
    out = []
    for rel in set(by_file) | scanned:
        fs = by_file.get(rel, [])
        ctx = contexts.get(rel)
        if ctx is not None:
            table, line_text = ctx.suppressions, ctx.line_text
        else:   # parse-failed: nothing to suppress, nothing to judge
            table, line_text = {}, (lambda _ln: "")
        out.extend(_apply_suppressions(fs, table, line_text, rel,
                                       emit_sup=rel in scanned,
                                       families=families))

    if persist_db:
        save_db(summaries, db_path)
    out.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return out


def analyze_file(path: str, families=None):
    """Scan ONE file (a one-file project: the per-file families plus the
    project families over the single-file graph)."""
    return analyze_paths([path], families=families)


# ---- baseline ratchet -------------------------------------------------------

def finding_key(f: Finding):
    text = re.sub(r"\s+", " ", f.source_line).strip()
    return (f.file, f.rule, text)


def fingerprint(f: Finding) -> str:
    """Stable short hex id of a finding's baseline key (the --json
    schema's machine-readable handle)."""
    return hashlib.sha1("|".join(finding_key(f)).encode()).hexdigest()[:12]


def load_baseline(path: str) -> Counter:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    counts = Counter()
    for e in data.get("entries", []):
        counts[(e["file"], e["rule"], e["text"])] += int(e.get("count", 1))
    return counts


def save_baseline(path: str, findings) -> None:
    bare = [f for f in findings if f.rule == "SUP001"]
    if bare:
        raise ValueError(
            "refusing to baseline SUP001 (bare suppression) findings — "
            "suppressions must carry reasons: " +
            ", ".join(f"{f.file}:{f.line}" for f in bare[:5]))
    counts = Counter(finding_key(f) for f in findings)
    entries = [{"file": k[0], "rule": k[1], "text": k[2], "count": n}
               for k, n in sorted(counts.items())]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "entries": entries}, fh, indent=1,
                  sort_keys=True)
        fh.write("\n")


def diff_against_baseline(findings, baseline: Counter):
    """Partition findings into (new, preexisting) against the baseline.

    Per fingerprint key, up to the baselined count rides; any excess is new.
    """
    seen = Counter()
    new, old = [], []
    for f in findings:
        k = finding_key(f)
        seen[k] += 1
        (old if seen[k] <= baseline.get(k, 0) else new).append(f)
    return new, old


# ---- reporting --------------------------------------------------------------

def format_finding(f: Finding, new: bool = False) -> str:
    tag = " NEW" if new else ""
    hint = f"\n      hint: {f.hint}" if f.hint else ""
    path = ""
    if f.callpath:
        path = f"\n      via: {' -> '.join(f.callpath)}"
    return (f"{f.file}:{f.line}:{f.col}: {f.rule} [{f.severity}]{tag} "
            f"{f.message}{hint}{path}")
