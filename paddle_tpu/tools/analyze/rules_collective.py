"""collective-order (CO) — collectives under divergent control flow.

The flight recorder's desync detector (exit 21) catches a rank issuing a
different collective sequence at run time; these rules catch the shapes that
produce one statically: a collective issue site reached under rank-dependent,
data-dependent, or exception-dependent control flow.

Sanctioned shapes the rules know:

* ranked point-to-point (``send``/``recv``/``isend``/``irecv``) is EXPECTED
  to branch on rank — exempt from CO001/CO004;
* host-state guards that are identical across ranks by construction
  (``no_sync()`` accumulation flags, partial-bucket flush at backward end)
  contain no rank/data reference and are never flagged;
* genuinely rank-guarded sites that are safe for a documented reason carry
  ``# tpu-lint: ok[CO001] <reason>``.
"""
from __future__ import annotations

import ast

from .engine import Finding, parent, parents, terminal_name

FAMILY = "collective-order"

RULES = {
    "CO001": ("error", "collective under a rank-dependent branch"),
    "CO002": ("error", "collective issued inside an exception handler"),
    "CO003": ("error", "collective under a device-data-dependent branch"),
    "CO004": ("error", "collective after a rank-dependent early exit"),
}

COLLECTIVES = {
    "all_reduce", "all_gather", "all_gather_object", "reduce",
    "reduce_scatter", "broadcast", "broadcast_object_list", "scatter",
    "scatter_object_list", "all_to_all", "alltoall", "alltoall_single",
    "barrier", "gloo_barrier", "all_reduce_quantized",
}
P2P = {"send", "recv", "isend", "irecv"}

_RANK_NAMES = {
    "rank", "local_rank", "node_rank", "rank_id", "global_rank",
    "cur_rank", "src_rank", "dst_rank", "self_rank", "world_rank",
}
_RANK_CALLS = {"get_rank", "get_group_rank", "get_world_rank"}
_FETCH_CALLS = {"item", "numpy"}


def _test_flags(test) -> tuple:
    """(rank_dependent, data_dependent) for a branch test expression."""
    rank = data = False
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in _RANK_NAMES:
            rank = True
        elif isinstance(node, ast.Attribute) and node.attr in _RANK_NAMES:
            rank = True
        elif isinstance(node, ast.Call):
            t = terminal_name(node.func)
            if t in _RANK_CALLS:
                rank = True
            elif t in _FETCH_CALLS:
                data = True
    return rank, data


def _collective_calls(tree):
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            t = terminal_name(node.func)
            if t in COLLECTIVES or t in P2P:
                yield node, t


def _branch_context(call):
    """Walk outward from a call collecting the branches that condition it."""
    rank_if = data_if = except_handler = None
    node = call
    for p in parents(call):
        if isinstance(p, (ast.If, ast.While)):
            # the test itself is evaluated unconditionally; only the body
            # and orelse are conditioned on it
            if node is not p.test:
                rank, data = _test_flags(p.test)
                if rank and rank_if is None:
                    rank_if = p
                if data and data_if is None:
                    data_if = p
        elif isinstance(p, ast.IfExp):
            if node is not p.test:
                rank, data = _test_flags(p.test)
                if rank and rank_if is None:
                    rank_if = p
                if data and data_if is None:
                    data_if = p
        elif isinstance(p, ast.ExceptHandler):
            if except_handler is None:
                except_handler = p
        elif isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            break  # conditions outside the enclosing function don't count
        node = p
    return rank_if, data_if, except_handler


def _is_rank_early_exit(node) -> bool:
    """An If with a rank-dependent test whose body unconditionally leaves
    the function/loop (return/break/continue) — everything after it runs on
    a rank-dependent subset of ranks."""
    if not isinstance(node, ast.If) or not node.body or node.orelse:
        return False
    if not isinstance(node.body[-1], (ast.Return, ast.Break, ast.Continue)):
        return False
    rank, _ = _test_flags(node.test)
    return rank


def _statements_after(block_stmt):
    """Statements that execute after ``block_stmt`` in its enclosing body."""
    p = parent(block_stmt)
    if p is None:
        return []
    after = []
    for field in ("body", "orelse", "finalbody"):
        seq = getattr(p, field, None)
        if isinstance(seq, list) and block_stmt in seq:
            after = seq[seq.index(block_stmt) + 1:]
            break
    return after


def run(ctx):
    findings = []
    calls = [(n, terminal_name(n.func)) for n in ctx.nodes
             if isinstance(n, ast.Call)]
    calls = [(n, t) for n, t in calls if t in COLLECTIVES or t in P2P]
    for call, name in calls:
        p2p = name in P2P
        rank_if, data_if, except_handler = _branch_context(call)
        if rank_if is not None and not p2p:
            findings.append(Finding(
                file=ctx.relpath, line=call.lineno, col=call.col_offset,
                rule="CO001", family=FAMILY, severity="error",
                message=f"collective '{name}' issued under a rank-dependent "
                        f"branch (`{ctx.src(rank_if)}`) — ranks reaching "
                        "different branches issue different sequences "
                        "(desync exit-21 class)",
                hint="hoist the collective out of the branch, use ranked "
                     "p2p send/recv, or suppress with the reason all ranks "
                     "agree on the predicate",
                source_line=ctx.src(call)))
        if except_handler is not None:
            findings.append(Finding(
                file=ctx.relpath, line=call.lineno, col=call.col_offset,
                rule="CO002", family=FAMILY, severity="error",
                message=f"collective '{name}' issued inside an exception "
                        "handler — only ranks that raised reach it",
                hint="move the collective outside try/except, or suppress "
                     "with the reason the raise is rank-symmetric",
                source_line=ctx.src(call)))
        if data_if is not None:
            findings.append(Finding(
                file=ctx.relpath, line=call.lineno, col=call.col_offset,
                rule="CO003", family=FAMILY, severity="error",
                message=f"collective '{name}' issued under a branch that "
                        "fetches device data "
                        f"(`{ctx.src(data_if)}`) — per-rank values can "
                        "diverge and split the collective schedule",
                hint="decide on replicated host state, or all_reduce the "
                     "predicate first",
                source_line=ctx.src(call)))
    # CO004: collective lexically after a rank-gated early exit
    for exit_if in ctx.nodes:
        if _is_rank_early_exit(exit_if):
            after = _statements_after(exit_if)
            for stmt in after:
                for call, name in _collective_calls(stmt):
                    if name in P2P:
                        continue
                    findings.append(Finding(
                        file=ctx.relpath, line=call.lineno,
                        col=call.col_offset,
                        rule="CO004", family=FAMILY, severity="error",
                        message=f"collective '{name}' is unreachable for "
                                "ranks taking the early exit at line "
                                f"{exit_if.lineno} "
                                f"(`{ctx.src(exit_if)}`)",
                        hint="issue the collective before the rank gate, "
                             "or restructure so every rank reaches it",
                        source_line=ctx.src(call)))
    return findings
