"""collective-order (CO) — collectives under divergent control flow.

The flight recorder's desync detector (exit 21) catches a rank issuing a
different collective sequence at run time; these rules catch the shapes that
produce one statically: a collective issue site reached under rank-dependent,
data-dependent, or exception-dependent control flow.

CO001-004 are per-file (the issue site and the divergent branch are in the
same function).  CO005 is the project-level closure of the same hazard: a
helper that (transitively) issues a collective, CALLED under a
rank-dependent branch — possibly two files away — splits the schedule just
as surely, but no single-file scan can see it.  Resolution follows the
pass-2 call graph (first-order dotted calls only; see callgraph.py).

Sanctioned shapes the rules know:

* ranked point-to-point (``send``/``recv``/``isend``/``irecv``) is EXPECTED
  to branch on rank — exempt from CO001/CO004/CO005;
* host-state guards that are identical across ranks by construction
  (``no_sync()`` accumulation flags, partial-bucket flush at backward end)
  contain no rank/data reference and are never flagged;
* genuinely rank-guarded sites that are safe for a documented reason carry
  ``# tpu-lint: ok[CO001] <reason>`` (or ok[CO005] at a call site).
"""
from __future__ import annotations

import ast

from .astutil import (COLLECTIVES, P2P, branch_context, parent, parents,
                      terminal_name, test_flags)
from .engine import Finding

FAMILY = "collective-order"

RULES = {
    "CO001": ("error", "collective under a rank-dependent branch"),
    "CO002": ("error", "collective issued inside an exception handler"),
    "CO003": ("error", "collective under a device-data-dependent branch"),
    "CO004": ("error", "collective after a rank-dependent early exit"),
    "CO005": ("error",
              "collective-reaching helper called under a rank-dependent "
              "branch (interprocedural)"),
}


def _collective_calls(tree):
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            t = terminal_name(node.func)
            if t in COLLECTIVES or t in P2P:
                yield node, t


def _is_rank_early_exit(node) -> bool:
    """An If with a rank-dependent test whose body unconditionally leaves
    the function/loop (return/break/continue) — everything after it runs on
    a rank-dependent subset of ranks."""
    if not isinstance(node, ast.If) or not node.body or node.orelse:
        return False
    if not isinstance(node.body[-1], (ast.Return, ast.Break, ast.Continue)):
        return False
    rank, _ = test_flags(node.test)
    return rank


def _statements_after(block_stmt):
    """Statements that execute after ``block_stmt`` in its enclosing body."""
    p = parent(block_stmt)
    if p is None:
        return []
    after = []
    for field in ("body", "orelse", "finalbody"):
        seq = getattr(p, field, None)
        if isinstance(seq, list) and block_stmt in seq:
            after = seq[seq.index(block_stmt) + 1:]
            break
    return after


def run(ctx):
    findings = []
    calls = [(n, terminal_name(n.func)) for n in ctx.nodes
             if isinstance(n, ast.Call)]
    calls = [(n, t) for n, t in calls if t in COLLECTIVES or t in P2P]
    for call, name in calls:
        p2p = name in P2P
        rank_if, data_if, except_handler = branch_context(call)
        if rank_if is not None and not p2p:
            findings.append(Finding(
                file=ctx.relpath, line=call.lineno, col=call.col_offset,
                rule="CO001", family=FAMILY, severity="error",
                message=f"collective '{name}' issued under a rank-dependent "
                        f"branch (`{ctx.src(rank_if)}`) — ranks reaching "
                        "different branches issue different sequences "
                        "(desync exit-21 class)",
                hint="hoist the collective out of the branch, use ranked "
                     "p2p send/recv, or suppress with the reason all ranks "
                     "agree on the predicate",
                source_line=ctx.src(call)))
        if except_handler is not None:
            findings.append(Finding(
                file=ctx.relpath, line=call.lineno, col=call.col_offset,
                rule="CO002", family=FAMILY, severity="error",
                message=f"collective '{name}' issued inside an exception "
                        "handler — only ranks that raised reach it",
                hint="move the collective outside try/except, or suppress "
                     "with the reason the raise is rank-symmetric",
                source_line=ctx.src(call)))
        if data_if is not None:
            findings.append(Finding(
                file=ctx.relpath, line=call.lineno, col=call.col_offset,
                rule="CO003", family=FAMILY, severity="error",
                message=f"collective '{name}' issued under a branch that "
                        "fetches device data "
                        f"(`{ctx.src(data_if)}`) — per-rank values can "
                        "diverge and split the collective schedule",
                hint="decide on replicated host state, or all_reduce the "
                     "predicate first",
                source_line=ctx.src(call)))
    # CO004: collective lexically after a rank-gated early exit
    for exit_if in ctx.nodes:
        if _is_rank_early_exit(exit_if):
            after = _statements_after(exit_if)
            for stmt in after:
                for call, name in _collective_calls(stmt):
                    if name in P2P:
                        continue
                    findings.append(Finding(
                        file=ctx.relpath, line=call.lineno,
                        col=call.col_offset,
                        rule="CO004", family=FAMILY, severity="error",
                        message=f"collective '{name}' is unreachable for "
                                "ranks taking the early exit at line "
                                f"{exit_if.lineno} "
                                f"(`{ctx.src(exit_if)}`)",
                        hint="issue the collective before the rank gate, "
                             "or restructure so every rank reaches it",
                        source_line=ctx.src(call)))
    return findings


# ---- CO005: interprocedural ------------------------------------------------

def run_project(project):
    """A rank-gated call site whose (transitively resolved) callee issues
    a collective: the same desync class CO001 catches in one function,
    across the call graph."""
    graph = project.graph
    # every function that LEXICALLY issues a non-p2p collective
    targets = {}
    for rel, s in project.summaries.items():
        for c in s.collectives:
            if c["name"] in P2P:
                continue
            targets.setdefault((rel, c["fn"]),
                               {"name": c["name"], "line": c["line"]})
    if not targets:
        return []
    reach = graph.reach(targets)
    findings = []
    for rel, s in project.summaries.items():
        for call in s.calls:
            if not call.get("rank_gated"):
                continue
            term = call["term"]
            if term in COLLECTIVES or term in P2P:
                continue  # the direct site: CO001's jurisdiction
            for node in graph.resolve(rel, call):
                hit = reach.get(node)
                if hit is None:
                    continue
                payload, path = hit
                findings.append(Finding(
                    file=rel, line=call["line"], col=call["col"],
                    rule="CO005", family=FAMILY, severity="error",
                    message=f"'{call['callee']}' reaches collective "
                            f"'{payload['name']}' "
                            f"({path[-1]}, {node[0]}:{payload['line']}) "
                            "but is called under a rank-dependent branch "
                            "— ranks skipping the call skip the "
                            "collective (desync exit-21 class)",
                    hint="hoist the call out of the rank gate, or "
                         "suppress with the reason all ranks agree on "
                         "the predicate",
                    source_line=call["text"],
                    qualname=call["caller"],
                    callpath=[call["caller"]] + path))
                break  # one finding per call site, not per candidate
    return findings
