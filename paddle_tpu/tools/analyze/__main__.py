"""tpu-lint CLI — ``python -m paddle_tpu.tools.analyze``.

Scans the paddle_tpu tree (or explicit paths) with the eight rule families
and gates against the checked-in ratcheting baseline: pre-existing findings
ride, any NEW finding exits :data:`EXIT_NEW_FINDINGS` (7).  Designed to run
as the post-verify gate next to ``tools/slowest_tests.py``.

``--changed-only`` scopes the scan to the files git says differ from HEAD
(staged, unstaged and untracked), reusing the summary DB for everything
else — the pre-commit loop runs in well under 2 s while the project-level
rules still see the whole call graph.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

from . import DEFAULT_BASELINE
from .engine import (EXIT_NEW_FINDINGS, all_rules, analyze_paths,
                     diff_against_baseline, fingerprint, format_finding,
                     load_baseline, package_root, save_baseline)

JSON_SCHEMA = 2


def _list_rules() -> str:
    rows = [("rule", "family", "severity", "title"), ("-" * 6,) * 4]
    for rid, (family, sev, title) in sorted(all_rules().items()):
        rows.append((rid, family, sev, title))
    widths = [max(len(r[i]) for r in rows) for i in range(3)]
    return "\n".join(
        "  ".join(c.ljust(w) for c, w in zip(r[:3], widths)) + "  " + r[3]
        for r in rows)


def _git_changed(repo: str):
    """Repo-relative paths of files differing from HEAD (staged +
    unstaged + untracked) — or None when git is unusable (the caller
    falls back to a full scan; scoping is an accelerator, not a gate)."""
    try:
        # --relative makes diff output cwd-relative, matching BOTH
        # ls-files (always cwd-relative) and _rel_ids()'s package-parent
        # base — without it a checkout nested inside a larger git repo
        # emits toplevel-relative names that never match, and the scoped
        # gate passes vacuously
        diff = subprocess.run(
            ["git", "diff", "--name-only", "--relative", "HEAD"], cwd=repo,
            capture_output=True, text=True, timeout=10)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=repo, capture_output=True, text=True, timeout=10)
        if diff.returncode != 0:
            return None
        # splitlines, not split: a path with a space is one name
        names = set(diff.stdout.splitlines())
        if untracked.returncode == 0:
            names |= set(untracked.stdout.splitlines())
        return {n.strip() for n in names if n.strip().endswith(".py")}
    except Exception:
        return None


def _finding_json(f) -> dict:
    d = dict(vars(f))
    d["fingerprint"] = fingerprint(f)
    return d


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.tools.analyze",
        description="tpu-lint: pure-AST two-pass project analysis for "
                    "paddle_tpu (collective-order, trace-purity, host-sync, "
                    "jax-compat, donation, locks, store-keys, "
                    "bounded-compile) with a ratcheting baseline gate.")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: the paddle_tpu "
                         "package root)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON to ratchet against")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding; exit 7 when any exist")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from this scan's findings")
    ap.add_argument("--changed-only", action="store_true",
                    help="scan only files git reports changed vs HEAD; "
                         "unchanged files feed the call graph from the "
                         "summary DB (pre-commit loop, sub-2s)")
    ap.add_argument("--families", default=None,
                    help="comma-separated family slugs to run (default all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as one JSON object on stdout "
                         "(schema 2: rule, fingerprint, qualname, callpath)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    ap.add_argument("--assert-no-jax", action="store_true",
                    help="fail if jax was imported into this process "
                         "(CI guard for the parse-only contract)")
    args = ap.parse_args(argv)

    if args.list_rules:
        print(_list_rules())
        return 0

    paths = args.paths or [package_root()]
    families = None
    if args.families:
        families = {f.strip() for f in args.families.split(",") if f.strip()}
        known = {fam for fam, _sev, _t in all_rules().values()} \
            - {"suppression", "engine"}
        bad = families - known
        if bad:
            print(f"tpu-lint: unknown families {sorted(bad)} — known: "
                  f"{sorted(known)}", file=sys.stderr)
            return 2
        if args.update_baseline:
            print("tpu-lint: --update-baseline with --families would "
                  "rewrite the baseline from a PARTIAL scan, deleting "
                  "every other family's entries — run it unfiltered",
                  file=sys.stderr)
            return 2
    changed = None
    if args.changed_only:
        if args.update_baseline:
            print("tpu-lint: --update-baseline with --changed-only would "
                  "rewrite the baseline from a PARTIAL scan — run it "
                  "unfiltered", file=sys.stderr)
            return 2
        repo = os.path.dirname(package_root())
        changed = _git_changed(repo)
        # git unusable -> silent full scan (never crash the loop)
    # only default full-tree scans refresh the summary DB — a scan of an
    # explicit path subset (scoped or not) must not shrink the cache the
    # next --changed-only run depends on (save_db replaces the file map)
    persist = not args.paths
    t0 = time.perf_counter()
    findings = analyze_paths(paths, families=families, changed=changed,
                             persist_db=persist)
    elapsed = time.perf_counter() - t0

    if args.update_baseline:
        try:
            save_baseline(args.baseline, findings)
        except ValueError as e:
            print(f"tpu-lint: {e}", file=sys.stderr)
            return 2
        print(f"tpu-lint: baseline updated with {len(findings)} finding(s) "
              f"-> {args.baseline}")
        return 0

    if args.no_baseline or not os.path.exists(args.baseline):
        new, old = list(findings), []
        if not args.no_baseline:
            print(f"tpu-lint: baseline {args.baseline} missing — treating "
                  "every finding as new", file=sys.stderr)
    else:
        new, old = diff_against_baseline(findings, load_baseline(args.baseline))

    if args.as_json:
        out = {
            "schema": JSON_SCHEMA,
            "elapsed_s": round(elapsed, 3),
            "scanned": paths,
            "changed_only": bool(args.changed_only),
            "new": [_finding_json(f) for f in new],
            "preexisting": [_finding_json(f) for f in old],
        }
        print(json.dumps(out, indent=1, sort_keys=True))
    else:
        for f in old:
            print(format_finding(f))
        for f in new:
            print(format_finding(f, new=True))
        scope = " (changed-only)" if args.changed_only else ""
        print(f"tpu-lint: {len(findings)} finding(s), {len(new)} new vs "
              f"baseline, scanned in {elapsed:.2f}s{scope}")

    if args.assert_no_jax and "jax" in sys.modules:
        print("tpu-lint: jax was imported during the scan — the analyzer "
              "must stay parse-only. The jax-free boot is auto-detected "
              "via /proc/self/cmdline (Linux); on hosts without procfs "
              "run with PADDLE_TPU_LINT_BOOT=1", file=sys.stderr)
        return 2
    return EXIT_NEW_FINDINGS if new else 0


if __name__ == "__main__":
    sys.exit(main())
