"""jax-compat (JC) — jax surfaces that must route through core/jax_compat.

``core/jax_compat.py`` shims this image's jax 0.4.x: it publishes top-level
``jax.shard_map`` (adapting the ``check_vma`` kwarg to the old ``check_rep``
spelling), ``jax.lax.pcast``, and ``jax.enable_x64``.  Code that bypasses
the shim — importing ``jax.experimental.shard_map`` directly, or passing
``check_rep=`` straight through — works on exactly one runtime generation
and breaks on the other.  These rules enforce the ROADMAP standing note
mechanically: the shimmed spelling is the only one that works everywhere.
"""
from __future__ import annotations

import ast

from .engine import Finding, dotted, terminal_name

FAMILY = "jax-compat"

RULES = {
    "JC001": ("error", "direct jax.experimental.shard_map import"),
    "JC002": ("error", "check_rep= passed to shard_map (pre-shim kwarg)"),
    "JC003": ("error", "direct jax.experimental enable_x64 import"),
}

_SHIM_FILE = "core/jax_compat.py"  # the one place the raw surface is legal


def run(ctx):
    if ctx.pkg_relpath == _SHIM_FILE:
        return []
    findings = []
    for node in ctx.nodes:
        if isinstance(node, ast.ImportFrom) and node.module:
            if node.module.startswith("jax.experimental.shard_map"):
                findings.append(Finding(
                    file=ctx.relpath, line=node.lineno, col=node.col_offset,
                    rule="JC001", family=FAMILY, severity="error",
                    message="direct `jax.experimental.shard_map` import "
                            "bypasses core/jax_compat — only the shimmed "
                            "`from jax import shard_map` works on every "
                            "supported runtime",
                    hint="use `from jax import shard_map` (the shim "
                         "publishes the alias at package import)",
                    source_line=ctx.src(node)))
            elif node.module == "jax.experimental" and any(
                    a.name == "enable_x64" for a in node.names):
                findings.append(Finding(
                    file=ctx.relpath, line=node.lineno, col=node.col_offset,
                    rule="JC003", family=FAMILY, severity="error",
                    message="direct `jax.experimental.enable_x64` import "
                            "bypasses core/jax_compat — modern runtimes "
                            "promoted it to `jax.enable_x64`",
                    hint="use `jax.enable_x64` (the shim back-fills it on "
                         "0.4.x)",
                    source_line=ctx.src(node)))
        elif isinstance(node, ast.Attribute) \
                and node.attr in ("shard_map", "enable_x64"):
            # the terminal attr gates the (comparatively pricey) chain walk
            if dotted(node).startswith("jax.experimental.shard_map"):
                findings.append(Finding(
                    file=ctx.relpath, line=node.lineno, col=node.col_offset,
                    rule="JC001", family=FAMILY, severity="error",
                    message="attribute use of `jax.experimental.shard_map` "
                            "bypasses core/jax_compat",
                    hint="use `jax.shard_map` / `from jax import shard_map`",
                    source_line=ctx.src(node)))
            elif dotted(node) == "jax.experimental.enable_x64":
                findings.append(Finding(
                    file=ctx.relpath, line=node.lineno, col=node.col_offset,
                    rule="JC003", family=FAMILY, severity="error",
                    message="attribute use of `jax.experimental.enable_x64` "
                            "bypasses core/jax_compat",
                    hint="use `jax.enable_x64`",
                    source_line=ctx.src(node)))
        elif isinstance(node, ast.Call) \
                and terminal_name(node.func) == "shard_map":
            for kw in node.keywords:
                if kw.arg == "check_rep":
                    findings.append(Finding(
                        file=ctx.relpath, line=kw.value.lineno,
                        col=kw.value.col_offset,
                        rule="JC002", family=FAMILY, severity="error",
                        message="`check_rep=` is the pre-shim kwarg — on a "
                                "modern jax the native `jax.shard_map` "
                                "rejects it with a TypeError; the shim "
                                "adapts `check_vma=` to whichever runtime "
                                "is installed",
                        hint="pass `check_vma=` and let core/jax_compat "
                             "translate",
                        source_line=ctx.src(node)))
    return findings
