"""store-keys (SK) — the control-plane keyspace protocol, machine-checked.

The replicated control plane (PR 10) turned the TCPStore key namespace
into a PROTOCOL: ``__``-internal keys skip the WAL, registry-scope keys
ride it, counters are claim-bracketed, and failover rotates
incarnation-scoped keys.  That protocol used to live in ~48 raw string
literals across tcp_store.py, elastic.py and serving/fleet/ — one typo'd
prefix away from a silent replication gap.  ISSUE 15 consolidates the
literals into ``distributed/keyspace.py``; these rules keep them there:

* **SK001** — a key literal with a known root (``__wal/``, ``__fence/``,
  ``elastic/``, ``serving/``, ``pshare/``) anywhere OUTSIDE the keyspace
  module.  Keys must come from the shared builders, so every subsystem
  agrees on the wire spelling.
* **SK002** — two different subsystems (top-level package dirs) WRITING
  under the same key root: a collision class no single file can see
  (the WAL applies both writers' mutations to one namespace).
* **SK003** — a mutating store op whose key is an ad-hoc inline string
  that routes through NO funnel (no keyspace builder, no
  ``*prefix*``/``*scope*``/``_k`` helper): failover re-homing and
  incarnation rotation only cover keys built through the funnels.
"""
from __future__ import annotations

from .engine import Finding
from .summary import KEYSPACE_FILE

FAMILY = "store-keys"

RULES = {
    "SK001": ("error", "store-key literal outside distributed/keyspace.py"),
    "SK002": ("error", "same key root written from two subsystems"),
    "SK003": ("warning", "mutating store key built without a "
                         "builder/scope funnel"),
}


def _exempt(s) -> bool:
    """The keyspace module owns the literals; the analyzer/tooling tree
    (``tools/``) mentions key spellings as DATA (rule tables, docs),
    never as wire traffic."""
    return s.pkg_relpath == KEYSPACE_FILE \
        or (s.pkg_relpath or "").startswith("tools/")


def run_project(project):
    findings = []
    # builder name -> root, read off the keyspace module's own summary
    builder_roots = {}
    for s in project.summaries.values():
        if s.pkg_relpath == KEYSPACE_FILE:
            builder_roots = dict(s.key_builders)

    # ---- SK001: raw literals outside the keyspace module
    for rel, s in project.summaries.items():
        if _exempt(s):
            continue
        for rec in s.store_keys:
            findings.append(Finding(
                file=rel, line=rec["line"], col=rec["col"],
                rule="SK001", family=FAMILY, severity="error",
                message=f"raw store-key literal under root "
                        f"'{rec['root']}/' — the keyspace protocol lives "
                        "in distributed/keyspace.py; a drifted spelling "
                        "here silently splits the namespace",
                hint="import the matching keyspace builder/constant "
                     "(distributed.keyspace) instead of inlining the key",
                source_line=rec["text"], qualname=rec["fn"]))

    # ---- SK002: one root written from two subsystems
    # file-level: a file writes root R when it (a) performs mutating
    # store ops and (b) references R via a raw literal or a keyspace
    # builder call.  Builder references are found on the call edges.
    writers = {}   # root -> {subsystem: [site]}
    for rel, s in project.summaries.items():
        if _exempt(s):
            continue
        if not s.store_writes:
            continue
        roots = {}
        for rec in s.store_keys:
            roots.setdefault(rec["root"], rec)
        for call in s.calls:
            root = builder_roots.get(call["term"])
            if root:
                roots.setdefault(root, call)
        for root, rec in roots.items():
            writers.setdefault(root, {}).setdefault(
                s.subsystem, []).append((rel, rec))
    for root, by_sub in writers.items():
        if len(by_sub) < 2:
            continue
        subs = sorted(by_sub)
        for sub in subs:
            rel, rec = by_sub[sub][0]
            others = ", ".join(x for x in subs if x != sub)
            findings.append(Finding(
                file=rel, line=rec["line"], col=rec["col"],
                rule="SK002", family=FAMILY, severity="error",
                message=f"subsystem '{sub}' writes store keys under root "
                        f"'{root}/' which '{others}' also writes — "
                        "cross-subsystem writers collide in one replicated "
                        "namespace",
                hint="give each subsystem its own root (add a builder to "
                     "distributed/keyspace.py), or suppress with the "
                     "reason the shared namespace is the design",
                source_line=rec["text"], qualname=rec["fn"]))

    # ---- SK003: ad-hoc mutating keys
    for rel, s in project.summaries.items():
        if _exempt(s):
            continue
        for rec in s.store_writes:
            if rec["funneled"] or rec["root"]:
                # builder/variable/prefix funnels are fine; known-root
                # literals are SK001's jurisdiction (one finding, not two)
                continue
            findings.append(Finding(
                file=rel, line=rec["line"], col=rec["col"],
                rule="SK003", family=FAMILY, severity="warning",
                message=f"store `{rec['op']}` on an ad-hoc inline key — "
                        "no keyspace builder, prefix or scope helper in "
                        "sight: incarnation rotation and failover "
                        "re-homing only rotate funneled keys, so this one "
                        "survives into the next incarnation and collides",
                hint="build the key through distributed.keyspace or the "
                     "owning class's prefix/_k helper (or "
                     "flight_recorder.store_scope() for per-incarnation "
                     "state)",
                source_line=rec["text"], qualname=rec["fn"]))
    return findings
