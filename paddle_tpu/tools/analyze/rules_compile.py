"""bounded-compile (RC) — the serving compile contract, statically.

The ragged serving round (PR 13) collapsed the bucket matrix to <= 4
programs per mixed round and made the count OBSERVABLE:
``ServingMetrics.on_compile`` feeds ``serving_compiles_total`` /
``serving_distinct_programs`` from ``_note_program`` at every install
site.  A shape-specialized ``jax.jit`` added to a serving path without
that accounting re-opens the blowup invisibly — the counters stay flat
while XLA compiles behind the scheduler's back, and the bench's
``distinct <= 4`` gate reads a lie.  These rules keep every install site
on the books:

* **RC001** — a ``jax.jit``/``pjit`` install site in the serving
  subsystem (or a ``# tpu-lint: hot-path`` file) whose surrounding class
  (or module scope) never touches ``_note_program``/``on_compile``.
* **RC002** — a cache key built from ``id(obj)`` (or any
  identity-hashed object) without a visible keepalive: a freed object's
  id is recycled, and the NEW callable silently inherits the OLD entry's
  compiled program (the exact dispatch-cache hazard PR 7 hardened
  against — keyed objects must be pinned).
"""
from __future__ import annotations

from .engine import Finding

FAMILY = "bounded-compile"

RULES = {
    "RC001": ("error", "unaccounted jit install on a serving path"),
    "RC002": ("warning", "identity-keyed cache without a visible "
                         "keepalive"),
}


def _class_of(qualname: str) -> str:
    return qualname.split(".", 1)[0] if "." in qualname else ""


def run_project(project):
    findings = []
    for rel, s in project.summaries.items():
        serving = (s.pkg_relpath or "").startswith("serving/") or s.hot_file
        if serving:
            noted_classes = {_class_of(q) for q in s.notes_compile}
            noted_module = bool(s.notes_compile)
            for rec in s.jit_sites:
                cls = _class_of(rec["fn"])
                accounted = (cls in noted_classes) if cls \
                    else noted_module
                if accounted:
                    continue
                findings.append(Finding(
                    file=rel, line=rec["line"], col=rec["col"],
                    rule="RC001", family=FAMILY, severity="error",
                    message=f"`{rec['wrapper']}` install in "
                            f"'{rec['fn']}' with no _note_program/"
                            "on_compile anywhere in its "
                            f"{'class' if cls else 'module'} — a "
                            "shape-specialized program the "
                            "serving_compiles_total contract never "
                            "sees (bounded-compile gate reads a lie)",
                    hint="thread the install through "
                         "ServingEngine._note_program (or call "
                         "metrics.on_compile), or suppress with the "
                         "reason the program is compile-time-bounded "
                         "elsewhere",
                    source_line=rec["text"], qualname=rec["fn"]))
        # RC002 applies tree-wide: identity-keyed caches alias recycled
        # ids wherever they live
        for rec in s.idkey_sites:
            findings.append(Finding(
                file=rel, line=rec["line"], col=rec["col"],
                rule="RC002", family=FAMILY, severity="warning",
                message=f"cache key built from `id(...)` in "
                        f"'{rec['fn']}' — once the keyed object is "
                        "freed its id is recycled and a NEW callable "
                        "inherits the OLD entry's compiled program",
                hint="pin the keyed object in a keepalive map for the "
                     "entry's lifetime (dispatch.py's _jit_keepalive "
                     "shape), then suppress with that reason",
                source_line=rec["text"], qualname=rec["fn"]))
    return findings
