"""locks (LK) — cross-thread deadlock shapes, project-wide.

The serving/fleet tier is the first genuinely multi-threaded subsystem in
the tree (engine serve loop + SIGTERM drain watcher + router dispatch +
registry heartbeats + page-share daemons), and PRs 10/13/14 each burned a
review round on a lock bug the per-file lint could not see: the
SIGTERM-drain-vs-foreground-step race, the signal-frame self-deadlock, a
store round-trip under the scheduler lock.  These rules catch the three
static shapes behind those bugs, using the pass-2 summaries + call graph:

* **LK001** — two code paths acquire the same two locks in opposite
  nesting order (the classic ABBA deadlock).  Order pairs come from
  lexical nesting AND from calls made while a lock is held, resolved
  through the project call graph.
* **LK002** — a blocking call (TCPStore round-trip, collective,
  ``.result()``) made while holding a lock that other threads contend on.
  Locks whose NAME marks them as store-serialization locks
  (``*store*``) exist precisely to bracket store round-trips and are
  exempt.
* **LK003** — a lock acquisition reachable from a signal handler (error)
  or an atexit callback (warning).  A signal frame interrupts the very
  thread that may already hold the lock: acquiring it re-entrantly is a
  self-deadlock (the PR-10 fix moved the SIGTERM drain to a watcher
  thread for exactly this reason — this rule keeps it moved).

Identity: ``self._x`` canonicalizes to ``Class._x`` — the same lock
attribute on every instance path through the class.  Distinct instances
of one class sharing a canonical id can over-approximate (two routers'
private locks are not one lock); that costs a rare suppression, never a
missed deadlock.
"""
from __future__ import annotations

from .engine import Finding
from .summary import lock_is_exempt

FAMILY = "locks"

RULES = {
    "LK001": ("error", "inconsistent nested lock-acquisition order"),
    "LK002": ("error", "blocking call while holding a contended lock"),
    "LK003": ("error", "lock acquired in a signal/atexit-reachable "
                       "function"),
}


def _locks_by_fn(project):
    """(relpath, fn) -> [lock acquisition records]."""
    out = {}
    for rel, s in project.summaries.items():
        for rec in s.locks:
            out.setdefault((rel, rec["fn"]), []).append(rec)
    return out


def _blocking_targets(project):
    """(relpath, fn) -> first lexical blocking-op record.  A store op
    bracketed by its own exempt ``_store_lock`` is still a blocking op
    for a CALLER holding some other lock — the exemption only silences
    the direct (same-function) finding, never the reach target."""
    out = {}
    for rel, s in project.summaries.items():
        for rec in s.blocking:
            out.setdefault((rel, rec["fn"]), rec)
    return out


def _order_pairs(project, locks_by_fn):
    """{(outer, inner): [site]} — every observed nesting order, lexical
    and through calls made with a lock held."""
    pairs = {}

    def note(outer, inner, rel, rec, via=None):
        if outer == inner:
            return  # re-entrant same-lock: RLock territory, not ABBA
        site = {"rel": rel, "line": rec["line"], "col": rec["col"],
                "text": rec["text"], "fn": rec["fn"], "via": via or []}
        pairs.setdefault((outer, inner), []).append(site)

    for rel, s in project.summaries.items():
        for rec in s.locks:
            for outer in rec["held"]:
                note(outer, rec["lock"], rel, rec)
        for call in s.calls:
            if not call["held"]:
                continue
            for target in project.graph.resolve(rel, call):
                for node in project.graph.callees(target):
                    for lrec in locks_by_fn.get(node, ()):
                        for outer in call["held"]:
                            note(outer, lrec["lock"], rel, call,
                                 via=[call["caller"], node[1]])
    return pairs


def run_project(project):
    findings = []
    locks_by_fn = _locks_by_fn(project)

    # ---- LK001: conflicting orders
    pairs = _order_pairs(project, locks_by_fn)
    flagged = set()
    for (a, b), sites in pairs.items():
        if (b, a) not in pairs or (b, a) in flagged:
            continue
        flagged.add((a, b))
        other = pairs[(b, a)][0]
        for site in sites[:1] + pairs[(b, a)][:1]:
            o1, o2 = ((a, b) if site in sites else (b, a))
            peer = other if site in sites else sites[0]
            findings.append(Finding(
                file=site["rel"], line=site["line"], col=site["col"],
                rule="LK001", family=FAMILY, severity="error",
                message=f"lock order {o1} -> {o2} here, but "
                        f"{peer['rel']}:{peer['line']} ({peer['fn']}) "
                        f"takes {o2} -> {o1} — two threads on these "
                        "paths can deadlock (ABBA)",
                hint="pick one global order for the two locks and "
                     "restructure the minority path",
                source_line=site["text"], qualname=site["fn"],
                callpath=site["via"]))

    # ---- LK002: blocking under a contended lock
    btargets = _blocking_targets(project)
    breach = project.graph.reach(btargets)
    direct_flagged = set()   # (rel, fn) that got a DIRECT finding below
    for rel, s in project.summaries.items():
        # direct: the blocking op itself sits in a lock region
        for rec in s.blocking:
            held = [h for h in rec["held"] if not lock_is_exempt(h)]
            if not held:
                continue
            direct_flagged.add((rel, rec["fn"]))
            findings.append(Finding(
                file=rel, line=rec["line"], col=rec["col"],
                rule="LK002", family=FAMILY, severity="error",
                message=f"blocking {rec['kind']} call "
                        f"`{rec['chain']}` while holding {held[-1]} — "
                        "every thread contending on the lock stalls for "
                        "the full round-trip (and a store outage turns "
                        "the lock region into a deadlock)",
                hint="move the blocking call outside the lock region, "
                     "or suppress with the reason the round-trip is "
                     "bounded and the lock is not on a hot path",
                source_line=rec["text"], qualname=rec["fn"]))
        # interprocedural: a call made under the lock reaches one
        for call in s.calls:
            held = [h for h in call["held"] if not lock_is_exempt(h)]
            if not held:
                continue
            if (rel, call["caller"]) in direct_flagged:
                # the direct finding above already names this function's
                # hazard — mere btargets membership (an UNLOCKED lexical
                # blocking op elsewhere in the fn) must not skip it
                continue
            for target in project.graph.resolve(rel, call):
                hit = breach.get(target)
                if hit is None:
                    continue
                payload, path = hit
                findings.append(Finding(
                    file=rel, line=call["line"], col=call["col"],
                    rule="LK002", family=FAMILY, severity="error",
                    message=f"'{call['callee']}' reaches blocking "
                            f"{payload['kind']} call "
                            f"`{payload['chain']}` but is called while "
                            f"holding {held[-1]} — the lock is held "
                            "across a network round-trip",
                    hint="move the call outside the lock region, or "
                         "suppress with the reason the round-trip is "
                         "bounded and acceptable under this lock",
                    source_line=call["text"], qualname=call["caller"],
                    callpath=[call["caller"]] + path))
                break
    # ---- LK003: locks reachable from signal/atexit frames
    for rel, s in project.summaries.items():
        for reg in s.handlers:
            h = reg["handler"]
            node_list = project.graph.resolve(
                rel, {"callee": h, "term": h.rsplit(".", 1)[-1],
                      "caller": "<module>"})
            sev = "error" if reg["kind"] == "signal" else "warning"
            for handler_node in node_list:
                for node in project.graph.callees(handler_node):
                    for lrec in locks_by_fn.get(node, ()):
                        findings.append(Finding(
                            file=node[0], line=lrec["line"],
                            col=lrec["col"],
                            rule="LK003", family=FAMILY, severity=sev,
                            message=f"lock {lrec['lock']} acquired in "
                                    f"'{node[1]}', reachable from the "
                                    f"{reg['kind']} handler '{h}' "
                                    f"({rel}:{reg['line']}) — a signal "
                                    "frame interrupting the holder "
                                    "self-deadlocks"
                                    if reg["kind"] == "signal" else
                                    f"lock {lrec['lock']} acquired in "
                                    f"'{node[1]}', reachable from the "
                                    f"atexit callback '{h}' "
                                    f"({rel}:{reg['line']}) — exit-time "
                                    "teardown can wedge behind a thread "
                                    "that died holding it",
                            hint="handlers should only set flags; do the "
                                 "locked work on a watcher thread "
                                 "(PR-10's SIGTERM-drain shape), or "
                                 "suppress with the reason the lock "
                                 "cannot be held at handler time",
                            source_line=lrec["text"], qualname=node[1],
                            callpath=[h, node[1]]))
    return findings
