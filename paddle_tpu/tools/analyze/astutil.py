"""Shared pure-AST helpers for tpu-lint (no local imports, stdlib only).

One copy of the node-walking primitives and the domain tables (collective
names, rank spellings, store-op names) used by the per-file rule modules,
the pass-1 summarizer, and the project-level (pass-2) rules.  Everything
here must stay importable with nothing but the stdlib — the analyzer's
zero-jax contract starts at this module.
"""
from __future__ import annotations

import ast

# ---- node indexing ---------------------------------------------------------


def index_tree(tree: ast.AST):
    """ONE DFS over the tree: attach parent links, collect the flat node
    list the rule modules iterate (instead of each re-walking), and compute
    dotted qualnames for named defs."""
    nodes = []
    qualnames = {}
    stack = [(tree, "")]
    while stack:
        node, prefix = stack.pop()
        nodes.append(node)
        for child in ast.iter_child_nodes(node):
            child._tpulint_parent = node  # type: ignore[attr-defined]
            cprefix = prefix
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                cprefix = f"{prefix}.{child.name}" if prefix else child.name
                if not isinstance(child, ast.ClassDef):
                    qualnames[child] = cprefix
            stack.append((child, cprefix))
    return nodes, qualnames


def parent(node):
    return getattr(node, "_tpulint_parent", None)


def parents(node):
    p = parent(node)
    while p is not None:
        yield p
        p = parent(p)


def terminal_name(func) -> str:
    """Last path component of a call target: ``a.b.c(...)`` -> ``"c"``."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def dotted(node) -> str:
    """Dotted source path of a Name/Attribute chain, "" when not a chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def enclosing_function(node):
    for p in parents(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return p
    return None


def enclosing_class_name(node) -> str:
    """Name of the nearest enclosing ClassDef, or ""."""
    for p in parents(node):
        if isinstance(p, ast.ClassDef):
            return p.name
    return ""


# ---- domain tables ---------------------------------------------------------

COLLECTIVES = {
    "all_reduce", "all_gather", "all_gather_object", "reduce",
    "reduce_scatter", "broadcast", "broadcast_object_list", "scatter",
    "scatter_object_list", "all_to_all", "alltoall", "alltoall_single",
    "barrier", "gloo_barrier", "all_reduce_quantized",
}
P2P = {"send", "recv", "isend", "irecv"}

RANK_NAMES = {
    "rank", "local_rank", "node_rank", "rank_id", "global_rank",
    "cur_rank", "src_rank", "dst_rank", "self_rank", "world_rank",
}
RANK_CALLS = {"get_rank", "get_group_rank", "get_world_rank"}
FETCH_CALLS = {"item", "numpy"}

# TCPStore-shaped client surface (blocking network round-trips)
STORE_OPS = {"get", "set", "add", "check", "delete_key", "wait",
             "multi_get", "multi_set", "compare_set"}
# mutating subset (``add(k, 0)`` is the counter-READ idiom, handled at
# the call site)
STORE_WRITE_OPS = {"set", "add", "delete_key", "compare_set", "multi_set"}


def is_store_chain(chain: str) -> bool:
    """A dotted receiver that is (or holds) a store client:
    ``self.store.get`` / ``store.set`` / ``self._store.add``."""
    parts = chain.split(".")
    return any("store" in p.lower() for p in parts[:-1])


def test_flags(test) -> tuple:
    """(rank_dependent, data_dependent) for a branch test expression."""
    rank = data = False
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in RANK_NAMES:
            rank = True
        elif isinstance(node, ast.Attribute) and node.attr in RANK_NAMES:
            rank = True
        elif isinstance(node, ast.Call):
            t = terminal_name(node.func)
            if t in RANK_CALLS:
                rank = True
            elif t in FETCH_CALLS:
                data = True
    return rank, data


def branch_context(call):
    """Walk outward from a call collecting the branches that condition it:
    -> (rank_if, data_if, except_handler) nodes (or None each)."""
    rank_if = data_if = except_handler = None
    node = call
    for p in parents(call):
        if isinstance(p, (ast.If, ast.While)):
            # the test itself is evaluated unconditionally; only the body
            # and orelse are conditioned on it
            if node is not p.test:
                rank, data = test_flags(p.test)
                if rank and rank_if is None:
                    rank_if = p
                if data and data_if is None:
                    data_if = p
        elif isinstance(p, ast.IfExp):
            if node is not p.test:
                rank, data = test_flags(p.test)
                if rank and rank_if is None:
                    rank_if = p
                if data and data_if is None:
                    data_if = p
        elif isinstance(p, ast.ExceptHandler):
            if except_handler is None:
                except_handler = p
        elif isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            break  # conditions outside the enclosing function don't count
        node = p
    return rank_if, data_if, except_handler


def joined_leading_text(node) -> str:
    """Static leading text of a string expression: the whole value for a
    str Constant, the text before the first interpolation for a JoinedStr,
    "" otherwise."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        out = []
        for part in node.values:
            if isinstance(part, ast.Constant) and isinstance(part.value, str):
                out.append(part.value)
            else:
                break
        return "".join(out)
    return ""
