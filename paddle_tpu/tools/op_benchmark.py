"""Op micro-benchmark CLI (reference: tools/ci_op_benchmark.sh — clone op
benchmarks, time ops, diff against a baseline via
tools/check_op_benchmark_result.py; here self-contained).

    python -m paddle_tpu.tools.op_benchmark --op matmul \
        --shapes 512x512,512x512 --dtype float32 --repeat 50
    python -m paddle_tpu.tools.op_benchmark --op relu --shapes 1024 \
        --baseline old.json --threshold 0.05

Prints one JSON line per op; with --baseline, exits 1 when an op got
slower than the threshold (the CI gate semantics of
check_op_benchmark_result.py).
"""
from __future__ import annotations

import argparse
import json
import sys
import time

__all__ = ["benchmark_op", "compare", "main"]


def _parse_shapes(spec):
    shapes = []
    for part in spec.split(","):
        part = part.strip()
        shapes.append([int(d) for d in part.split("x")] if part else [])
    return shapes


def benchmark_op(op_name, shapes, dtype="float32", repeat=50, warmup=5,
                 seed=0):
    """Time one eager op on the current device; returns a result dict."""
    import numpy as np

    import paddle_tpu as paddle
    fn = getattr(paddle, op_name, None)
    if fn is None:
        import paddle_tpu.nn.functional as F
        fn = getattr(F, op_name, None)
    if fn is None:
        raise SystemExit(f"unknown op '{op_name}' (looked in paddle.* "
                         "and paddle.nn.functional.*)")
    rng = np.random.RandomState(seed)
    # feed exactly the op's required positional arity (a unary op given
    # two --shapes must not receive a stray tensor as its name= kwarg)
    import inspect
    try:
        params = list(inspect.signature(fn).parameters.values())
        required = len([p for p in params
                        if p.default is inspect.Parameter.empty
                        and p.kind in (p.POSITIONAL_ONLY,
                                       p.POSITIONAL_OR_KEYWORD)])
        shapes = shapes[:max(required, 1)]
    except (TypeError, ValueError):
        pass
    args = [paddle.to_tensor(rng.rand(*s).astype(dtype) + 0.1)
            for s in shapes]
    for _ in range(warmup):
        out = fn(*args)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(repeat):
        out = fn(*args)
    _sync(out)
    us = (time.perf_counter() - t0) / repeat * 1e6
    import jax
    return {"op": op_name, "shapes": shapes, "dtype": dtype,
            "repeat": repeat, "us_per_call": round(us, 2),
            "device": jax.devices()[0].device_kind}


def _sync(out):
    import numpy as np
    t = out[0] if isinstance(out, (tuple, list)) else out
    np.asarray(t._data)  # device fetch = true sync (tunnel-safe)


def compare(results, baseline, threshold=0.05):
    """Reference: tools/check_op_benchmark_result.py — report ops slower
    than baseline by more than threshold; returns the regressions."""
    base = {r["op"]: r for r in baseline}
    regressions = []
    for r in results:
        b = base.get(r["op"])
        if b is None:
            continue
        ratio = r["us_per_call"] / max(b["us_per_call"], 1e-9)
        if ratio > 1.0 + threshold:
            regressions.append({"op": r["op"], "ratio": round(ratio, 3),
                                "now_us": r["us_per_call"],
                                "baseline_us": b["us_per_call"]})
    return regressions


def main(argv=None):
    ap = argparse.ArgumentParser(prog="paddle_tpu.tools.op_benchmark")
    ap.add_argument("--op", action="append", required=True,
                    help="op name (repeatable)")
    ap.add_argument("--shapes", default="256x256",
                    help="comma-separated DxD shapes, one per op input")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--repeat", type=int, default=50)
    ap.add_argument("--baseline", default=None,
                    help="json file of prior results to diff against")
    ap.add_argument("--threshold", type=float, default=0.05)
    ap.add_argument("--out", default=None, help="write results json here")
    args = ap.parse_args(argv)

    shapes = _parse_shapes(args.shapes)
    results = [benchmark_op(op, shapes, args.dtype, args.repeat)
               for op in args.op]
    for r in results:
        print(json.dumps(r))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f)
    if args.baseline:
        with open(args.baseline) as f:
            regs = compare(results, json.load(f), args.threshold)
        if regs:
            print(json.dumps({"regressions": regs}), file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
