"""Summarize per-test durations from a tier-1 pytest log.

The tier-1 suite runs against a hard wall-clock budget (870s; see
ROADMAP.md) and history shows it creeps: every PR adds "a few seconds" of
not-slow tests until one run on a loaded host trips the timeout at 92%
with zero failures. This tool makes the creep visible per PR: point it at
the tier-1 log (the verify command tees ``/tmp/_t1.log`` and passes
``--durations=N`` so pytest appends its slowest-durations section) and it
aggregates the call/setup/teardown rows into a per-test and per-file
ranking plus the budget headroom.

    python -m paddle_tpu.tools.slowest_tests /tmp/_t1.log
    python -m paddle_tpu.tools.slowest_tests /tmp/_t1.log -n 30 --by-file

Reads only what pytest already printed — no re-run, no plugins.
"""
from __future__ import annotations

import argparse
import re
import sys
from collections import defaultdict

__all__ = ["parse_durations", "summarize", "main"]

# "0.12s call     tests/test_x.py::test_y[param]"
_ROW = re.compile(
    r"^\s*(\d+(?:\.\d+)?)s\s+(call|setup|teardown)\s+(\S+)\s*$")
# "855 passed, 24 deselected in 712.34s (0:11:52)"
_TOTAL = re.compile(r" in (\d+(?:\.\d+)?)s")


def parse_durations(lines):
    """-> ({test_id: seconds (call+setup+teardown)}, wall_seconds|None)."""
    per_test = defaultdict(float)
    wall = None
    for line in lines:
        m = _ROW.match(line)
        if m:
            per_test[m.group(3)] += float(m.group(1))
            continue
        if ("passed" in line or "failed" in line) and " in " in line:
            t = _TOTAL.search(line)
            if t:
                wall = float(t.group(1))
    return dict(per_test), wall


def summarize(per_test, top=20, by_file=False):
    """-> list of (name, seconds) ranked slowest-first."""
    if by_file:
        per_file = defaultdict(float)
        for test_id, s in per_test.items():
            per_file[test_id.split("::")[0]] += s
        items = per_file.items()
    else:
        items = per_test.items()
    return sorted(items, key=lambda kv: -kv[1])[:top]


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Rank the slowest tests in a tier-1 pytest log "
                    "(requires the log to contain pytest's --durations "
                    "section)")
    ap.add_argument("log", help="pytest log file (e.g. /tmp/_t1.log)")
    ap.add_argument("-n", "--top", type=int, default=20)
    ap.add_argument("--by-file", action="store_true",
                    help="aggregate per test file instead of per test")
    ap.add_argument("--budget", type=float, default=870.0,
                    help="tier-1 wall-clock budget in seconds")
    args = ap.parse_args(argv)
    try:
        with open(args.log, errors="replace") as f:
            per_test, wall = parse_durations(f)
    except OSError as e:
        print(f"slowest_tests: cannot read {args.log}: {e}",
              file=sys.stderr)
        return 2
    if not per_test:
        print("slowest_tests: no durations section in the log — run the "
              "suite with --durations=50 (the ROADMAP tier-1 command "
              "includes it) so pytest appends per-test timings",
              file=sys.stderr)
        return 1
    rows = summarize(per_test, top=args.top, by_file=args.by_file)
    unit = "file" if args.by_file else "test"
    timed = sum(per_test.values())
    print(f"slowest {len(rows)} {unit}s "
          f"(timed {timed:.1f}s across {len(per_test)} tests"
          + (f"; run wall {wall:.1f}s of {args.budget:.0f}s budget, "
             f"{args.budget - wall:.1f}s headroom" if wall else "")
          + "):")
    for name, secs in rows:
        print(f"{secs:9.2f}s  {name}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
