"""Summarize per-test durations from a tier-1 pytest log.

The tier-1 suite runs against a hard wall-clock budget (870s; see
ROADMAP.md) and history shows it creeps: every PR adds "a few seconds" of
not-slow tests until one run on a loaded host trips the timeout at 92%
with zero failures. This tool makes the creep visible per PR: point it at
the tier-1 log (the verify command tees ``/tmp/_t1.log`` and passes
``--durations=N`` so pytest appends its slowest-durations section) and it
aggregates the call/setup/teardown rows into a per-test and per-file
ranking plus the budget headroom.

    python -m paddle_tpu.tools.slowest_tests /tmp/_t1.log
    python -m paddle_tpu.tools.slowest_tests /tmp/_t1.log -n 30 --by-file

As a post-verify GATE (ISSUE 10 satellite), ``--fail-over-pct N`` exits
non-zero (rc 3) when the measured wall crosses N% of the budget — so
timing creep fails loudly per PR instead of being discovered as a
mysterious timeout months later::

    python -m paddle_tpu.tools.slowest_tests /tmp/_t1.log \
        --budget 870 --fail-over-pct 95

A log whose durations section exists but whose summary line is missing
(pytest was killed by the timeout before printing it) also fails the
gate: that IS the over-budget case.

Reads only what pytest already printed — no re-run, no plugins.
"""
from __future__ import annotations

import argparse
import re
import sys
from collections import defaultdict

__all__ = ["parse_durations", "summarize", "main"]

# "0.12s call     tests/test_x.py::test_y[param]"
_ROW = re.compile(
    r"^\s*(\d+(?:\.\d+)?)s\s+(call|setup|teardown)\s+(\S+)\s*$")
# "855 passed, 24 deselected in 712.34s (0:11:52)"
_TOTAL = re.compile(r" in (\d+(?:\.\d+)?)s")


def parse_durations(lines):
    """-> ({test_id: seconds (call+setup+teardown)}, wall_seconds|None)."""
    per_test = defaultdict(float)
    wall = None
    for line in lines:
        m = _ROW.match(line)
        if m:
            per_test[m.group(3)] += float(m.group(1))
            continue
        if ("passed" in line or "failed" in line) and " in " in line:
            t = _TOTAL.search(line)
            if t:
                wall = float(t.group(1))
    return dict(per_test), wall


def summarize(per_test, top=20, by_file=False):
    """-> list of (name, seconds) ranked slowest-first."""
    if by_file:
        per_file = defaultdict(float)
        for test_id, s in per_test.items():
            per_file[test_id.split("::")[0]] += s
        items = per_file.items()
    else:
        items = per_test.items()
    return sorted(items, key=lambda kv: -kv[1])[:top]


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Rank the slowest tests in a tier-1 pytest log "
                    "(requires the log to contain pytest's --durations "
                    "section)")
    ap.add_argument("log", help="pytest log file (e.g. /tmp/_t1.log)")
    ap.add_argument("-n", "--top", type=int, default=20)
    ap.add_argument("--by-file", action="store_true",
                    help="aggregate per test file instead of per test")
    ap.add_argument("--budget", type=float, default=870.0,
                    help="tier-1 wall-clock budget in seconds")
    ap.add_argument("--fail-over-pct", type=float, default=None,
                    dest="fail_over_pct", metavar="N",
                    help="exit 3 when the measured wall exceeds N%% of "
                         "--budget (or when the log has no summary line "
                         "at all — a timeout-killed run); wire as a "
                         "post-verify step so creep fails per PR")
    args = ap.parse_args(argv)
    try:
        with open(args.log, errors="replace") as f:
            per_test, wall = parse_durations(f)
    except OSError as e:
        print(f"slowest_tests: cannot read {args.log}: {e}",
              file=sys.stderr)
        return 2
    if not per_test:
        print("slowest_tests: no durations section in the log — run the "
              "suite with --durations=50 (the ROADMAP tier-1 command "
              "includes it) so pytest appends per-test timings",
              file=sys.stderr)
        return 1
    rows = summarize(per_test, top=args.top, by_file=args.by_file)
    unit = "file" if args.by_file else "test"
    timed = sum(per_test.values())
    print(f"slowest {len(rows)} {unit}s "
          f"(timed {timed:.1f}s across {len(per_test)} tests"
          + (f"; run wall {wall:.1f}s of {args.budget:.0f}s budget, "
             f"{args.budget - wall:.1f}s headroom" if wall else "")
          + "):")
    for name, secs in rows:
        print(f"{secs:9.2f}s  {name}")
    if args.fail_over_pct is not None:
        thresh = args.budget * args.fail_over_pct / 100.0
        if wall is None:
            print(f"slowest_tests: BUDGET GATE FAILED — the log has a "
                  "durations section but no summary line: pytest never "
                  "finished (timeout-killed run counts as over budget)",
                  file=sys.stderr)
            return 3
        if wall > thresh:
            print(f"slowest_tests: BUDGET GATE FAILED — wall "
                  f"{wall:.1f}s > {thresh:.1f}s "
                  f"({args.fail_over_pct:.0f}% of the "
                  f"{args.budget:.0f}s budget); trim or @slow-mark the "
                  "slowest tests above before merging", file=sys.stderr)
            return 3
        print(f"slowest_tests: budget gate ok — wall {wall:.1f}s <= "
              f"{thresh:.1f}s ({args.fail_over_pct:.0f}% of "
              f"{args.budget:.0f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
