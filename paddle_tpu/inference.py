"""paddle.inference — the deployment Predictor API.

Reference: paddle/fluid/inference/api/analysis_predictor.cc:392 +
paddle_inference_api.h (Config / create_predictor / get_input_handle /
run). The reference's analysis passes, IR fusion, and TensorRT subgraphs
collapse into XLA AOT: the .pdmodel artifact written by paddle.jit.save is
a serialized StableHLO executable, so a Predictor is a thin handle-based
wrapper over jit.load — kernel fusion happened at export compile time.
"""
from __future__ import annotations

import os

import numpy as np

from .core.tensor import Tensor
from .jit.save_load import load as _jit_load

__all__ = ["Config", "Predictor", "create_predictor", "PredictorTensor"]


class Config:
    """Reference: AnalysisConfig (paddle_analysis_config.h). Device/IR-pass
    knobs that have XLA equivalents are accepted and recorded; pure
    GPU/TensorRT toggles are accepted for API compatibility and ignored."""

    def __init__(self, prog_file=None, params_file=None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self._path = prog_file
        self._enable_memory_optim = True
        self._device = "tpu"
        self._ir_optim = True  # XLA optimizes at AOT-compile time

    def set_prog_file(self, path):
        self._path = path

    def prog_file(self):
        return (self._path or "") + ".pdmodel"

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"  # accelerator routing is PjRt's job

    def enable_xpu(self, *a, **k):
        self._device = "tpu"

    def disable_gpu(self):
        self._device = "cpu"

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = flag

    def enable_tensorrt_engine(self, *a, **k):
        pass  # XLA AOT already fused/compiled the graph

    def summary(self):
        return (f"Config(path={self._path!r}, device={self._device}, "
                "engine=XLA-AOT)")


class PredictorTensor:
    """Handle-based IO tensor (reference: ZeroCopyTensor)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def reshape(self, shape):
        pass  # shapes come from the data in copy_from_cpu

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return list(self._value.shape) if self._value is not None else []


class Predictor:
    """Reference: AnalysisPredictor (analysis_predictor.cc:392 init, :1205
    Run). Holds a loaded AOT executable + named IO handles."""

    def __init__(self, config):
        if isinstance(config, str):
            config = Config(config)
        self._config = config
        path = config._path
        if path is None or not os.path.exists(path + ".pdmodel"):
            raise FileNotFoundError(
                f"no exported model at {path!r}; produce one with "
                "paddle.jit.save(layer, path, input_spec=[...])")
        self._layer = _jit_load(path)
        n_in = len(self._layer._meta.get("input_specs", []))
        self._inputs = [PredictorTensor(f"input_{i}") for i in range(n_in)]
        self._outputs: list = []

    def get_input_names(self):
        return [t.name for t in self._inputs]

    def get_input_handle(self, name):
        for t in self._inputs:
            if t.name == name:
                return t
        raise KeyError(name)

    def get_output_names(self):
        return [t.name for t in self._outputs] or ["output_0"]

    def get_output_handle(self, name):
        for t in self._outputs:
            if t.name == name:
                return t
        raise KeyError(name)

    def run(self, inputs=None):
        """Handle-style (None) or direct list-of-arrays call."""
        if inputs is None:
            arrs = [t._value for t in self._inputs]
            if any(a is None for a in arrs):
                missing = [t.name for t in self._inputs if t._value is None]
                raise RuntimeError(f"inputs not set: {missing}")
        else:
            arrs = [a.numpy() if isinstance(a, Tensor) else np.asarray(a)
                    for a in inputs]
        outs = self._layer(*arrs)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        self._outputs = []
        results = []
        for i, o in enumerate(outs):
            h = PredictorTensor(f"output_{i}")
            h._value = np.asarray(o.numpy() if isinstance(o, Tensor)
                                  else o)
            self._outputs.append(h)
            results.append(h._value)
        return results


def create_predictor(config):
    """Reference: paddle_infer::CreatePredictor."""
    return Predictor(config)
