"""paddle.inference — the deployment Predictor API.

Reference: paddle/fluid/inference/api/analysis_predictor.cc:392 +
paddle_inference_api.h (Config / create_predictor / get_input_handle /
run). The reference's analysis passes, IR fusion, and TensorRT subgraphs
collapse into XLA AOT: the .pdmodel artifact written by paddle.jit.save is
a serialized StableHLO executable, so a Predictor is a thin handle-based
wrapper over jit.load — kernel fusion happened at export compile time.
"""
from __future__ import annotations

import os

import numpy as np

from .core.tensor import Tensor
from .jit.save_load import load as _jit_load

__all__ = ["Config", "Predictor", "create_predictor", "PredictorTensor",
           "BatchingPredictor", "pick_bucket"]


def pick_bucket(n, buckets, strict=False):
    """Smallest bucket >= n — ONE copy of the pad-to-bucket rule, shared
    by :class:`BatchingPredictor` (batch dim) and the serving engine's
    bucketed fallback (batch AND sequence dims): a small bucket set keeps
    XLA's compile cache bounded while filling the padded shape.

    When ``n`` exceeds the largest bucket the default is the historical
    clamp-down (callers like BatchingPredictor split oversize batches
    themselves). ``strict=True`` raises instead (ISSUE 13 satellite): a
    serving launch sized by a clamped-down bucket would index past its
    padding and silently truncate the round — callers that cannot split
    must fail loudly."""
    for b in buckets:
        if b >= n:
            return b
    if strict:
        raise ValueError(
            f"batch of {n} exceeds the largest configured bucket "
            f"{buckets[-1]} — split the round or widen the bucket set "
            "(a clamped-down launch would truncate the round)")
    return buckets[-1]


class Config:
    """Reference: AnalysisConfig (paddle_analysis_config.h). Device/IR-pass
    knobs that have XLA equivalents are accepted and recorded; pure
    GPU/TensorRT toggles are accepted for API compatibility and ignored."""

    def __init__(self, prog_file=None, params_file=None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self._path = prog_file
        self._enable_memory_optim = True
        self._device = "tpu"
        self._ir_optim = True  # XLA optimizes at AOT-compile time

    def set_prog_file(self, path):
        self._path = path

    def prog_file(self):
        return (self._path or "") + ".pdmodel"

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"  # accelerator routing is PjRt's job

    def enable_xpu(self, *a, **k):
        self._device = "tpu"

    def disable_gpu(self):
        self._device = "cpu"

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = flag

    def enable_tensorrt_engine(self, *a, **k):
        pass  # XLA AOT already fused/compiled the graph

    def summary(self):
        return (f"Config(path={self._path!r}, device={self._device}, "
                "engine=XLA-AOT)")


class PredictorTensor:
    """Handle-based IO tensor (reference: ZeroCopyTensor)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def reshape(self, shape):
        pass  # shapes come from the data in copy_from_cpu

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return list(self._value.shape) if self._value is not None else []


class Predictor:
    """Reference: AnalysisPredictor (analysis_predictor.cc:392 init, :1205
    Run). Holds a loaded AOT executable + named IO handles."""

    def __init__(self, config):
        if isinstance(config, str):
            config = Config(config)
        self._config = config
        path = config._path
        if path is None or not os.path.exists(path + ".pdmodel"):
            raise FileNotFoundError(
                f"no exported model at {path!r}; produce one with "
                "paddle.jit.save(layer, path, input_spec=[...])")
        self._layer = _jit_load(path)
        n_in = len(self._layer._meta.get("input_specs", []))
        self._inputs = [PredictorTensor(f"input_{i}") for i in range(n_in)]
        self._outputs: list = []

    def get_input_names(self):
        return [t.name for t in self._inputs]

    def get_input_handle(self, name):
        for t in self._inputs:
            if t.name == name:
                return t
        raise KeyError(name)

    def get_output_names(self):
        return [t.name for t in self._outputs] or ["output_0"]

    def get_output_handle(self, name):
        for t in self._outputs:
            if t.name == name:
                return t
        raise KeyError(name)

    def run(self, inputs=None):
        """Handle-style (None) or direct list-of-arrays call."""
        if inputs is None:
            arrs = [t._value for t in self._inputs]
            if any(a is None for a in arrs):
                missing = [t.name for t in self._inputs if t._value is None]
                raise RuntimeError(f"inputs not set: {missing}")
        else:
            arrs = [a.numpy() if isinstance(a, Tensor) else np.asarray(a)
                    for a in inputs]
        outs = self._layer(*arrs)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        self._outputs = []
        results = []
        for i, o in enumerate(outs):
            h = PredictorTensor(f"output_{i}")
            h._value = np.asarray(o.numpy() if isinstance(o, Tensor)
                                  else o)
            self._outputs.append(h)
            results.append(h._value)
        return results


def create_predictor(config):
    """Reference: paddle_infer::CreatePredictor."""
    return Predictor(config)


class BatchingPredictor:
    """Serving-side dynamic batching over a Predictor (reference: the
    serving path the inference engine feeds — fluid/inference/api plus the
    server-side batching of Paddle Serving; SURVEY layer 11's 'partial'
    gap). Requests are queued, grouped up to ``max_batch_size`` (waiting
    at most ``max_wait_ms`` for stragglers), padded to the next bucket
    size, and executed as ONE compiled call — the TPU-native answer to
    per-request latency vs MXU utilization: bucketed static shapes keep
    XLA's compile cache small while filling the batch dim.
    """

    def __init__(self, predictor, max_batch_size=8, max_wait_ms=2.0,
                 batch_buckets=None):
        import queue
        import threading
        self._pred = predictor
        self._buckets = sorted(batch_buckets or
                               [1, 2, 4, max_batch_size])
        # a batch larger than the largest bucket could never be padded to
        # a known compiled shape — clamp (one-compiled-shape-per-bucket)
        self._max_b = min(max_batch_size, self._buckets[-1])
        self._wait_s = max_wait_ms / 1e3
        self._q: "queue.Queue" = queue.Queue()
        self._stop = False
        self._closed = False
        # guards the closed-check+enqueue vs close's drain: without it a
        # predict() preempted between the check and the put could enqueue
        # into an already-drained queue and hang to its own timeout
        self._close_lock = threading.Lock()
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def _bucket(self, n):
        return pick_bucket(n, self._buckets)

    def _loop(self):
        import queue
        import time
        while not self._stop:
            try:
                first = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            batch = [first]
            deadline = time.perf_counter() + self._wait_s
            while len(batch) < self._max_b:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._q.get(timeout=remaining))
                except queue.Empty:
                    break
            self._run_batch(batch)

    def _run_batch(self, batch):
        import numpy as np
        arrs = [np.asarray(req[0]) for req in batch]
        n = len(arrs)
        b = self._bucket(n)
        stacked = np.stack(arrs)
        if b > n:  # pad to the bucket: one compiled shape per bucket
            pad = np.repeat(stacked[-1:], b - n, axis=0)
            stacked = np.concatenate([stacked, pad], axis=0)
        try:
            outs = self._pred.run([stacked])
            for i, (_, fut) in enumerate(batch):
                fut["result"] = [o[i] for o in outs]
                fut["event"].set()
        except Exception as e:  # propagate to every waiter
            for _, fut in batch:
                fut["error"] = e
                fut["event"].set()

    def predict(self, example, timeout=30.0):
        """Submit ONE example (no batch dim); blocks for the result."""
        import threading
        fut = {"event": threading.Event(), "result": None, "error": None}
        with self._close_lock:
            if self._closed:
                raise RuntimeError("BatchingPredictor is closed")
            self._q.put((example, fut))
        if not fut["event"].wait(timeout):
            raise TimeoutError("BatchingPredictor request timed out")
        if fut["error"] is not None:
            raise fut["error"]
        res = fut["result"]
        return res[0] if len(res) == 1 else res

    def close(self, timeout=5.0):
        """Stop the worker and FAIL anything still queued. Before this
        fix teardown leaked the daemon thread and silently dropped
        in-flight requests: a waiter blocked in ``predict`` hung until
        its own timeout with no cause. Now the worker drains its current
        batch, queued futures get a ``RuntimeError``, and later
        ``predict`` calls fail fast. Idempotent; also the context-manager
        exit (``with BatchingPredictor(p) as bp: ...``)."""
        import queue
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self._stop = True
        self._worker.join(timeout=timeout)
        while True:
            try:
                _, fut = self._q.get_nowait()
            except queue.Empty:
                break
            fut["error"] = RuntimeError(
                "BatchingPredictor closed before the request ran")
            fut["event"].set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
