"""paddle.audio.features — Spectrogram / MelSpectrogram / MFCC layers.

Reference: python/paddle/audio/features/layers.py. The STFT is framing
(strided gather) + window + rfft — all staged through the dispatch tape so
feature extraction is differentiable and jit-stageable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import fft as _fft
from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..nn import Layer
from . import functional as F

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def _frame(x, frame_length, hop_length, center=True, pad_mode="reflect"):
    """[..., T] -> [..., n_frames, frame_length]."""
    def f(a):
        if center:
            pad = [(0, 0)] * (a.ndim - 1) + [(frame_length // 2,
                                              frame_length // 2)]
            a = jnp.pad(a, pad, mode=pad_mode)
        T = a.shape[-1]
        n = 1 + (T - frame_length) // hop_length
        starts = jnp.arange(n) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]
        return jnp.take(a, idx, axis=-1)
    return apply("audio_frame", f, [x])


class Spectrogram(Layer):
    """Reference: audio/features/layers.py Spectrogram."""

    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = F.get_window(window, self.win_length, dtype=dtype)
        if self.win_length < n_fft:  # center-pad window to n_fft
            lpad = (n_fft - self.win_length) // 2
            w = Tensor(jnp.pad(w._data,
                               (lpad, n_fft - self.win_length - lpad)))
        self.register_buffer("window", w)

    def forward(self, x):
        frames = _frame(x, self.n_fft, self.hop_length, self.center,
                        self.pad_mode)
        windowed = apply("stft_window", lambda a, w: a * w,
                         [frames, self.window])
        spec = _fft.rfft(windowed, n=self.n_fft, axis=-1)
        # [..., n_frames, n_fft//2+1] -> [..., freq, time]
        mag = apply("spec_power",
                    lambda s: jnp.abs(s) ** self.power
                    if self.power != 1.0 else jnp.abs(s), [spec])
        return apply("spec_transpose", lambda a: jnp.swapaxes(a, -1, -2),
                     [mag])


class MelSpectrogram(Layer):
    """Reference: audio/features/layers.py MelSpectrogram."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", dtype="float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power, center, pad_mode,
                                       dtype)
        fb = F.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max, htk,
                                    norm)
        self.register_buffer("fbank_matrix", fb)

    def forward(self, x):
        spec = self.spectrogram(x)           # [..., freq, time]
        return apply("mel_project", lambda fb, s: fb @ s,
                     [self.fbank_matrix, spec])


class LogMelSpectrogram(Layer):
    """Reference: audio/features/layers.py LogMelSpectrogram."""

    def __init__(self, sr=22050, ref_value=1.0, amin=1e-10, top_db=None,
                 **kwargs):
        super().__init__()
        self.mel = MelSpectrogram(sr=sr, **kwargs)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return F.power_to_db(self.mel(x), self.ref_value, self.amin,
                             self.top_db)


class MFCC(Layer):
    """Reference: audio/features/layers.py MFCC."""

    def __init__(self, sr=22050, n_mfcc=40, norm="ortho", **kwargs):
        super().__init__()
        self.log_mel = LogMelSpectrogram(sr=sr, top_db=80.0, **kwargs)
        n_mels = self.log_mel.mel.fbank_matrix.shape[0]
        self.register_buffer("dct_matrix", F.create_dct(n_mfcc, n_mels,
                                                        norm))

    def forward(self, x):
        logmel = self.log_mel(x)             # [..., n_mels, time]
        return apply("mfcc_dct", lambda d, s: jnp.swapaxes(
            jnp.swapaxes(s, -1, -2) @ d, -1, -2),
            [self.dct_matrix, logmel])
