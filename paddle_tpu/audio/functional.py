"""paddle.audio.functional — windows, mel scales, spectrogram math.

Reference: python/paddle/audio/functional/{window,functional}.py. All pure
jnp through the dispatch tape; the STFT rides paddle.fft (XLA FFT HLO, with
the CPU fallback where the runtime lacks it).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct",
           "get_window"]


def _as_np(x):
    return np.asarray(x.numpy() if isinstance(x, Tensor) else x)


def hz_to_mel(freq, htk=False):
    """Reference: audio/functional/functional.py hz_to_mel (slaney
    default)."""
    scalar = np.isscalar(freq)
    f = _as_np(freq).astype(np.float32)
    if htk:
        m = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        m = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        m = np.where(f >= min_log_hz,
                     min_log_mel + np.log(np.maximum(f, 1e-10)
                                          / min_log_hz) / logstep, m)
    return float(m) if scalar else Tensor(jnp.asarray(m))


def mel_to_hz(mel, htk=False):
    scalar = np.isscalar(mel)
    m = _as_np(mel).astype(np.float32)
    if htk:
        f = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        f = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        f = np.where(m >= min_log_mel,
                     min_log_hz * np.exp(logstep * (m - min_log_mel)), f)
    return float(f) if scalar else Tensor(jnp.asarray(f))


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False):
    lo = hz_to_mel(float(f_min), htk)
    hi = hz_to_mel(float(f_max), htk)
    mels = np.linspace(lo, hi, n_mels)
    return Tensor(jnp.asarray(_as_np(mel_to_hz(mels, htk)), jnp.float32))

def fft_frequencies(sr, n_fft):
    return Tensor(jnp.linspace(0, sr / 2, 1 + n_fft // 2,
                               dtype=jnp.float32))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney"):
    """Mel filterbank [n_mels, 1 + n_fft//2]."""
    f_max = f_max if f_max is not None else sr / 2.0
    fft_f = np.asarray(fft_frequencies(sr, n_fft).numpy())
    mel_f = np.asarray(
        mel_frequencies(n_mels + 2, f_min, f_max, htk).numpy())
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    fb = np.maximum(0.0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        fb *= enorm[:, None]
    return Tensor(jnp.asarray(fb, jnp.float32))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """Reference: audio/functional power_to_db."""
    def f(s):
        log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s))
        log_spec = log_spec - 10.0 * jnp.log10(
            jnp.maximum(amin, ref_value))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
        return log_spec
    return apply("power_to_db", f,
                 [spect if isinstance(spect, Tensor) else Tensor(spect)])


def create_dct(n_mfcc, n_mels, norm="ortho"):
    """DCT-II matrix [n_mels, n_mfcc]."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)[None, :]
    dct = np.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct[:, 0] *= 1.0 / math.sqrt(2)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return Tensor(jnp.asarray(dct, jnp.float32))


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """Reference: audio/functional/window.py get_window."""
    N = win_length
    M = N if not fftbins else N + 1  # periodic windows drop the last point
    n = np.arange(M, dtype=np.float64)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * math.pi * n / (M - 1))
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * math.pi * n / (M - 1))
    elif window == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * math.pi * n / (M - 1))
             + 0.08 * np.cos(4 * math.pi * n / (M - 1)))
    elif window in ("rect", "boxcar", "ones"):
        w = np.ones(M)
    elif window == "bartlett":
        w = 1.0 - np.abs(2 * n / (M - 1) - 1.0)
    elif window == "bohman":
        x = np.abs(2 * n / (M - 1) - 1.0)
        w = (1 - x) * np.cos(math.pi * x) + np.sin(math.pi * x) / math.pi
    elif window == "cosine":
        w = np.sin(math.pi * (n + 0.5) / M)
    else:
        raise ValueError(f"unsupported window {window!r}")
    w = w[:N]
    return Tensor(jnp.asarray(w, dtype))
