"""paddle.audio.datasets — ESC50 / TESS audio-classification datasets.

Reference: python/paddle/audio/datasets/{dataset,esc50,tess}.py. Zero
egress here, so ``archive`` downloads raise with instructions; the loaders
read the standard on-disk layouts (ESC-50-master/meta/esc50.csv + audio/,
TESS 'OAF_word_emotion.wav' files), with the reference feat_type options
computed by paddle_tpu.audio.features.
"""
from __future__ import annotations

import os

import numpy as np

from ..io import Dataset

__all__ = ["AudioClassificationDataset", "ESC50", "TESS"]


class AudioClassificationDataset(Dataset):
    """Reference: audio/datasets/dataset.py AudioClassificationDataset —
    (waveform-or-feature, label) pairs from (files, labels)."""

    _FEATS = ("raw", "spectrogram", "melspectrogram", "logmelspectrogram",
              "mfcc")

    def __init__(self, files, labels, feat_type="raw", sample_rate=None,
                 **feat_config):
        super().__init__()
        if feat_type not in self._FEATS:
            raise RuntimeError(
                f"Unknown feat_type: {feat_type}, it must be one of "
                f"{list(self._FEATS)}")
        self.files = list(files)
        self.labels = list(labels)
        self.feat_type = feat_type
        self.sample_rate = sample_rate
        self.feat_config = feat_config

    def _featurize(self, waveform, sr):
        import paddle_tpu as paddle
        from . import features as feats
        x = paddle.to_tensor(waveform[None].astype("float32"))
        if self.feat_type == "raw":
            return x[0]
        cfg = dict(self.feat_config)
        if self.feat_type == "spectrogram":
            return feats.Spectrogram(**cfg)(x)[0]
        if self.feat_type == "melspectrogram":
            return feats.MelSpectrogram(sr=sr, **cfg)(x)[0]
        if self.feat_type == "logmelspectrogram":
            return feats.LogMelSpectrogram(sr=sr, **cfg)(x)[0]
        return feats.MFCC(sr=sr, **cfg)(x)[0]

    def __getitem__(self, idx):
        from . import backends
        wav, sr = backends.load(self.files[idx])
        w = np.asarray(wav.numpy() if hasattr(wav, "numpy") else wav)
        if w.ndim == 2:
            w = w[0]
        self.sample_rate = sr
        feat = self._featurize(w, sr)
        return np.asarray(feat.numpy()), np.int64(self.labels[idx])

    def __len__(self):
        return len(self.files)


def _no_download(name, url):
    raise RuntimeError(
        f"{name}: automatic download is unavailable (no network egress); "
        f"fetch {url} elsewhere and pass data_dir=<extracted dir>")


class ESC50(AudioClassificationDataset):
    """Reference: audio/datasets/esc50.py — 2000 recordings, 50 classes,
    5 folds; mode='train' takes folds != split, 'dev' takes fold == split.
    data_dir must hold ESC-50-master/ (meta/esc50.csv + audio/*.wav)."""

    URL = "https://github.com/karoldvl/ESC-50/archive/master.zip"
    sample_rate = 44100
    duration = 5

    def __init__(self, mode="train", split=1, feat_type="raw",
                 data_dir=None, archive=None, **kwargs):
        if data_dir is None:
            _no_download("ESC50", self.URL)
        root = data_dir
        if os.path.isdir(os.path.join(data_dir, "ESC-50-master")):
            root = os.path.join(data_dir, "ESC-50-master")
        meta = os.path.join(root, "meta", "esc50.csv")
        audio_dir = os.path.join(root, "audio")
        files, labels = [], []
        with open(meta) as f:
            rows = f.read().splitlines()[1:]  # header row
        for row in rows:
            filename, fold, target = row.split(",")[:3]
            fold, target = int(fold), int(target)
            keep = (fold != split) if mode == "train" else (fold == split)
            if keep:
                files.append(os.path.join(audio_dir, filename))
                labels.append(target)
        super().__init__(files, labels, feat_type=feat_type, **kwargs)


class TESS(AudioClassificationDataset):
    """Reference: audio/datasets/tess.py — Toronto emotional speech set:
    2800 files '(OAF|YAF)_word_emotion.wav', 7 emotion classes; n_folds
    cross-validation split like the reference."""

    URL = ("https://tspace.library.utoronto.ca/bitstream/1807/24487/1/"
           "TESS_Toronto_emotional_speech_set_data.zip")
    label_list = ["angry", "disgust", "fear", "happy", "neutral", "ps",
                  "sad"]

    def __init__(self, mode="train", n_folds=5, split=1, feat_type="raw",
                 data_dir=None, archive=None, **kwargs):
        if data_dir is None:
            _no_download("TESS", self.URL)
        if not 1 <= split <= n_folds:
            raise ValueError(f"split {split} out of 1..{n_folds}")
        wavs = []
        for dirpath, _, names in os.walk(data_dir):
            for n in sorted(names):
                if n.lower().endswith(".wav"):
                    wavs.append(os.path.join(dirpath, n))
        wavs.sort()
        files, labels = [], []
        for i, path in enumerate(wavs):
            emotion = os.path.splitext(os.path.basename(path))[0] \
                .split("_")[-1].lower()
            if emotion not in self.label_list:
                continue
            fold = i % n_folds + 1
            keep = (fold != split) if mode == "train" else (fold == split)
            if keep:
                files.append(path)
                labels.append(self.label_list.index(emotion))
        super().__init__(files, labels, feat_type=feat_type, **kwargs)
