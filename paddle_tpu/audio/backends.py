"""paddle.audio.backends — wav load/save/info over the stdlib wave module.

Reference: python/paddle/audio/backends (soundfile-based; this environment
has no soundfile, and 16-bit PCM WAV covers the reference datasets)."""
from __future__ import annotations

import wave as _wave

import numpy as np

from ..core.tensor import Tensor

__all__ = ["load", "save", "info", "AudioInfo"]


class AudioInfo:
    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def info(filepath):
    with _wave.open(filepath, "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                         f.getsampwidth() * 8)


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Returns (Tensor [C, T] or [T, C], sample_rate)."""
    with _wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        n_ch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    dtype = {1: np.uint8, 2: np.int16, 4: np.int32}[width]
    data = np.frombuffer(raw, dtype=dtype).reshape(-1, n_ch)
    if width == 1:
        data = data.astype(np.float32) / 128.0 - 1.0
    elif normalize:
        data = data.astype(np.float32) / float(2 ** (8 * width - 1))
    if channels_first:
        data = data.T
    return Tensor(np.ascontiguousarray(data)), sr


def save(filepath, src, sample_rate, channels_first=True,
         bits_per_sample=16):
    data = np.asarray(src.numpy() if isinstance(src, Tensor) else src)
    if channels_first:
        data = data.T
    assert bits_per_sample == 16, "16-bit PCM only"
    pcm = np.clip(data * 32767.0, -32768, 32767).astype(np.int16)
    with _wave.open(filepath, "wb") as f:
        f.setnchannels(pcm.shape[1] if pcm.ndim > 1 else 1)
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(pcm.tobytes())
