"""paddle.audio — audio feature extraction + IO.

Reference namespace: python/paddle/audio/ (functional, features, backends,
datasets). Datasets that require downloads raise with instructions (zero
egress here); feature layers and IO are fully functional.
"""
from . import backends  # noqa: F401
from . import datasets  # noqa: F401
from . import features  # noqa: F401
from . import functional  # noqa: F401
from .backends import info, load, save  # noqa: F401
from .features import (  # noqa: F401
    LogMelSpectrogram, MelSpectrogram, MFCC, Spectrogram,
)

__all__ = ["functional", "features", "backends", "load", "save", "info",
           "Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]
