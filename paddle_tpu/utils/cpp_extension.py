"""Custom-op registration — TPU-native analog of Paddle's custom operators.

Reference: ``paddle/fluid/framework/custom_operator.cc`` (PD_BUILD_OP
registration + kernel wiring), ``python/paddle/utils/cpp_extension/``
(CppExtension/CUDAExtension/load build path), ``test/custom_op/`` (the
user-facing contract: a custom op behaves exactly like a built-in — eager,
static, with autograd).

On TPU the "custom kernel" is a user JAX or Pallas function, so the C++
build machinery collapses: :func:`custom_op` registers a python function
operating on raw jax arrays as a first-class taped op. The registered op

* dispatches through :func:`core.dispatch.apply` — AMP autocast, the
  profiler, NaN/Inf checking, the static-graph recorder and the autograd
  tape all see it exactly like a generated op;
* differentiates via ``jax.vjp`` of the forward by default, or a
  user-supplied VJP rule (wrapped into ``jax.custom_vjp``);
* works under ``to_static`` (tracing dispatches the same ``apply`` path);
* optionally binds onto the ``Tensor`` method surface;
* carries a built-in golden check (:meth:`CustomOp.check`) replicating the
  reference's OpTest numeric-gradient validation for user ops.
"""
from __future__ import annotations

import functools

import numpy as np

__all__ = ["custom_op", "CustomOp", "get_op", "registered_ops",
           "CppExtension", "CUDAExtension", "load"]

_REGISTRY: dict = {}


class CustomOp:
    """A registered custom operator (reference: the OpMetaInfo record built
    by PD_BUILD_OP, custom_operator.cc).

    ``fn(*args, **attrs)`` operates on raw jax arrays (Tensor args are
    unwrapped before the call). ``vjp``, when given, receives
    ``(ct, *args, out)`` — the output cotangent, the op's original
    (array-valued) arguments, and the forward output — and must return one
    cotangent per Tensor argument, in positional order.
    """

    def __init__(self, name, fn, vjp=None, nout=1, golden=None):
        self.name = name
        self.fn = fn
        self.vjp = vjp
        self.nout = nout
        self.golden = golden
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        from ..core.dispatch import apply
        from ..core.tensor import Tensor
        for k, v in kwargs.items():
            if isinstance(v, Tensor):
                raise TypeError(
                    f"custom op '{self.name}': Tensor keyword argument "
                    f"'{k}' is not supported — pass tensors positionally "
                    "(keywords are compile-time attributes)")
        tensor_idx = [i for i, a in enumerate(args)
                      if isinstance(a, Tensor)]
        statics = list(args)

        def fwd(*arrs):
            merged = list(statics)
            for pos, a in zip(tensor_idx, arrs):
                merged[pos] = a
            return self.fn(*merged, **kwargs)

        if self.vjp is not None:
            fwd = self._with_custom_vjp(fwd, tensor_idx, statics, kwargs)
        tensors = [args[i] for i in tensor_idx]
        return apply(self.name, fwd, tensors, nout=self.nout)

    def _with_custom_vjp(self, fwd, tensor_idx, statics, kwargs):
        """Wrap the array-level forward with the user's backward rule, so
        the tape's jax.vjp picks up the custom rule (the custom grad
        kernel of custom_operator.cc RunCustomOperator's grad path)."""
        import jax
        user_vjp = self.vjp
        f = jax.custom_vjp(fwd)

        def f_fwd(*arrs):
            out = fwd(*arrs)
            return out, (arrs, out)

        def f_bwd(res, ct):
            arrs, out = res
            merged = list(statics)
            for pos, a in zip(tensor_idx, arrs):
                merged[pos] = a
            cts = user_vjp(ct, *merged, out=out, **kwargs)
            if not isinstance(cts, (tuple, list)):
                cts = (cts,)
            if len(cts) != len(arrs):
                raise ValueError(
                    f"custom op '{self.name}': vjp returned {len(cts)} "
                    f"cotangents for {len(arrs)} tensor inputs")
            return tuple(cts)

        f.defvjp(f_fwd, f_bwd)
        return f

    # -- golden validation (reference: test/custom_op/ + OpTest) ----------
    def check(self, *args, golden=None, rtol=1e-5, atol=1e-6, grad=True,
              eps=1e-3, seed=0, **kwargs):
        """Validate the op against a numpy reference and (directionally)
        its gradient against finite differences — the OpTest
        check_output/check_grad pair for user ops. Raises on mismatch."""
        from ..core.tensor import Tensor
        golden = golden or self.golden
        out = self(*args, **kwargs)
        outs = out if isinstance(out, tuple) else (out,)
        if golden is not None:
            np_args = [np.asarray(a._data) if isinstance(a, Tensor) else a
                       for a in args]
            ref = golden(*np_args, **kwargs)
            refs = ref if isinstance(ref, tuple) else (ref,)
            for o, r in zip(outs, refs):
                np.testing.assert_allclose(np.asarray(o._data), r,
                                           rtol=rtol, atol=atol,
                                           err_msg=f"{self.name} forward")
        if not grad:
            return
        tensors = [a for a in args if isinstance(a, Tensor)
                   and not a.stop_gradient]
        if not tensors:
            return
        rng = np.random.RandomState(seed)
        ct = [rng.randn(*o.shape).astype("float32") for o in outs]

        def scalar_loss(inputs):
            res = self(*inputs, **kwargs)
            res = res if isinstance(res, tuple) else (res,)
            total = None
            for o, c in zip(res, ct):
                term = (o.astype("float32") * Tensor(c)).sum()
                total = term if total is None else total + term
            return total

        loss = scalar_loss(list(args))
        from ..core.autograd import grad as _grad
        analytic = _grad([loss], tensors, allow_unused=True)
        # directional FD: d/dt loss(x + t*d) at t=0 vs <grad, d>
        for t, g in zip(tensors, analytic):
            d = rng.randn(*t.shape).astype(np.asarray(t._data).dtype)
            base = np.asarray(t._data)

            def loss_at(delta):
                shifted = []
                for a in args:
                    if a is t:
                        shifted.append(Tensor(base + delta * d,
                                              stop_gradient=True))
                    else:
                        shifted.append(a)
                return float(np.asarray(scalar_loss(shifted)._data))

            fd = (loss_at(eps) - loss_at(-eps)) / (2 * eps)
            an = float(np.sum(np.asarray(g._data) * d)) if g is not None \
                else 0.0
            np.testing.assert_allclose(
                an, fd, rtol=5e-2, atol=5e-3,
                err_msg=f"{self.name} grad wrt input (analytic {an} vs "
                        f"finite-difference {fd})")


def custom_op(name=None, vjp=None, nout=1, bind_method=False, golden=None,
              override=False):
    """Decorator registering a JAX/Pallas function as a first-class op.

    Example (the TPU analog of a PD_BUILD_OP custom kernel)::

        @paddle.utils.cpp_extension.custom_op(vjp=my_relu_grad)
        def my_relu(x):                 # raw jax arrays in/out
            return jnp.maximum(x, 0)

        y = my_relu(tensor)             # eager, taped
        paddle.jit.to_static(f)(...)    # stages like any built-in op

    ``vjp(ct, *args, out=...)`` returns one cotangent per Tensor argument.
    ``bind_method=True`` also attaches the op to the Tensor method surface.
    """
    def decorate(fn):
        op_name = name or fn.__name__
        if op_name in _REGISTRY and not override:
            raise ValueError(
                f"custom op '{op_name}' is already registered; pass "
                "override=True to replace it")
        op = CustomOp(op_name, fn, vjp=vjp, nout=nout, golden=golden)
        _REGISTRY[op_name] = op
        if bind_method:
            from ..core.tensor import Tensor
            if hasattr(Tensor, op_name) and not override:
                raise ValueError(
                    f"Tensor already has a method '{op_name}'; pass "
                    "override=True to shadow it")
            setattr(Tensor, op_name,
                    lambda self, *a, **k: op(self, *a, **k))
        return op

    if callable(name):  # bare @custom_op
        fn, name = name, None
        return decorate(fn)
    return decorate


def get_op(name):
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"no custom op named '{name}' is registered "
            f"(registered: {sorted(_REGISTRY)})") from None


def registered_ops():
    return sorted(_REGISTRY)


# -- reference-API build shims -------------------------------------------
def _no_cpp(name):
    raise NotImplementedError(
        f"{name}: C++/CUDA extension builds target CUDA devices; on the "
        "TPU backend register a JAX/Pallas function with "
        "paddle.utils.cpp_extension.custom_op instead (same taped-op "
        "semantics, no build step)")


def CppExtension(*a, **k):
    _no_cpp("CppExtension")


def CUDAExtension(*a, **k):
    _no_cpp("CUDAExtension")


def load(*a, **k):
    _no_cpp("load")
