"""paddle.utils — install checks + misc helpers.

Reference: python/paddle/utils/ (install_check.run_check, deprecated
decorator, unique_name). run_check is the canonical "is my install sane"
entry: it verifies device visibility, a compute round-trip, autograd, and
(when more than one device is visible) a sharded matmul.
"""
from __future__ import annotations

import contextlib

from . import cpp_extension  # noqa: F401  (custom-op registration)

__all__ = ["run_check", "deprecated", "unique_name", "try_import",
           "cpp_extension"]


def run_check(verbose=True):
    """Reference: paddle.utils.run_check() — prints a health summary and
    raises on failure."""
    import jax
    import numpy as np

    from ..core.tensor import Tensor

    def log(msg):
        if verbose:
            print(msg)

    devs = jax.devices()
    kind = devs[0].device_kind if devs else "none"
    log(f"paddle_tpu is checking {len(devs)} device(s): {kind}")

    # compute + transfer round trip
    a = Tensor(np.eye(4, dtype=np.float32))
    out = (a @ a).numpy()
    assert np.allclose(out, np.eye(4)), "matmul round-trip failed"

    # autograd
    x = Tensor(np.ones(3, np.float32), stop_gradient=False)
    (x * x).sum().backward()
    assert np.allclose(np.asarray(x._grad), 2.0), "autograd check failed"

    if len(devs) > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mesh = Mesh(np.array(devs), ("d",))
        arr = jax.device_put(np.ones((len(devs) * 2, 4), np.float32),
                             NamedSharding(mesh, P("d")))
        s = float(np.asarray(arr.sum()))
        assert s == len(devs) * 8, "sharded reduction failed"
        log(f"paddle_tpu works on {len(devs)} devices (sharded compute "
            "verified)")
    log("paddle_tpu is installed successfully!")
    return True


def deprecated(update_to="", since="", reason="", level=0):
    """Reference: utils/deprecated.py — decorator that warns on use."""
    import functools
    import warnings

    def wrap(fn):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            msg = f"{fn.__name__} is deprecated since {since or 'now'}"
            if update_to:
                msg += f"; use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return inner
    return wrap


class _UniqueName:
    def __init__(self):
        self._counters = {}

    def generate(self, key="tmp"):
        n = self._counters.get(key, 0)
        self._counters[key] = n + 1
        return f"{key}_{n}"

    @contextlib.contextmanager
    def guard(self, new_generator=None):
        saved = self._counters
        self._counters = {}
        try:
            yield
        finally:
            self._counters = saved


unique_name = _UniqueName()


def try_import(module_name, err_msg=None):
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or
                          f"{module_name} is required but not installed")
