"""paddle.signal — stft / istft.

Reference: python/paddle/signal.py (stft returns [..., n_fft//2+1 (or
n_fft), n_frames] complex; istft inverts with overlap-add and window
normalization). Built on the audio framing helper + paddle.fft (XLA FFT
HLO with the host fallback where the runtime lacks it).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import fft as _fft
from .audio.functional import get_window
from .audio.features import _frame
from .core.dispatch import apply
from .core.tensor import Tensor

__all__ = ["stft", "istft"]


def _prep_window(window, win_length, n_fft, dtype="float32"):
    if window is None:
        w = Tensor(jnp.ones(win_length, dtype))
    elif isinstance(window, str):
        w = get_window(window, win_length, dtype=dtype)
    else:
        w = window if isinstance(window, Tensor) else Tensor(window)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        w = Tensor(jnp.pad(w._data, (lpad, n_fft - win_length - lpad)))
    return w


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """x: [..., T] real (or complex with onesided=False). Returns
    [..., freq, n_frames] complex."""
    hop_length = n_fft // 4 if hop_length is None else hop_length
    win_length = n_fft if win_length is None else win_length
    assert hop_length > 0 and win_length > 0, \
        f"hop_length/win_length must be positive ({hop_length}, {win_length})"
    w = _prep_window(window, win_length, n_fft)
    frames = _frame(x, n_fft, hop_length, center, pad_mode)
    windowed = apply("stft_win", lambda a, ww: a * ww, [frames, w])
    if onesided:
        spec = _fft.rfft(windowed, n=n_fft, axis=-1)
    else:
        spec = _fft.fft(windowed, n=n_fft, axis=-1)
    if normalized:
        spec = apply("stft_norm",
                     lambda s: s * np.float32(1.0 / np.sqrt(n_fft)), [spec])
    return apply("stft_T", lambda s: jnp.swapaxes(s, -1, -2), [spec])


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse of stft: x [..., freq, n_frames] -> [..., T]."""
    hop_length = n_fft // 4 if hop_length is None else hop_length
    win_length = n_fft if win_length is None else win_length
    assert hop_length > 0 and win_length > 0, \
        f"hop_length/win_length must be positive ({hop_length}, {win_length})"
    if onesided and return_complex:
        raise ValueError(
            "onesided=True implies a real signal; return_complex=True is "
            "contradictory (reference paddle.signal.istft raises too)")
    w = _prep_window(window, win_length, n_fft)
    spec = apply("istft_T", lambda s: jnp.swapaxes(s, -1, -2), [x])
    if normalized:
        spec = apply("istft_norm",
                     lambda s: s * np.float32(np.sqrt(n_fft)), [spec])
    if onesided:
        frames = _fft.irfft(spec, n=n_fft, axis=-1)
    else:
        frames = _fft.ifft(spec, n=n_fft, axis=-1)
        if not return_complex:
            frames = apply("istft_real", lambda f: jnp.real(f), [frames])

    def overlap_add(fr, ww):
        n_frames = fr.shape[-2]
        T = n_fft + hop_length * (n_frames - 1)
        fr = fr * ww  # window again for WOLA
        batch = fr.shape[:-2]
        # one scatter-add for all frames (an unrolled python loop emitted
        # ~2 ops per frame — minutes of compile for long signals)
        pos = (jnp.arange(n_frames)[:, None] * hop_length
               + jnp.arange(n_fft)[None, :]).reshape(-1)
        out = jnp.zeros(batch + (T,), fr.dtype) \
            .at[..., pos].add(fr.reshape(batch + (-1,)))
        norm = jnp.zeros((T,), jnp.float32).at[pos].add(
            jnp.tile(ww.astype(jnp.float32) ** 2, n_frames))
        out = out / jnp.maximum(norm, 1e-11)
        if center:
            out = out[..., n_fft // 2:T - n_fft // 2]
        return out

    out = apply("istft_ola", overlap_add, [frames, w])
    if length is not None:
        out = apply("istft_len", lambda o: o[..., :length], [out])
    return out
