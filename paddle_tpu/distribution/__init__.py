"""paddle_tpu.distribution — probability distributions.

Reference: python/paddle/distribution/ (Distribution base, Normal, Uniform,
Categorical, Bernoulli, kl_divergence). Sampling draws from the framework RNG
(core/random.py) so results are deterministic under paddle.seed; log_prob /
entropy go through the op tape and are differentiable.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as _random
from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "kl_divergence", "Beta", "Dirichlet", "Exponential", "Gamma",
           "Geometric", "Gumbel", "Laplace", "LogNormal", "Multinomial",
           "Poisson", "StudentT", "Transform", "AbsTransform",
           "AffineTransform", "ExpTransform", "SigmoidTransform",
           "TanhTransform", "PowerTransform", "ChainTransform",
           "TransformedDistribution", "Independent"]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x, np.float32))


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def probs(self, value):
        from .. import ops
        return ops.exp(self.log_prob(value))

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    """Reference: distribution/normal.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self.scale * self.scale

    def sample(self, shape=(), seed=0):
        key = _random.next_key()
        shape = tuple(shape)
        full = shape + tuple(self.loc.shape)

        def fwd(mu, sigma):
            eps = jax.random.normal(key, full, jnp.float32)
            return mu + sigma * eps
        return apply("normal_sample", fwd, [self.loc, self.scale])

    rsample = sample

    def log_prob(self, value):
        def fwd(v, mu, sigma):
            var = sigma * sigma
            return -((v - mu) ** 2) / (2 * var) - jnp.log(sigma) \
                - 0.5 * math.log(2 * math.pi)
        return apply("normal_log_prob", fwd, [_t(value), self.loc,
                                              self.scale])

    def entropy(self):
        def fwd(sigma):
            return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(sigma)
        return apply("normal_entropy", fwd, [self.scale])


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)

    def sample(self, shape=(), seed=0):
        key = _random.next_key()
        full = tuple(shape) + tuple(self.low.shape)

        def fwd(lo, hi):
            u = jax.random.uniform(key, full, jnp.float32)
            return lo + (hi - lo) * u
        return apply("uniform_sample", fwd, [self.low, self.high])

    rsample = sample

    def log_prob(self, value):
        def fwd(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)
        return apply("uniform_log_prob", fwd, [_t(value), self.low,
                                               self.high])

    def entropy(self):
        def fwd(lo, hi):
            return jnp.log(hi - lo)
        return apply("uniform_entropy", fwd, [self.low, self.high])


class Categorical(Distribution):
    """Reference: distribution/categorical.py — parameterized by logits."""

    def __init__(self, logits, name=None):
        self.logits = _t(logits)

    def sample(self, shape=()):
        key = _random.next_key()
        shape = tuple(shape)

        def fwd(lg):
            return jax.random.categorical(key, lg, shape=shape
                                          + lg.shape[:-1])
        out = apply("categorical_sample", fwd, [self.logits.detach()])
        return out

    def log_prob(self, value):
        def fwd(lg, v):
            logp = jax.nn.log_softmax(lg, axis=-1)
            return jnp.take_along_axis(
                logp, v.astype(jnp.int32)[..., None], axis=-1)[..., 0]
        return apply("categorical_log_prob", fwd, [self.logits, _t(value)])

    def entropy(self):
        def fwd(lg):
            logp = jax.nn.log_softmax(lg, axis=-1)
            return -(jnp.exp(logp) * logp).sum(-1)
        return apply("categorical_entropy", fwd, [self.logits])


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_param = _t(probs)

    def sample(self, shape=()):
        key = _random.next_key()
        full = tuple(shape) + tuple(self.probs_param.shape)

        def fwd(p):
            return jax.random.bernoulli(key, p, full).astype(jnp.float32)
        return apply("bernoulli_sample", fwd, [self.probs_param.detach()])

    def log_prob(self, value):
        def fwd(p, v):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return apply("bernoulli_log_prob", fwd, [self.probs_param, _t(value)])

    def entropy(self):
        def fwd(p):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))
        return apply("bernoulli_entropy", fwd, [self.probs_param])


def kl_divergence(p, q):
    """Reference: distribution/kl.py."""
    if isinstance(p, Normal) and isinstance(q, Normal):
        def fwd(mu1, s1, mu2, s2):
            var1, var2 = s1 * s1, s2 * s2
            return (jnp.log(s2 / s1) + (var1 + (mu1 - mu2) ** 2)
                    / (2 * var2) - 0.5)
        return apply("kl_normal", fwd, [p.loc, p.scale, q.loc, q.scale])
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        def fwd(l1, l2):
            lp = jax.nn.log_softmax(l1, -1)
            lq = jax.nn.log_softmax(l2, -1)
            return (jnp.exp(lp) * (lp - lq)).sum(-1)
        return apply("kl_categorical", fwd, [p.logits, q.logits])
    if isinstance(p, Bernoulli) and isinstance(q, Bernoulli):
        def fwd(p1, p2):
            p1 = jnp.clip(p1, 1e-7, 1 - 1e-7)
            p2 = jnp.clip(p2, 1e-7, 1 - 1e-7)
            return p1 * (jnp.log(p1) - jnp.log(p2)) + (1 - p1) * (
                jnp.log1p(-p1) - jnp.log1p(-p2))
        return apply("kl_bernoulli", fwd, [p.probs_param, q.probs_param])
    if isinstance(p, Uniform) and isinstance(q, Uniform):
        def fwd(lo1, hi1, lo2, hi2):
            return jnp.log((hi2 - lo2) / (hi1 - lo1))
        return apply("kl_uniform", fwd, [p.low, p.high, q.low, q.high])
    from .extra import (Beta, Dirichlet, Exponential, Gamma, Laplace,
                        LogNormal)
    if isinstance(p, Exponential) and isinstance(q, Exponential):
        def fwd(r1, r2):
            return jnp.log(r1 / r2) + r2 / r1 - 1.0
        return apply("kl_exponential", fwd, [p.rate, q.rate])
    if isinstance(p, LogNormal) and isinstance(q, LogNormal):
        # same KL as the underlying Normals (exp is a bijection)
        def fwd(mu1, s1, mu2, s2):
            var1, var2 = s1 * s1, s2 * s2
            return (jnp.log(s2 / s1) + (var1 + (mu1 - mu2) ** 2)
                    / (2 * var2) - 0.5)
        return apply("kl_lognormal", fwd, [p.loc, p.scale, q.loc, q.scale])
    if isinstance(p, Gamma) and isinstance(q, Gamma):
        from jax.scipy.special import digamma, gammaln

        def fwd(a1, r1, a2, r2):
            return ((a1 - a2) * digamma(a1) - gammaln(a1) + gammaln(a2)
                    + a2 * (jnp.log(r1) - jnp.log(r2))
                    + a1 * (r2 - r1) / r1)
        return apply("kl_gamma", fwd,
                     [p.concentration, p.rate, q.concentration, q.rate])
    if isinstance(p, Beta) and isinstance(q, Beta):
        from jax.scipy.special import betaln, digamma

        def fwd(a1, b1, a2, b2):
            return (betaln(a2, b2) - betaln(a1, b1)
                    + (a1 - a2) * digamma(a1) + (b1 - b2) * digamma(b1)
                    + (a2 - a1 + b2 - b1) * digamma(a1 + b1))
        return apply("kl_beta", fwd, [p.alpha, p.beta, q.alpha, q.beta])
    if isinstance(p, Dirichlet) and isinstance(q, Dirichlet):
        from jax.scipy.special import digamma, gammaln

        def fwd(c1, c2):
            s1 = jnp.sum(c1, -1)
            t = (gammaln(s1) - jnp.sum(gammaln(c1), -1)
                 - gammaln(jnp.sum(c2, -1)) + jnp.sum(gammaln(c2), -1))
            return t + jnp.sum(
                (c1 - c2) * (digamma(c1) - digamma(s1)[..., None]), -1)
        return apply("kl_dirichlet", fwd,
                     [p.concentration, q.concentration])
    if isinstance(p, Laplace) and isinstance(q, Laplace):
        def fwd(m1, b1, m2, b2):
            d = jnp.abs(m1 - m2)
            return (jnp.log(b2 / b1) + d / b2
                    + b1 / b2 * jnp.exp(-d / b1) - 1.0)
        return apply("kl_laplace", fwd, [p.loc, p.scale, q.loc, q.scale])
    raise NotImplementedError(
        f"kl_divergence({type(p).__name__}, {type(q).__name__}) "
        "is not registered")


from .extra import (  # noqa: E402,F401
    AbsTransform, AffineTransform, Beta, ChainTransform, Dirichlet,
    Exponential, ExpTransform, Gamma, Geometric, Gumbel, Independent,
    Laplace, LogNormal, Multinomial, Poisson, PowerTransform,
    SigmoidTransform, StudentT, TanhTransform, Transform,
    TransformedDistribution,
)
