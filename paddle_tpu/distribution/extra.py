"""Additional distributions + transforms.

Reference: python/paddle/distribution/{beta,dirichlet,exponential,gamma,
geometric,gumbel,laplace,lognormal,multinomial,poisson,transform,
transformed_distribution}.py. Sampling draws framework RNG keys
(core/random.py) so paddle.seed governs determinism; log_prob/entropy run
through the dispatch tape and are differentiable.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as _random
from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["Beta", "Dirichlet", "Exponential", "Gamma", "Geometric",
           "Gumbel", "Laplace", "LogNormal", "Multinomial", "Poisson",
           "StudentT", "Transform", "AbsTransform", "AffineTransform",
           "ExpTransform", "SigmoidTransform", "TanhTransform",
           "PowerTransform", "ChainTransform", "TransformedDistribution",
           "Independent"]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x, np.float32))


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _key():
    return _random.next_key()


def _op(name, fn, ins):
    return apply(name, fn, [t if isinstance(t, Tensor) else _t(t)
                            for t in ins])


from . import Distribution  # noqa: E402  (base class from the package root)


class Exponential(Distribution):
    """Reference: distribution/exponential.py. rate λ; pdf λ e^{-λx}."""

    def __init__(self, rate):
        self.rate = _t(rate)

    @property
    def mean(self):
        return _op("div", lambda r: 1.0 / r, [self.rate])

    @property
    def variance(self):
        return _op("var", lambda r: 1.0 / (r * r), [self.rate])

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self.rate.shape)
        u = jax.random.uniform(_key(), shape, jnp.float32, 1e-7, 1.0)
        return Tensor(-jnp.log(u) / _arr(self.rate), stop_gradient=True)

    def rsample(self, shape=()):
        """Pathwise/reparameterized: dispatched through the tape so
        gradients flow to the rate."""
        shape = tuple(shape) + tuple(self.rate.shape)
        key = _key()
        return _op("exp_rsample", lambda r: -jnp.log(
            jax.random.uniform(key, shape, jnp.float32, 1e-7, 1.0)) / r,
            [self.rate])

    def log_prob(self, value):
        return _op("exp_lp",
                   lambda r, v: jnp.log(r) - r * v, [self.rate, _t(value)])

    def entropy(self):
        return _op("exp_ent", lambda r: 1.0 - jnp.log(r), [self.rate])


class Gamma(Distribution):
    """Reference: distribution/gamma.py (concentration/rate)."""

    def __init__(self, concentration, rate):
        self.concentration = _t(concentration)
        self.rate = _t(rate)

    @property
    def mean(self):
        return _op("gmean", lambda a, r: a / r,
                   [self.concentration, self.rate])

    @property
    def variance(self):
        return _op("gvar", lambda a, r: a / (r * r),
                   [self.concentration, self.rate])

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self.concentration.shape)
        g = jax.random.gamma(_key(), _arr(self.concentration), shape)
        return Tensor(g / _arr(self.rate), stop_gradient=True)

    def rsample(self, shape=()):
        """jax.random.gamma is differentiable in the concentration
        (implicit reparameterization), so the tape carries pathwise
        gradients to both parameters."""
        shape = tuple(shape) + tuple(self.concentration.shape)
        key = _key()
        return _op("gamma_rsample",
                   lambda a, r: jax.random.gamma(key, a, shape) / r,
                   [self.concentration, self.rate])

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        return _op("glp", lambda a, r, v: a * jnp.log(r)
                   + (a - 1) * jnp.log(v) - r * v - gammaln(a),
                   [self.concentration, self.rate, _t(value)])

    def entropy(self):
        from jax.scipy.special import digamma, gammaln
        return _op("gent", lambda a, r: a - jnp.log(r) + gammaln(a)
                   + (1 - a) * digamma(a),
                   [self.concentration, self.rate])


class Beta(Distribution):
    """Reference: distribution/beta.py."""

    def __init__(self, alpha, beta):
        self.alpha = _t(alpha)
        self.beta = _t(beta)

    @property
    def mean(self):
        return _op("bmean", lambda a, b: a / (a + b),
                   [self.alpha, self.beta])

    @property
    def variance(self):
        return _op("bvar",
                   lambda a, b: a * b / ((a + b) ** 2 * (a + b + 1)),
                   [self.alpha, self.beta])

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self.alpha.shape)
        s = jax.random.beta(_key(), _arr(self.alpha), _arr(self.beta),
                            shape)
        return Tensor(s, stop_gradient=True)

    def rsample(self, shape=()):
        shape = tuple(shape) + tuple(self.alpha.shape)
        key = _key()
        return _op("beta_rsample",
                   lambda a, b: jax.random.beta(key, a, b, shape),
                   [self.alpha, self.beta])

    def log_prob(self, value):
        from jax.scipy.special import betaln
        return _op("blp", lambda a, b, v: (a - 1) * jnp.log(v)
                   + (b - 1) * jnp.log1p(-v) - betaln(a, b),
                   [self.alpha, self.beta, _t(value)])

    def entropy(self):
        from jax.scipy.special import betaln, digamma
        return _op("bent", lambda a, b: betaln(a, b)
                   - (a - 1) * digamma(a) - (b - 1) * digamma(b)
                   + (a + b - 2) * digamma(a + b),
                   [self.alpha, self.beta])


class Dirichlet(Distribution):
    """Reference: distribution/dirichlet.py."""

    def __init__(self, concentration):
        self.concentration = _t(concentration)

    @property
    def mean(self):
        return _op("dmean", lambda c: c / jnp.sum(c, -1, keepdims=True),
                   [self.concentration])

    def sample(self, shape=()):
        s = jax.random.dirichlet(_key(), _arr(self.concentration),
                                 tuple(shape))
        return Tensor(s, stop_gradient=True)

    def rsample(self, shape=()):
        key = _key()
        shp = tuple(shape)
        return _op("dirichlet_rsample",
                   lambda c: jax.random.dirichlet(key, c, shp),
                   [self.concentration])

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        return _op("dlp", lambda c, v: jnp.sum((c - 1) * jnp.log(v), -1)
                   + gammaln(jnp.sum(c, -1)) - jnp.sum(gammaln(c), -1),
                   [self.concentration, _t(value)])

    def entropy(self):
        from jax.scipy.special import digamma, gammaln

        def f(c):
            a0 = jnp.sum(c, -1)
            k = c.shape[-1]
            lnB = jnp.sum(gammaln(c), -1) - gammaln(a0)
            return lnB + (a0 - k) * digamma(a0) \
                - jnp.sum((c - 1) * digamma(c), -1)
        return _op("dent", f, [self.concentration])


class Laplace(Distribution):
    """Reference: distribution/laplace.py."""

    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return _op("lvar", lambda s: 2 * s * s, [self.scale])

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.loc.shape, self.scale.shape))
        s = jax.random.laplace(_key(), shape, jnp.float32)
        return Tensor(_arr(self.loc) + _arr(self.scale) * s,
                      stop_gradient=True)

    def rsample(self, shape=()):
        shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.loc.shape, self.scale.shape))
        key = _key()
        return _op("laplace_rsample", lambda m, s: m + s
                   * jax.random.laplace(key, shape, jnp.float32),
                   [self.loc, self.scale])

    def log_prob(self, value):
        return _op("llp", lambda m, s, v: -jnp.abs(v - m) / s
                   - jnp.log(2 * s), [self.loc, self.scale, _t(value)])

    def entropy(self):
        return _op("lent", lambda s: 1 + jnp.log(2 * s), [self.scale])


class Gumbel(Distribution):
    """Reference: distribution/gumbel.py."""

    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    @property
    def mean(self):
        g = np.float32(np.euler_gamma)
        return _op("gumean", lambda m, s: m + g * s,
                   [self.loc, self.scale])

    @property
    def variance(self):
        c = np.float32(math.pi ** 2 / 6)
        return _op("guvar", lambda s: c * s * s, [self.scale])

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.loc.shape, self.scale.shape))
        s = jax.random.gumbel(_key(), shape, jnp.float32)
        return Tensor(_arr(self.loc) + _arr(self.scale) * s,
                      stop_gradient=True)

    def rsample(self, shape=()):
        shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.loc.shape, self.scale.shape))
        key = _key()
        return _op("gumbel_rsample", lambda m, s: m + s
                   * jax.random.gumbel(key, shape, jnp.float32),
                   [self.loc, self.scale])

    def log_prob(self, value):
        def f(m, s, v):
            z = (v - m) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)
        return _op("gulp", f, [self.loc, self.scale, _t(value)])

    def entropy(self):
        g = np.float32(np.euler_gamma)
        return _op("guent", lambda s: jnp.log(s) + 1 + g, [self.scale])


class Geometric(Distribution):
    """Reference: distribution/geometric.py (k failures before success,
    support {0, 1, ...})."""

    def __init__(self, probs):
        self.probs_param = _t(probs)

    @property
    def mean(self):
        return _op("geomean", lambda p: (1 - p) / p, [self.probs_param])

    @property
    def variance(self):
        return _op("geovar", lambda p: (1 - p) / (p * p),
                   [self.probs_param])

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self.probs_param.shape)
        u = jax.random.uniform(_key(), shape, jnp.float32, 1e-7, 1.0)
        p = _arr(self.probs_param)
        return Tensor(jnp.floor(jnp.log(u) / jnp.log1p(-p)),
                      stop_gradient=True)

    def log_prob(self, value):
        return _op("geolp", lambda p, k: k * jnp.log1p(-p) + jnp.log(p),
                   [self.probs_param, _t(value)])

    def entropy(self):
        def f(p):
            q = 1 - p
            return -(q * jnp.log(q) + p * jnp.log(p)) / p
        return _op("geoent", f, [self.probs_param])


class LogNormal(Distribution):
    """Reference: distribution/lognormal.py."""

    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    @property
    def mean(self):
        return _op("lnmean", lambda m, s: jnp.exp(m + s * s / 2),
                   [self.loc, self.scale])

    @property
    def variance(self):
        return _op("lnvar",
                   lambda m, s: (jnp.exp(s * s) - 1)
                   * jnp.exp(2 * m + s * s), [self.loc, self.scale])

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.loc.shape, self.scale.shape))
        z = jax.random.normal(_key(), shape, jnp.float32)
        return Tensor(jnp.exp(_arr(self.loc) + _arr(self.scale) * z),
                      stop_gradient=True)

    def rsample(self, shape=()):
        shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.loc.shape, self.scale.shape))
        key = _key()
        return _op("lognormal_rsample", lambda m, s: jnp.exp(
            m + s * jax.random.normal(key, shape, jnp.float32)),
            [self.loc, self.scale])

    def log_prob(self, value):
        c = np.float32(0.5 * math.log(2 * math.pi))

        def f(m, s, v):
            lv = jnp.log(v)
            return -((lv - m) ** 2) / (2 * s * s) - jnp.log(s) - lv - c
        return _op("lnlp", f, [self.loc, self.scale, _t(value)])

    def entropy(self):
        c = np.float32(0.5 * math.log(2 * math.pi) + 0.5)
        return _op("lnent", lambda m, s: m + jnp.log(s) + c,
                   [self.loc, self.scale])


class Multinomial(Distribution):
    """Reference: distribution/multinomial.py."""

    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs_param = _t(probs)

    @property
    def mean(self):
        n = self.total_count
        return _op("mnmean", lambda p: n * p, [self.probs_param])

    def sample(self, shape=()):
        p = _arr(self.probs_param)
        shape = tuple(shape)
        # draw total_count iid categoricals with the batch dims right-
        # aligned (jax.random.categorical broadcast rule), then histogram
        idx = jax.random.categorical(
            _key(), jnp.log(p),
            shape=(self.total_count,) + shape + p.shape[:-1])
        counts = jax.nn.one_hot(idx, p.shape[-1]).sum(0)
        return Tensor(counts, stop_gradient=True)

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        def f(p, v):
            return gammaln(jnp.sum(v, -1) + 1) \
                - jnp.sum(gammaln(v + 1), -1) \
                + jnp.sum(v * jnp.log(p), -1)
        return _op("mnlp", f, [self.probs_param, _t(value)])


class Poisson(Distribution):
    """Reference: distribution/poisson.py."""

    def __init__(self, rate):
        self.rate = _t(rate)

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self.rate.shape)
        s = jax.random.poisson(_key(), _arr(self.rate), shape)
        return Tensor(s.astype(jnp.float32), stop_gradient=True)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        return _op("plp", lambda r, k: k * jnp.log(r) - r - gammaln(k + 1),
                   [self.rate, _t(value)])


class StudentT(Distribution):
    """Reference: distribution/student_t.py."""

    def __init__(self, df, loc=0.0, scale=1.0):
        self.df = _t(df)
        self.loc = _t(loc)
        self.scale = _t(scale)

    @property
    def mean(self):
        return self.loc

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.df.shape, self.loc.shape, self.scale.shape))
        s = jax.random.t(_key(), _arr(self.df), shape, jnp.float32)
        return Tensor(_arr(self.loc) + _arr(self.scale) * s,
                      stop_gradient=True)

    def rsample(self, shape=()):
        shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.df.shape, self.loc.shape, self.scale.shape))
        key = _key()
        return _op("studentt_rsample", lambda df, m, s: m + s
                   * jax.random.t(key, df, shape, jnp.float32),
                   [self.df, self.loc, self.scale])

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        def f(df, m, s, v):
            z = (v - m) / s
            return gammaln((df + 1) / 2) - gammaln(df / 2) \
                - 0.5 * jnp.log(df * np.float32(math.pi)) - jnp.log(s) \
                - (df + 1) / 2 * jnp.log1p(z * z / df)
        return _op("stlp", f, [self.df, self.loc, self.scale, _t(value)])


# ---------------- transforms ----------------
class Transform:
    """Reference: distribution/transform.py Transform base."""

    def forward(self, x):
        return _op(f"{type(self).__name__}_fwd", self._forward, [_t(x)])

    def inverse(self, y):
        return _op(f"{type(self).__name__}_inv", self._inverse, [_t(y)])

    def forward_log_det_jacobian(self, x):
        return _op(f"{type(self).__name__}_fldj", self._fldj, [_t(x)])

    def inverse_log_det_jacobian(self, y):
        inv = self.inverse(y)
        fldj = self.forward_log_det_jacobian(inv)
        from .. import ops
        return ops.neg(fldj)

    def __call__(self, x):
        return self.forward(x)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class AbsTransform(Transform):
    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _fldj(self, x):
        return jnp.zeros_like(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def _forward(self, x):
        return _arr(self.loc) + _arr(self.scale) * x

    def _inverse(self, y):
        return (y - _arr(self.loc)) / _arr(self.scale)

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(_arr(self.scale))),
                                x.shape)


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _t(power)

    def _forward(self, x):
        return jnp.power(x, _arr(self.power))

    def _inverse(self, y):
        return jnp.power(y, 1.0 / _arr(self.power))

    def _fldj(self, x):
        p = _arr(self.power)
        return jnp.log(jnp.abs(p * jnp.power(x, p - 1)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        return 2.0 * (jnp.log(jnp.float32(2.0)) - x
                      - jax.nn.softplus(-2.0 * x))


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        from .. import ops
        total = None
        for t in self.transforms:
            ld = t.forward_log_det_jacobian(x)
            total = ld if total is None else ops.add(total, ld)
            x = t.forward(x)
        return total


class TransformedDistribution(Distribution):
    """Reference: distribution/transformed_distribution.py."""

    def __init__(self, base, transforms):
        self.base = base
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.transform = ChainTransform(transforms) \
            if len(transforms) != 1 else transforms[0]

    def sample(self, shape=()):
        return self.transform.forward(self.base.sample(shape))

    def rsample(self, shape=()):
        return self.transform.forward(self.base.rsample(shape))

    def log_prob(self, value):
        from .. import ops
        x = self.transform.inverse(value)
        base_lp = self.base.log_prob(x)
        ildj = self.transform.forward_log_det_jacobian(x)
        return ops.subtract(base_lp, ildj)


class Independent(Distribution):
    """Reference: distribution/independent.py — reinterprets batch dims as
    event dims (log_prob sums over them)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        from .. import ops
        for _ in range(self.rank):
            lp = ops.sum(lp, axis=-1)
        return lp

    def entropy(self):
        ent = self.base.entropy()
        from .. import ops
        for _ in range(self.rank):
            ent = ops.sum(ent, axis=-1)
        return ent
