"""paddle_tpu.autograd — public autograd namespace.

Reference: ``python/paddle/autograd`` (paddle.grad, PyLayer, no_grad, hooks).
The engine lives in ``paddle_tpu.core.autograd`` (tape over jax.vjp); this
package adds the user-facing PyLayer custom-op API.
"""
from ..core.autograd import (  # noqa: F401
    backward, enable_grad, grad, is_grad_enabled, no_grad, set_grad_enabled,
)
from .functional import hessian, jacobian, jvp, vjp  # noqa: F401
from .py_layer import PyLayer, PyLayerContext  # noqa: F401

__all__ = [
    "backward", "grad", "no_grad", "enable_grad", "is_grad_enabled",
    "set_grad_enabled", "PyLayer", "PyLayerContext", "jacobian", "hessian",
    "vjp", "jvp",
]
