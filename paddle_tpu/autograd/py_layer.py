"""PyLayer — user-defined differentiable ops with Python forward/backward.

Reference: ``python/paddle/autograd/py_layer.py`` (class PyLayer + CPyLayerContext)
over the eager engine's PyLayer grad node (``paddle/fluid/eager/pylayer/``).
TPU-native design: PyLayer.apply runs the user forward under ``no_grad`` and
records a single TapeNode whose pullback invokes the user backward; under
``create_graph=True`` the user backward runs grad-enabled so its ops are taped,
giving double-grad through PyLayer for free.
"""
from __future__ import annotations

from ..core import autograd as engine
from ..core.dispatch import _is_diff
from ..core.dtype import is_floating
from ..core.tensor import Tensor


class PyLayerContext:
    """Context passed to forward/backward (reference: PyLayerContext).

    ``save_for_backward`` stores tensors for the backward pass;
    ``saved_tensor`` returns them.
    """

    def __init__(self):
        self.container = ()
        self._non_differentiable = set()
        self._materialize_grads = True

    def save_for_backward(self, *tensors):
        self.container = tensors

    def saved_tensor(self):
        return self.container

    def mark_non_differentiable(self, *tensors):
        self._non_differentiable |= {id(t) for t in tensors}

    def set_materialize_grads(self, value: bool):
        self._materialize_grads = bool(value)

    def mark_not_inplace(self, *args):  # compatibility no-op (functional arrays)
        pass


class PyLayer:
    """Base class for custom differentiable operations.

    Usage mirrors the reference (python/paddle/autograd/py_layer.py)::

        class Cube(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x * x

            @staticmethod
            def backward(ctx, dy):
                x, = ctx.saved_tensor()
                return 3 * x * x * dy

        y = Cube.apply(x)
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError(
            "PyLayer subclasses must implement a forward staticmethod")

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError(
            "PyLayer subclasses must implement a backward staticmethod")

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_args = [a for a in args if isinstance(a, Tensor)]
        diff_inputs = [t for t in tensor_args if _is_diff(t)] \
            if engine.is_grad_enabled() else []

        with engine.no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)

        single = not isinstance(outputs, (tuple, list))
        out_list = [outputs] if single else list(outputs)
        if not diff_inputs:
            return outputs

        # outputs eligible for taping: floating tensors not marked non-diff
        taped = [o for o in out_list
                 if isinstance(o, Tensor) and id(o) not in ctx._non_differentiable
                 and is_floating(o.dtype)]
        if not taped:
            return outputs

        def _select(res):
            """Map user backward results onto the diff inputs."""
            res = list(res) if isinstance(res, (tuple, list)) else [res]
            if len(res) == len(diff_inputs):
                pairs = zip(diff_inputs, res)
            elif len(res) == len(tensor_args):
                pairs = ((t, g) for t, g in zip(tensor_args, res)
                         if any(t is d for d in diff_inputs))
            else:
                raise RuntimeError(
                    f"{cls.__name__}.backward returned {len(res)} gradients but "
                    f"forward has {len(tensor_args)} tensor inputs "
                    f"({len(diff_inputs)} differentiable)")
            return pairs

        def raw_vjp(cts):
            cts = cts if isinstance(cts, tuple) else (cts,)
            ct_tensors = [Tensor(c, stop_gradient=True) for c in cts]
            with engine.no_grad():
                res = cls.backward(ctx, *ct_tensors)
            out = [None] * len(diff_inputs)
            for i, (t, g) in enumerate(_select(res)):
                out[i] = g._data if isinstance(g, Tensor) else g
            return tuple(out)

        def tensor_vjp(ct_tensors):
            res = cls.backward(ctx, *ct_tensors)
            out = [None] * len(diff_inputs)
            for i, (t, g) in enumerate(_select(res)):
                out[i] = g
            return out

        engine.record_op(f"py_layer_{cls.__name__}", diff_inputs, raw_vjp,
                         taped, tensor_vjp=tensor_vjp)
        return outputs


def once_differentiable(backward_fn):
    """Decorator marking a backward as non-re-differentiable (compat shim)."""
    return staticmethod(backward_fn) if not isinstance(
        backward_fn, staticmethod) else backward_fn
