"""Functional higher-order autograd: jacobian / hessian / vjp / jvp.

Reference: python/paddle/incubate/autograd/functional.py (paddle.incubate.
autograd.Jacobian/Hessian) and paddle.autograd.jacobian. TPU-native: the
user function (eager Tensor code) is staged into a pure array function —
the op tape records through tracers — and jax.jacrev/jacfwd compute the
derivative matrices in one compiled program each.
"""
from __future__ import annotations

import jax

from ..core.tensor import Tensor

__all__ = ["jacobian", "hessian", "vjp", "jvp"]


def _purify(func, n_in):
    def pure(*arrs):
        ts = [Tensor(a, stop_gradient=False) for a in arrs]
        out = func(*ts)
        if isinstance(out, (tuple, list)):
            return tuple(o._data for o in out)
        return out._data
    return pure


def _unpack(xs):
    single = isinstance(xs, Tensor)
    xs_list = [xs] if single else list(xs)
    return single, xs_list, [t._data for t in xs_list]


def jacobian(func, xs, create_graph=False, allow_unused=False):
    """d func / d xs as full matrices (reference:
    incubate/autograd/functional.py Jacobian). Reverse-mode."""
    if create_graph:
        raise NotImplementedError(
            "jacobian(create_graph=True) is not supported here: the result "
            "is computed in one staged jax program and is not on the eager "
            "tape. Chain paddle.grad(..., create_graph=True) for "
            "differentiable derivatives.")
    single, xs_list, arrs = _unpack(xs)
    pure = _purify(func, len(xs_list))
    jac = jax.jacrev(pure, argnums=tuple(range(len(arrs))))(*arrs)
    if not isinstance(jac, tuple):
        jac = (jac,)
    outs = [Tensor(j, stop_gradient=True) for j in jac]
    return outs[0] if single else outs


def hessian(func, xs, create_graph=False, allow_unused=False):
    """d² func / d xs² (reference: Hessian). func must return a scalar."""
    if create_graph:
        raise NotImplementedError(
            "hessian(create_graph=True) is not supported here — chain "
            "paddle.grad(..., create_graph=True) instead.")
    single, xs_list, arrs = _unpack(xs)
    pure = _purify(func, len(xs_list))
    hess = jax.hessian(pure, argnums=tuple(range(len(arrs))))(*arrs)
    if single:
        h = hess[0][0] if isinstance(hess, tuple) else hess
        return Tensor(h, stop_gradient=True)
    return [[Tensor(hess[i][j], stop_gradient=True)
             for j in range(len(arrs))] for i in range(len(arrs))]


def vjp(func, xs, v=None):
    """(outputs, vjp_result) (reference: paddle.incubate.autograd.vjp)."""
    single, xs_list, arrs = _unpack(xs)
    pure = _purify(func, len(xs_list))
    out, pullback = jax.vjp(pure, *arrs)
    if v is None:
        import jax.numpy as jnp
        cot = jnp.ones_like(out) if not isinstance(out, tuple) else \
            tuple(jnp.ones_like(o) for o in out)
    else:
        cot = v._data if isinstance(v, Tensor) else \
            tuple(t._data for t in v)
    grads = pullback(cot)
    outs = Tensor(out) if not isinstance(out, tuple) else \
        tuple(Tensor(o) for o in out)
    gs = [Tensor(g) for g in grads]
    return outs, (gs[0] if single else gs)


def jvp(func, xs, v=None):
    """(outputs, jvp_result) — forward mode (reference: jvp)."""
    import jax.numpy as jnp
    single, xs_list, arrs = _unpack(xs)
    pure = _purify(func, len(xs_list))
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrs)
    else:
        vs = [v] if isinstance(v, Tensor) else list(v)
        tangents = tuple(t._data for t in vs)
    out, tangent_out = jax.jvp(pure, tuple(arrs), tangents)
    outs = Tensor(out) if not isinstance(out, tuple) else \
        tuple(Tensor(o) for o in out)
    touts = Tensor(tangent_out) if not isinstance(tangent_out, tuple) else \
        tuple(Tensor(t) for t in tangent_out)
    return outs, touts
