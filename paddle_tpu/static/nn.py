"""paddle.static.nn — control flow + static-graph layer helpers.

Reference: python/paddle/static/nn/__init__.py (control_flow.py,
common.py). The control-flow ops lower onto lax.cond/lax.while_loop (see
jit/control_flow.py); fc/embedding/batch_norm map onto the dygraph layers.
"""
from __future__ import annotations

from ..jit.control_flow import (  # noqa: F401
    case, cond, scan_loop, switch_case, while_loop,
)

__all__ = ["cond", "while_loop", "case", "switch_case", "scan_loop"]
