"""paddle.static.nn — control flow + static-graph layer helpers.

Reference: python/paddle/static/nn/__init__.py (control_flow.py,
common.py). The control-flow ops lower onto lax.cond/lax.while_loop (see
jit/control_flow.py); fc/embedding/batch_norm map onto the dygraph layers.
"""
from __future__ import annotations

from ..jit.control_flow import (  # noqa: F401
    case, cond, scan_loop, switch_case, while_loop,
)

__all__ = ["cond", "while_loop", "case", "switch_case", "scan_loop",
           "fc", "embedding"]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Reference: paddle.static.nn.fc (static/nn/common.py:28) — a fully
    connected layer on a static Variable; parameters are created (and
    initialised) immediately, the matmul records into the Program."""
    from .. import nn as _nn
    in_features = 1
    for s in x.shape[num_flatten_dims:]:
        in_features *= int(s)
    lin = _nn.Linear(in_features, size, weight_attr=weight_attr,
                     bias_attr=bias_attr)
    h = x
    if len(x.shape) > num_flatten_dims + 1:
        import paddle_tpu as _p
        h = _p.reshape(h, [s if s is not None else -1
                           for s in x.shape[:num_flatten_dims]]
                       + [in_features])
    out = lin(h)
    if activation is not None:
        from ..nn import functional as _F
        out = getattr(_F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    """Reference: paddle.static.nn.embedding."""
    from .. import nn as _nn
    emb = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                        weight_attr=param_attr)
    return emb(input)
