"""paddle_tpu.static — compatibility shim over the jit compile story.

Reference: python/paddle/static. The static-graph Program/Executor machinery
is replaced by trace-to-HLO (SURVEY §7: layers 7b/7c/7d collapse into
jit.to_static); this namespace keeps the commonly used entry points working
on top of it: InputSpec and save/load_inference_model map onto the jax.export
AOT path.
"""
from __future__ import annotations

from ..jit.api import InputSpec  # noqa: F401
from ..jit.save_load import load as _jit_load
from ..jit.save_load import save as _jit_save
from . import nn  # noqa: F401

__all__ = ["InputSpec", "nn", "save_inference_model",
           "load_inference_model"]


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Reference: static/io.py save_inference_model. `fetch_vars` must carry
    the layer via `.layer` or kwargs['layer'] (the dygraph-first rebuild has
    no global default Program to capture)."""
    layer = kwargs.get("layer")
    if layer is None:
        raise ValueError(
            "paddle_tpu.static.save_inference_model requires layer=<Layer>: "
            "the static Program is replaced by tracing a Layer "
            "(use paddle_tpu.jit.save directly for the native API)")
    specs = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    _jit_save(layer, path_prefix, input_spec=list(specs))


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Reference: static/io.py load_inference_model → TranslatedLayer."""
    return _jit_load(path_prefix)


def default_main_program():
    raise NotImplementedError(
        "paddle_tpu is dygraph-first: there is no global static Program. "
        "Use jit.to_static to compile functions/Layers (SURVEY §7).")


default_startup_program = default_main_program
