"""paddle_tpu.static — compatibility shim over the jit compile story.

Reference: python/paddle/static. The static-graph Program/Executor machinery
is replaced by trace-to-HLO (SURVEY §7: layers 7b/7c/7d collapse into
jit.to_static); this namespace keeps the commonly used entry points working
on top of it: InputSpec and save/load_inference_model map onto the jax.export
AOT path.
"""
from __future__ import annotations

from ..jit.api import InputSpec  # noqa: F401
from ..jit.save_load import load as _jit_load
from ..jit.save_load import save as _jit_save
from . import nn  # noqa: F401
from .program import (  # noqa: F401
    Executor, Program, Variable, data, default_main_program,
    default_startup_program, global_scope, program_guard, scope_guard,
)

__all__ = ["InputSpec", "nn", "save_inference_model",
           "load_inference_model", "Program", "Variable", "Executor",
           "data", "program_guard", "default_main_program",
           "default_startup_program", "global_scope", "scope_guard"]


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Reference: static/io.py save_inference_model — reference signature:
    feed/fetch are static Variables of the recorded Program; the DAG is
    traced into a StableHLO AOT artifact (dynamic batch dims export with
    batch=1; pass layer=<Layer> for the dygraph-native path)."""
    layer = kwargs.get("layer")
    if layer is not None:
        specs = feed_vars if isinstance(feed_vars, (list, tuple)) \
            else [feed_vars]
        return _jit_save(layer, path_prefix, input_spec=list(specs))

    from ..core.tensor import Parameter
    from ..nn import Layer
    from .program import Variable, _eval, disable_static_mode, \
        enable_static_mode, in_static_mode

    feeds = list(feed_vars) if isinstance(feed_vars, (list, tuple)) \
        else [feed_vars]
    fetches = list(fetch_vars) if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    if not all(isinstance(v, Variable) for v in feeds + fetches):
        raise TypeError(
            "save_inference_model expects static Variables (from "
            "paddle.static.data / recorded ops), or layer=<Layer>")
    prog = fetches[0]._program
    params = prog.all_parameters()

    class _ProgramModule(Layer):
        def __init__(self):
            super().__init__()
            for i, p in enumerate(params):
                self.add_parameter(f"p{i}", p if isinstance(p, Parameter)
                                   else Parameter(p._data))

        def forward(self, *args):
            was = in_static_mode()
            disable_static_mode()
            try:
                env = {id(v): a for v, a in zip(feeds, args)}
                outs = [_eval(f, env) for f in fetches]
                return outs[0] if len(outs) == 1 else tuple(outs)
            finally:
                if was:
                    enable_static_mode()

    specs = [InputSpec([1 if d is None else int(d) for d in v.shape],
                       v.dtype) for v in feeds]
    _jit_save(_ProgramModule(), path_prefix, input_spec=specs)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Reference: static/io.py load_inference_model → TranslatedLayer."""
    return _jit_load(path_prefix)


