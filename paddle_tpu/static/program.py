"""Static-graph Program/Executor compatibility layer.

Reference: python/paddle/base/framework.py (Program:6174, program_guard),
base/executor.py:1608 (Executor.run feed/fetch),
static/input.py data(). TPU-native collapse: a Program is a lazily
recorded op DAG — under ``paddle.enable_static()`` every dispatch
(`core/dispatch.apply`) on a static Variable appends a node instead of
executing, and ``Executor.run(feed, fetch_list)`` evaluates the DAG with
the eager tape live (so ``optimizer.minimize`` replays backward + update),
op-dispatching onto XLA. Parameters are initialised at creation, so the
startup program is a no-op run (reference semantics preserved: after
``exe.run(startup_program)`` params are live).
"""
from __future__ import annotations

import contextlib

import numpy as np

import jax.numpy as jnp

from ..core import dispatch as _dispatch
from ..core.dtype import convert_dtype
from ..core.tensor import Parameter, Tensor

__all__ = ["Program", "Variable", "program_guard", "data",
           "default_main_program", "default_startup_program", "Executor",
           "enable_static_mode", "disable_static_mode", "in_static_mode",
           "global_scope", "scope_guard"]

_static_mode = [False]


def in_static_mode():
    return _static_mode[0]


def enable_static_mode():
    _static_mode[0] = True
    _dispatch._static_graph_hook = _maybe_record


def disable_static_mode():
    _static_mode[0] = False
    _dispatch._static_graph_hook = None


class Variable(Tensor):
    """A symbolic node in a Program (reference: framework.py Variable).
    Holds no data; ``shape`` may contain None (batch) dims."""

    def __init__(self, program, shape, dtype, name, op=None, ins=None,
                 nout=1, out_idx=0, is_feed=False):
        # deliberately NOT calling Tensor.__init__ — no data exists
        self._data = None
        self._grad = None
        self._grad_fn = None
        self.stop_gradient = True
        self.name = name
        self.persistable = False
        self._program = program
        self._declared_shape = list(shape)
        self._declared_dtype = convert_dtype(dtype) or jnp.float32
        self._op = op            # (op_name, fwd, nout) or None for feeds
        self._ins = ins or []
        self._nout = nout
        self._out_idx = out_idx
        self._is_feed = is_feed

    @property
    def shape(self):
        return list(self._declared_shape)

    @property
    def dtype(self):
        return self._declared_dtype

    @property
    def ndim(self):
        return len(self._declared_shape)

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={self._declared_shape}, "
                f"dtype={self._declared_dtype})")


class Program:
    """Reference: base/framework.py Program — here the recorded DAG plus
    the parameters and optimizer steps it reaches."""

    _counter = [0]

    def __init__(self):
        Program._counter[0] += 1
        self.id = Program._counter[0]
        self.feeds: dict = {}          # name -> Variable
        self.vars: list = []
        self.minimize_ops: list = []   # (optimizer, loss_variable)
        self.random_seed = None

    def _new_name(self, base):
        return f"{base}_{self.id}_{len(self.vars)}"

    def global_block(self):
        return self

    def all_parameters(self):
        seen, out = set(), []

        def walk(v):
            if isinstance(v, Variable):
                for i in v._ins:
                    walk(i)
            elif isinstance(v, Parameter) and id(v) not in seen:
                seen.add(id(v))
                out.append(v)
            elif isinstance(v, Tensor):
                pass
        for v in self.vars:
            for i in v._ins:
                walk(i)
        for _, loss in self.minimize_ops:
            walk(loss)
        return out

    def clone(self, for_test=False):
        import copy
        p = copy.copy(self)
        if for_test:
            p = copy.copy(self)
            p.minimize_ops = []
        return p

    def __repr__(self):
        return (f"Program(id={self.id}, vars={len(self.vars)}, "
                f"feeds={sorted(self.feeds)})")


_default_main = Program()
_default_startup = Program()


def default_main_program():
    """Reference: paddle.static.default_main_program."""
    return _default_main


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """Reference: paddle.static.program_guard."""
    global _default_main, _default_startup
    prev_m, prev_s = _default_main, _default_startup
    _default_main = main_program
    if startup_program is not None:
        _default_startup = startup_program
    try:
        yield
    finally:
        _default_main, _default_startup = prev_m, prev_s


def data(name, shape, dtype="float32", lod_level=0):
    """Reference: paddle.static.data (static/input.py)."""
    prog = _default_main
    v = Variable(prog, shape, dtype, name, is_feed=True)
    prog.feeds[name] = v
    prog.vars.append(v)
    return v


def _maybe_record(name, fwd, inputs, nout, has_aux):
    """dispatch hook: when any input is a static Variable, record a DAG
    node instead of executing. Returns None to fall through to eager."""
    if not any(isinstance(t, Variable) for t in inputs):
        return None
    if has_aux:
        raise NotImplementedError(
            f"op '{name}' with aux outputs is not supported in static "
            "graph recording yet; run in dygraph mode")
    prog = None
    for t in inputs:
        if isinstance(t, Variable):
            prog = t._program
            break
    # infer output shapes/dtypes by abstract evaluation
    import jax

    def shaped(t):
        if isinstance(t, Variable):
            shp = [1 if s is None else s for s in t._declared_shape]
            return jax.ShapeDtypeStruct(tuple(shp), t._declared_dtype)
        if isinstance(t, Tensor):
            return jax.ShapeDtypeStruct(tuple(t._data.shape),
                                        t._data.dtype)
        return t

    try:
        out_aval = jax.eval_shape(fwd, *[shaped(t) for t in inputs])
    except Exception as e:
        raise RuntimeError(
            f"static-graph shape inference failed for op '{name}': {e}")
    avals = out_aval if isinstance(out_aval, tuple) else (out_aval,)
    outs = []
    batch_dims = {i for t in inputs if isinstance(t, Variable)
                  for i, s in enumerate(t._declared_shape) if s is None}
    op_rec = (name, fwd, nout)     # shared: siblings compare by identity
    ins_rec = list(inputs)
    for i, av in enumerate(avals):
        shp = list(av.shape)
        # propagate the None batch dim when it survives at dim 0
        if 0 in batch_dims and shp and any(
                isinstance(t, Variable) and t._declared_shape
                and t._declared_shape[0] is None
                and shp[0] == 1 for t in inputs):
            shp[0] = None
        v = Variable(prog, shp, av.dtype, prog._new_name(name),
                     op=op_rec, ins=ins_rec, nout=len(avals), out_idx=i)
        prog.vars.append(v)
        outs.append(v)
    return outs[0] if len(outs) == 1 else tuple(outs)


class _Scope:
    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, None)

    def find_var(self, name):
        return self._vars.get(name)


_scope = _Scope()


def global_scope():
    return _scope


@contextlib.contextmanager
def scope_guard(scope):
    global _scope
    prev = _scope
    _scope = scope
    try:
        yield
    finally:
        _scope = prev


class Executor:
    """Reference: base/executor.py:1608. ``run`` binds feeds, evaluates
    the DAG with the autograd tape live, replays recorded minimize ops
    (backward + optimizer update), and returns fetched numpy arrays."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, **kwargs):
        prog = program if program is not None else _default_main
        if prog is _default_startup or (isinstance(prog, Program)
                                        and not prog.vars
                                        and not prog.minimize_ops):
            return []  # startup: params initialised at creation
        feed = feed or {}
        env: dict = {}
        for name, arr in feed.items():
            if name not in prog.feeds:
                raise KeyError(f"feed '{name}' is not a data() var of "
                               f"{prog}")
            env[id(prog.feeds[name])] = Tensor(jnp.asarray(arr))

        was_static = in_static_mode()
        disable_static_mode()  # evaluation itself runs eagerly
        try:
            for opt, loss in prog.minimize_ops:
                if not opt._parameter_list:
                    # reference: parameters default to the program's
                    # trainable vars. Extend IN PLACE — _param_groups[0]
                    # aliases this list (optimizer.py ctor).
                    found = prog.all_parameters()
                    opt._parameter_list.extend(found)
                    opt._pid_to_param.update(
                        {id(p): p for p in found})
                loss_t = _eval(loss, env)
                loss_t.backward()
                opt.step()
                opt.clear_grad()
            results = []
            for f in (fetch_list or []):
                t = _eval(f, env) if isinstance(f, Variable) else f
                results.append(np.asarray(t._data) if return_numpy else t)
            return results
        finally:
            if was_static:
                enable_static_mode()

    def close(self):
        return None


def _eval(v, env):
    """Evaluate a Variable against bound feeds (memoized per run)."""
    if not isinstance(v, Variable):
        return v
    if id(v) in env:
        return env[id(v)]
    if v._is_feed:
        raise RuntimeError(
            f"data variable '{v.name}' was not fed (feed={{...}})")
    name, fwd, nout = v._op
    from ..core.dispatch import apply
    ins = [_eval(i, env) if isinstance(i, Variable) else i for i in v._ins]
    out = apply(name, fwd, ins, nout=v._nout)
    outs = out if isinstance(out, tuple) else (out,)
    # cache every sibling output of this node
    sibs = [s for s in v._program.vars
            if isinstance(s, Variable) and s._op is not None
            and s._op is v._op and s._ins is v._ins]
    for s in sibs:
        env[id(s)] = outs[s._out_idx]
    env[id(v)] = outs[v._out_idx]
    return env[id(v)]
