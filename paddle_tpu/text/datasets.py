"""Text datasets (reference: python/paddle/text/datasets/{imdb,uci_housing,
conll05,movielens,...}.py).

No network egress here, so ``download=True`` raises with instructions; the
loaders read the standard on-disk formats (IMDB aclImdb tar layout, UCI
housing whitespace table, tokenized text files).
"""
from __future__ import annotations

import os
import re
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "UCIHousing", "Conll05st", "Movielens",
           "Imikolov", "WMT14", "WMT16"]


def _no_download(name, url):
    raise RuntimeError(
        f"{name}: automatic download is unavailable (no network egress); "
        f"fetch {url} elsewhere and pass data_file=<local path>")


class UCIHousing(Dataset):
    """Boston housing regression (reference: text/datasets/uci_housing.py).
    data_file: the whitespace-separated 'housing.data' table (506 x 14)."""

    FEATURE_DIM = 13
    URL = "http://paddlemodels.bj.bcebos.com/uci_housing/housing.data"

    def __init__(self, data_file=None, mode="train", download=False):
        if data_file is None:
            _no_download("UCIHousing", self.URL)
        raw = np.loadtxt(data_file, dtype=np.float32)
        assert raw.shape[1] == 14, f"expected 14 columns, got {raw.shape}"
        # reference split/normalization: global feature scaling, 80/20
        maxs, mins = raw.max(axis=0), raw.min(axis=0)
        avgs = raw.mean(axis=0)
        feat = (raw[:, :-1] - avgs[:-1]) / (maxs[:-1] - mins[:-1] + 1e-8)
        n_train = int(raw.shape[0] * 0.8)
        if mode == "train":
            self.data = feat[:n_train]
            self.label = raw[:n_train, -1:]
        else:
            self.data = feat[n_train:]
            self.label = raw[n_train:, -1:]

    def __getitem__(self, idx):
        return self.data[idx], self.label[idx]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """IMDB sentiment classification (reference: text/datasets/imdb.py).
    data_file: the aclImdb_v1.tar.gz archive."""

    URL = "https://ai.stanford.edu/~amaas/data/sentiment/aclImdb_v1.tar.gz"

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=False):
        if data_file is None:
            _no_download("Imdb", self.URL)
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        # the vocabulary always comes from the TRAIN split (reference
        # imdb.py builds word_idx from train), so train/test instances
        # agree on token ids
        vocab_pat = re.compile(r"aclImdb/train/(pos|neg)/.*\.txt$")
        tok = re.compile(r"[A-Za-z0-9']+")
        docs, labels = [], []
        freq: dict = {}
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                in_vocab = vocab_pat.match(member.name)
                m = pat.match(member.name)
                if not (m or in_vocab):
                    continue
                text = tf.extractfile(member).read().decode(
                    "utf-8", "ignore").lower()
                words = tok.findall(text)
                if m:
                    docs.append(words)
                    labels.append(0 if m.group(1) == "pos" else 1)
                if in_vocab:
                    for w in words:
                        freq[w] = freq.get(w, 0) + 1
        kept = [w for w, c in sorted(freq.items(),
                                     key=lambda kv: (-kv[1], kv[0]))
                if c >= cutoff]
        self.word_idx = {w: i for i, w in enumerate(kept)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.array([self.word_idx.get(w, unk) for w in d],
                              np.int64) for d in docs]
        self.labels = np.array(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Conll05st(Dataset):
    """CoNLL-2005 SRL test split (reference: text/datasets/conll05.py).

    data_file: the conll05st-tests.tar.gz archive (words/props .gz members)
    or a directory holding ``test.wsj.words``/``test.wsj.props`` text files.
    Each sample is the reference 9-tuple:
    (word_idx, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_idx, mark,
    label_idx) — one sample per (sentence, predicate) pair, labels
    bracket-decoded to B-/I-/O tags.
    """

    URL = "http://www.cs.upc.edu/~srlconll/conll05st-tests.tar.gz"
    UNK_IDX = 0
    _WORDS = "conll05st-release/test.wsj/words/test.wsj.words.gz"
    _PROPS = "conll05st-release/test.wsj/props/test.wsj.props.gz"

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, emb_file=None,
                 download=False):
        if data_file is None or word_dict_file is None or \
                verb_dict_file is None or target_dict_file is None:
            _no_download("Conll05st", self.URL)
        self.word_dict = self._read_dict(word_dict_file)
        self.predicate_dict = self._read_dict(verb_dict_file)
        self.label_dict = self._read_label_dict(target_dict_file)
        self._emb_file = emb_file
        words, props = self._read_streams(data_file)
        self.sentences, self.predicates, self.labels = \
            self._expand(words, props)

    # -- file plumbing --
    @staticmethod
    def _read_dict(path):
        with open(path) as f:
            return {line.strip(): i for i, line in enumerate(f)}

    @staticmethod
    def _read_label_dict(path):
        """Tags listed as B-/I- lines; index pairs per tag, 'O' last
        (reference semantics: _load_label_dict)."""
        tags = set()
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line[:2] in ("B-", "I-"):
                    tags.add(line[2:])
        d = {}
        for tag in tags:           # reference iterates the set directly
            d["B-" + tag] = len(d)
            d["I-" + tag] = len(d)
        d["O"] = len(d)
        return d

    def _read_streams(self, data_file):
        import gzip
        import io
        if os.path.isdir(data_file):
            wp = os.path.join(data_file, "test.wsj.words")
            pp = os.path.join(data_file, "test.wsj.props")
            return (open(wp).read().splitlines(),
                    open(pp).read().splitlines())
        with tarfile.open(data_file) as tf:
            wz = gzip.decompress(tf.extractfile(self._WORDS).read())
            pz = gzip.decompress(tf.extractfile(self._PROPS).read())
        return (io.StringIO(wz.decode()).read().splitlines(),
                io.StringIO(pz.decode()).read().splitlines())

    # -- propbank bracket decoding --
    @staticmethod
    def _decode_props(col):
        """One predicate column of '(A0*', '*', '*)' chunks -> BIO tags."""
        seq, tag, inside = [], "O", False
        for tok in col:
            if tok == "*":
                seq.append("I-" + tag if inside else "O")
            elif tok == "*)":
                seq.append("I-" + tag)
                inside = False
            elif "(" in tok:
                tag = tok[1:tok.index("*")]
                seq.append("B-" + tag)
                inside = ")" not in tok
            else:
                raise ValueError(f"unexpected props token {tok!r}")
        return seq

    def _expand(self, word_lines, prop_lines):
        sentences, predicates, labels = [], [], []
        sent, cols = [], []

        def flush():
            if not cols:
                return
            verbs = [v for v in (r[0] for r in cols) if v != "-"]
            n_pred = len(cols[0]) - 1
            for k in range(n_pred):
                col = [r[k + 1] for r in cols]
                sentences.append(list(sent))
                predicates.append(verbs[k])
                labels.append(self._decode_props(col))
            sent.clear()
            cols.clear()

        for wline, pline in zip(word_lines, prop_lines):
            w = wline.strip()
            parts = pline.strip().split()
            if not parts:              # sentence boundary
                flush()
                continue
            sent.append(w)
            cols.append(parts)
        flush()                        # EOF without trailing blank line
        return sentences, predicates, labels

    def get_dict(self):
        """Reference API: (word_dict, verb_dict, label_dict)."""
        return self.word_dict, self.predicate_dict, self.label_dict

    def get_embedding(self):
        if self._emb_file is None:
            _no_download("Conll05st embedding", self.URL)
        return np.loadtxt(self._emb_file, dtype=np.float32)

    def __getitem__(self, idx):
        sent = self.sentences[idx]
        labels = self.labels[idx]
        n = len(sent)
        v = labels.index("B-V")
        mark = np.zeros(n, np.int64)
        ctx = {}
        for off, name, fallback in [(-2, "ctx_n2", "bos"),
                                    (-1, "ctx_n1", "bos"),
                                    (0, "ctx_0", None),
                                    (1, "ctx_p1", "eos"),
                                    (2, "ctx_p2", "eos")]:
            j = v + off
            if 0 <= j < n:
                ctx[name] = sent[j]
                mark[j] = 1
            else:
                ctx[name] = fallback
        wd = self.word_dict
        word_idx = np.array([wd.get(w, self.UNK_IDX) for w in sent])
        rows = [word_idx]
        for name in ("ctx_n2", "ctx_n1", "ctx_0", "ctx_p1", "ctx_p2"):
            rows.append(np.full(n, wd.get(ctx[name], self.UNK_IDX)))
        rows.append(np.full(n, self.predicate_dict.get(
            self.predicates[idx], 0)))
        rows.append(mark)
        rows.append(np.array([self.label_dict[t] for t in labels]))
        return tuple(rows)

    def __len__(self):
        return len(self.sentences)


class Movielens(Dataset):
    """MovieLens-1M ratings (reference: text/datasets/movielens.py).
    data_file: the ml-1m.zip archive (users.dat / movies.dat /
    ratings.dat '::'-separated). Yields (user_feats, movie_feats, rating):
    user = [id, gender, age, job], movie = [id, title-ids, category-ids].
    """

    URL = "https://files.grouplens.org/datasets/movielens/ml-1m.zip"

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=False):
        if data_file is None:
            _no_download("Movielens", self.URL)
        import zipfile
        users, movies, ratings = {}, {}, []
        with zipfile.ZipFile(data_file) as zf:
            root = next(n for n in zf.namelist()
                        if n.endswith("users.dat")).rsplit("/", 1)[0]

            def lines(name):
                with zf.open(f"{root}/{name}") as f:
                    for ln in f.read().decode("latin-1").splitlines():
                        if ln.strip():
                            yield ln.split("::")

            genders = {"M": 0, "F": 1}
            ages = {}
            jobs = {}
            for uid, g, age, job, _zip in lines("users.dat"):
                ages.setdefault(age, len(ages))
                jobs.setdefault(job, len(jobs))
                users[int(uid)] = np.array(
                    [int(uid), genders[g], ages[age], jobs[job]], np.int64)
            cats, words = {}, {}
            for mid, title, cat in lines("movies.dat"):
                cat_ids = [cats.setdefault(c, len(cats))
                           for c in cat.split("|")]
                title_ids = [words.setdefault(w.lower(), len(words))
                             for w in title.split()]
                movies[int(mid)] = (np.array([int(mid)], np.int64),
                                    np.array(title_ids, np.int64),
                                    np.array(cat_ids, np.int64))
            for uid, mid, r, _ts in lines("ratings.dat"):
                ratings.append((int(uid), int(mid), float(r)))
        rng = np.random.RandomState(rand_seed)
        order = rng.permutation(len(ratings))
        n_test = int(len(ratings) * test_ratio)
        sel = order[n_test:] if mode == "train" else order[:n_test]
        self._users, self._movies = users, movies
        self._samples = [ratings[i] for i in sel]

    def __getitem__(self, idx):
        uid, mid, r = self._samples[idx]
        mid_arr, title, cat = self._movies[mid]
        return (self._users[uid], mid_arr, title, cat,
                np.array([r], np.float32))

    def __len__(self):
        return len(self._samples)


class Imikolov(Dataset):
    """PTB language-model dataset (reference: text/datasets/imikolov.py).

    data_file: the simple-examples tar (ptb.train/valid.txt inside) or a
    directory holding ``ptb.train.txt``/``ptb.valid.txt``. data_type
    'NGRAM' (sliding windows of window_size) or 'SEQ' (<s> src / trg <e>
    pairs); dict built from train+valid with min_word_freq cutoff,
    '<unk>' last — reference semantics exactly.
    """

    URL = "https://dataset.bj.bcebos.com/imikolov%2Fsimple-examples.tgz"
    _TRAIN = "./simple-examples/data/ptb.train.txt"
    _VALID = "./simple-examples/data/ptb.valid.txt"

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=False):
        if data_file is None:
            _no_download("Imikolov", self.URL)
        data_type = data_type.upper()
        assert data_type in ("NGRAM", "SEQ"), data_type
        assert mode in ("train", "valid", "test")
        self.data_type = data_type
        self.window_size = window_size
        self.mode = "valid" if mode == "test" else mode
        self.min_word_freq = min_word_freq
        train_text, valid_text = self._read_texts(data_file)
        self.word_idx = self._build_dict(train_text, valid_text)
        self.data = self._expand(train_text if self.mode == "train"
                                 else valid_text)

    def _read_texts(self, data_file):
        if os.path.isdir(data_file):
            tr = open(os.path.join(data_file, "ptb.train.txt")).read()
            va = open(os.path.join(data_file, "ptb.valid.txt")).read()
            return tr.splitlines(), va.splitlines()
        with tarfile.open(data_file) as tf:
            tr = tf.extractfile(self._TRAIN).read().decode()
            va = tf.extractfile(self._VALID).read().decode()
        return tr.splitlines(), va.splitlines()

    def _build_dict(self, train_text, valid_text):
        freq: dict = {}
        for line in train_text + valid_text:
            for w in line.strip().split():
                freq[w] = freq.get(w, 0) + 1
        freq.pop("<unk>", None)
        kept = [kv for kv in freq.items() if kv[1] > self.min_word_freq]
        kept.sort(key=lambda kv: (-kv[1], kv[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _expand(self, lines):
        data = []
        unk = self.word_idx["<unk>"]
        for line in lines:
            if self.data_type == "NGRAM":
                assert self.window_size > -1, "Invalid gram length"
                toks = ["<s>"] + line.strip().split() + ["<e>"]
                if len(toks) >= self.window_size:
                    ids = [self.word_idx.get(w, unk) for w in toks]
                    for i in range(self.window_size, len(ids) + 1):
                        data.append(tuple(ids[i - self.window_size:i]))
            else:
                ids = [self.word_idx.get(w, unk)
                       for w in line.strip().split()]
                src = [self.word_idx.get("<s>", unk)] + ids
                trg = ids + [self.word_idx.get("<e>", unk)]
                if self.window_size > 0 and len(src) > self.window_size:
                    continue
                data.append((src, trg))
        return data

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


class WMT16(Dataset):
    """ACL2016 multimodal MT dataset (reference: text/datasets/wmt16.py).

    data_file: the wmt16 tar (wmt16/{train,val,test} tab-separated
    en\\tde lines) or a directory with those files. Dicts are built from
    the train split, sized to src/trg_dict_size, with <s>/<e>/<unk>
    reserved — reference semantics. Samples: (src_ids, trg_ids,
    trg_ids_next)."""

    URL = "http://paddlemodels.bj.bcebos.com/wmt/wmt16.tar.gz"
    START, END, UNK = "<s>", "<e>", "<unk>"

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=False):
        if data_file is None:
            _no_download("WMT16", self.URL)
        assert mode in ("train", "val", "test")
        assert src_dict_size > 0 and trg_dict_size > 0, \
            "dict_size should be set as positive number"
        self.mode = mode
        self.lang = lang
        train_lines = self._read(data_file, "train")
        src_col = 0 if lang == "en" else 1
        self.src_dict = self._build_dict(train_lines, src_col,
                                         src_dict_size)
        self.trg_dict = self._build_dict(train_lines, 1 - src_col,
                                         trg_dict_size)
        self._load(self._read(data_file, mode), src_col)

    def _read(self, data_file, split):
        if os.path.isdir(data_file):
            return open(os.path.join(data_file, split)).read().splitlines()
        with tarfile.open(data_file) as tf:
            return tf.extractfile(f"wmt16/{split}").read() \
                .decode().splitlines()

    def _build_dict(self, lines, col, size):
        freq: dict = {}
        for line in lines:
            parts = line.strip().split("\t")
            if len(parts) != 2:
                continue
            for w in parts[col].split():
                freq[w] = freq.get(w, 0) + 1
        kept = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
        words = [self.START, self.END, self.UNK] + \
            [w for w, _ in kept[:max(size - 3, 0)]]
        return {w: i for i, w in enumerate(words)}

    def _load(self, lines, src_col):
        s_id, e_id = self.src_dict[self.START], self.src_dict[self.END]
        unk = self.src_dict[self.UNK]
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        for line in lines:
            parts = line.strip().split("\t")
            if len(parts) != 2:
                continue
            src = [s_id] + [self.src_dict.get(w, unk)
                            for w in parts[src_col].split()] + [e_id]
            trg = [self.trg_dict.get(w, unk)
                   for w in parts[1 - src_col].split()]
            self.src_ids.append(src)
            self.trg_ids.append([s_id] + trg)
            self.trg_ids_next.append(trg + [e_id])

    def get_dict(self, lang, reverse=False):
        d = self.src_dict if lang == self.lang else self.trg_dict
        return {v: k for k, v in d.items()} if reverse else d

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)


WMT14 = WMT16  # reference WMT14 shares the loader contract (tar of
# tab-separated parallel text); pass the wmt14 archive's files
