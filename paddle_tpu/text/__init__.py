"""paddle_tpu.text (reference: python/paddle/text — datasets + viterbi_decode).

The dataset downloads need network egress (unavailable); the compute op
(viterbi_decode) is implemented TPU-natively with lax.scan.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from .. import nn

from . import datasets  # noqa: E402,F401
from .datasets import (  # noqa: E402,F401
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
)

__all__ = ["viterbi_decode", "ViterbiDecoder", "datasets", "Imdb",
           "Imikolov", "WMT14", "WMT16",
           "UCIHousing", "Conll05st", "Movielens"]


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decoding (reference: paddle.text.viterbi_decode →
    phi viterbi_decode kernel). potentials: [B, T, N] emission scores;
    transition_params: [N, N]. Returns (scores [B], paths [B, T]).

    TPU-native: the per-step max-product recurrence is a lax.scan (compiled
    control flow); backtracking is a reverse scan over the argmax pointers.
    Variable-length batches (`lengths`) are not yet supported — pad-free
    inputs only (loud error instead of silently wrong scores).
    """
    if lengths is not None:
        raise NotImplementedError(
            "viterbi_decode(lengths=...) is not supported yet; decode "
            "unpadded sequences (or split the batch by length)")

    def fwd(emis, trans):
        b, t, n = emis.shape
        ef = emis.astype(jnp.float32)
        tf = trans.astype(jnp.float32)

        def step(alpha, emit_t):
            # scores[b, i, j] = alpha[b, i] + trans[i, j] + emit[b, j]
            scores = alpha[:, :, None] + tf[None] + emit_t[:, None, :]
            best_prev = jnp.argmax(scores, axis=1)          # [B, N]
            alpha_new = jnp.max(scores, axis=1)
            return alpha_new, best_prev

        alpha0 = ef[:, 0]
        alpha, pointers = jax.lax.scan(step, alpha0,
                                       jnp.swapaxes(ef[:, 1:], 0, 1))
        # pointers: [T-1, B, N]
        last_tag = jnp.argmax(alpha, axis=-1)               # [B]
        score = jnp.max(alpha, axis=-1)

        def back(tag, ptr_t):
            prev = jnp.take_along_axis(ptr_t, tag[:, None], axis=1)[:, 0]
            return prev, tag

        if t > 1:
            first_tag, tags_rev = jax.lax.scan(back, last_tag, pointers,
                                               reverse=True)
            path = jnp.concatenate([first_tag[None], tags_rev], axis=0)
        else:
            path = last_tag[None]
        return score, (jnp.swapaxes(path, 0, 1).astype(jnp.int64),)

    out = apply("viterbi_decode", fwd, [potentials, transition_params],
                has_aux=True)
    score, path = out
    return score, path


class ViterbiDecoder(nn.Layer):
    """Reference: paddle.text.ViterbiDecoder."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) \
            else Tensor(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
