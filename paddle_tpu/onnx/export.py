"""Structural ONNX exporter for sequential models.

Reference: python/paddle/onnx/export.py (paddle.onnx.export via
paddle2onnx). TPU-native context: the native deployment format remains
serialized StableHLO (paddle_tpu.jit.save); this exporter emits genuine
ONNX ModelProto bytes (opset 13) for the classic deployment shapes — MLP /
CNN classifiers expressed as ``nn.Sequential`` chains (Linear, Conv2D,
BatchNorm2D, LayerNorm, activations, pooling, Flatten, Dropout) — with
weights as initializers. Models with bespoke forward() logic should export
through jit.save, or be re-expressed as a Sequential for ONNX.
"""
from __future__ import annotations

import numpy as np

from . import proto

__all__ = ["export"]

_ACTS = {"ReLU": "Relu", "Sigmoid": "Sigmoid", "Tanh": "Tanh",
         "Silu": "Silu", "Softplus": "Softplus", "Softsign": "Softsign",
         "ELU": "Elu"}


class _Emitter:
    def __init__(self):
        self.nodes = []
        self.inits = []
        self.count = 0

    def name(self, base):
        self.count += 1
        return f"{base}_{self.count}"

    def add_init(self, base, arr):
        arr = np.ascontiguousarray(np.asarray(arr, np.float32))
        nm = self.name(base)
        self.inits.append(proto.tensor_proto(nm, arr.shape, proto.FLOAT,
                                             arr.tobytes()))
        return nm

    def emit(self, op, inputs, attrs=()):
        out = self.name(op.lower())
        self.nodes.append(proto.node(op, inputs, [out],
                                     name=self.name(op), attrs=attrs))
        return out


def _pair(v):
    return list(v) if isinstance(v, (list, tuple)) else [int(v), int(v)]


def _emit_layer(em, layer, cur):
    from .. import nn
    kind = type(layer).__name__
    w = getattr(layer, "weight", None)
    b = getattr(layer, "bias", None)
    if isinstance(layer, nn.Linear):
        # paddle keeps W as [in, out]: Gemm with transB=0
        wn = em.add_init("weight", w._data)
        ins = [cur, wn]
        attrs = [proto.attribute("transB", i=0)]
        if b is not None:
            ins.append(em.add_init("bias", b._data))
        return em.emit("Gemm", ins, attrs)
    if isinstance(layer, nn.Conv2D):
        wn = em.add_init("weight", w._data)
        ins = [cur, wn]
        if b is not None:
            ins.append(em.add_init("bias", b._data))
        pad = layer._padding
        pads = _pair(pad) * 2 if not isinstance(pad, (list, tuple)) or \
            len(_pair(pad)) == 2 else list(pad)
        attrs = [proto.attribute("strides", ints=_pair(layer._stride)),
                 proto.attribute("pads", ints=pads),
                 proto.attribute("dilations", ints=_pair(layer._dilation)),
                 proto.attribute("group", i=layer._groups)]
        return em.emit("Conv", ins, attrs)
    if kind in ("BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D"):
        ins = [cur,
               em.add_init("gamma", layer.weight._data),
               em.add_init("beta", layer.bias._data),
               em.add_init("mean", layer._mean._data),
               em.add_init("var", layer._variance._data)]
        return em.emit("BatchNormalization", ins,
                       [proto.attribute("epsilon",
                                        f=float(layer._epsilon))])
    if kind == "LayerNorm":
        ins = [cur, em.add_init("gamma", layer.weight._data)]
        if layer.bias is not None:
            ins.append(em.add_init("beta", layer.bias._data))
        return em.emit("LayerNormalization", ins,
                       [proto.attribute("epsilon",
                                        f=float(layer._epsilon))])
    if kind in _ACTS:
        return em.emit(_ACTS[kind], [cur])
    if kind == "GELU":
        return em.emit("Gelu", [cur])
    if kind == "LeakyReLU":
        return em.emit("LeakyRelu", [cur],
                       [proto.attribute("alpha",
                                        f=float(layer._negative_slope))])
    if kind == "ReLU6":
        return em.emit("Clip", [cur, em.add_init("min", np.float32(0)),
                                em.add_init("max", np.float32(6))])
    if kind == "Softmax":
        return em.emit("Softmax", [cur],
                       [proto.attribute("axis",
                                        i=getattr(layer, "axis", -1))])
    if kind == "MaxPool2D":
        return em.emit("MaxPool", [cur], [
            proto.attribute("kernel_shape", ints=_pair(layer.ksize)),
            proto.attribute("strides",
                            ints=_pair(layer.stride or layer.ksize)),
            proto.attribute("pads", ints=_pair(layer.padding) * 2
                            if len(_pair(layer.padding)) == 2
                            else list(layer.padding)),
            proto.attribute("ceil_mode", i=int(layer.ceil_mode))])
    if kind == "AvgPool2D":
        return em.emit("AveragePool", [cur], [
            proto.attribute("kernel_shape", ints=_pair(layer.ksize)),
            proto.attribute("strides",
                            ints=_pair(layer.stride or layer.ksize)),
            proto.attribute("pads", ints=_pair(layer.padding) * 2
                            if len(_pair(layer.padding)) == 2
                            else list(layer.padding)),
            proto.attribute("ceil_mode", i=int(layer.ceil_mode))])
    if kind == "AdaptiveAvgPool2D":
        out_sz = layer.output_size
        out_sz = _pair(out_sz)
        if out_sz != [1, 1]:
            raise NotImplementedError(
                "ONNX export supports AdaptiveAvgPool2D(1) "
                "(GlobalAveragePool) only")
        return em.emit("GlobalAveragePool", [cur])
    if kind == "Flatten":
        return em.emit("Flatten", [cur], [proto.attribute(
            "axis", i=getattr(layer, "start_axis", 1))])
    if kind in ("Dropout", "Dropout2D", "Dropout3D", "AlphaDropout",
                "Identity"):
        return cur  # inference graph
    if kind in ("Sequential", "LayerList"):
        for sub in layer:
            cur = _emit_layer(em, sub, cur)
        return cur
    raise NotImplementedError(
        f"ONNX export does not support layer type {kind}; supported: "
        "Sequential chains of Linear/Conv2D/BatchNorm*/LayerNorm/"
        "activations/pooling/Flatten/Dropout. Use paddle_tpu.jit.save "
        "(StableHLO) for arbitrary models.")


def _example_from_spec(spec):
    """Concrete example tensor from an InputSpec/shape (None dims -> 1)."""
    import numpy as np

    from ..core.tensor import Tensor
    shape = [1 if d is None else int(d) for d in
             (spec.shape if hasattr(spec, "shape") else spec)]
    dtype = str(getattr(spec, "dtype", "float32") or "float32")
    if "int" in dtype:
        return Tensor(np.zeros(shape, dtype))
    return Tensor(np.zeros(shape, np.float32))


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Reference: paddle.onnx.export(layer, path, input_spec) — writes
    ``path + '.onnx'``. input_spec: InputSpec/shape (None dims = dynamic
    batch) or concrete example Tensors.

    Sequential MLP/CNN stacks go through the layer-by-layer emitter (keeps
    dynamic batch dims and Gemm/Conv-level nodes); ANY other traceable
    model goes through the jaxpr walker (jaxpr_export.export_traced) —
    the paddle2onnx-equivalent general path."""
    if input_spec is None:
        raise ValueError("paddle.onnx.export requires input_spec")
    from .. import nn
    from ..core.tensor import Tensor
    specs = list(input_spec) if isinstance(input_spec, (list, tuple)) \
        else [input_spec]
    if not isinstance(layer, nn.Sequential):
        from .jaxpr_export import export_traced
        examples = [s if isinstance(s, Tensor) else _example_from_spec(s)
                    for s in specs]
        was_training = getattr(layer, "training", False)
        if hasattr(layer, "eval"):
            layer.eval()
        try:
            return export_traced(layer, examples, path,
                                 opset_version=opset_version)
        finally:
            if was_training and hasattr(layer, "train"):
                layer.train()
    spec = specs[0]
    shape = list(spec.shape) if hasattr(spec, "shape") else list(spec)

    em = _Emitter()
    out_name = _emit_layer(em, layer, "input")
    # rename the graph output for a stable interface
    g_inputs = [proto.value_info("input", proto.FLOAT, shape)]
    g_outputs = [proto.value_info(out_name, proto.FLOAT, [None])]
    g = proto.graph(em.nodes, "paddle_tpu_graph", em.inits, g_inputs,
                    g_outputs)
    blob = proto.model(g, opset=opset_version)
    out_path = path if str(path).endswith(".onnx") else str(path) + ".onnx"
    with open(out_path, "wb") as f:
        f.write(blob)
    return out_path
