"""Minimal numpy evaluator for the ONNX op subset this package emits.

No onnxruntime is available in the environment, so numerical verification
of exports runs the parsed ModelProto (proto.parse_model) directly — the
same role onnxruntime plays in the reference's paddle2onnx test suite.
"""
from __future__ import annotations

import numpy as np

from . import proto

__all__ = ["run_model"]


def _from_tensor(t):
    return t["array"]


def _pool_view(x, kernel, strides, pads):
    n, c, h, w = x.shape
    kh, kw = kernel
    sh, sw = strides
    pt, pl, pb, pr = pads[0], pads[1], pads[2], pads[3]
    return x, n, c, h, w, kh, kw, sh, sw, pt, pl, pb, pr


def run_model(blob_or_parsed, feeds):
    """Execute a (parsed) model on {input_name: np array}; returns the list
    of graph outputs."""
    m = blob_or_parsed if isinstance(blob_or_parsed, dict) else \
        proto.parse_model(blob_or_parsed)
    g = m["graph"]
    env = dict(feeds)
    for init in g["initializers"]:
        env[init["name"]] = _from_tensor(init)

    for nd in g["nodes"]:
        op = nd["op_type"]
        a = nd["attrs"]
        x = [env[i] for i in nd["inputs"] if i]
        out = None
        if op == "Add":
            out = x[0] + x[1]
        elif op == "Sub":
            out = x[0] - x[1]
        elif op == "Mul":
            out = x[0] * x[1]
        elif op == "Div":
            out = x[0] / x[1]
        elif op == "MatMul":
            out = x[0] @ x[1]
        elif op == "Max":
            out = np.maximum(x[0], x[1])
        elif op == "Min":
            out = np.minimum(x[0], x[1])
        elif op == "Pow":
            out = np.power(x[0], x[1])
        elif op == "Neg":
            out = -x[0]
        elif op == "Exp":
            out = np.exp(x[0])
        elif op == "Log":
            out = np.log(x[0])
        elif op == "Sqrt":
            out = np.sqrt(x[0])
        elif op == "Reciprocal":
            out = 1.0 / x[0]
        elif op == "Tanh":
            out = np.tanh(x[0])
        elif op == "Sigmoid":
            out = 1.0 / (1.0 + np.exp(-x[0]))
        elif op == "Erf":
            from scipy.special import erf as _erf
            out = _erf(x[0])
        elif op == "Abs":
            out = np.abs(x[0])
        elif op == "Sign":
            out = np.sign(x[0])
        elif op == "Floor":
            out = np.floor(x[0])
        elif op == "Ceil":
            out = np.ceil(x[0])
        elif op == "Round":
            out = np.round(x[0])
        elif op == "Equal":
            out = x[0] == x[1]
        elif op == "Less":
            out = x[0] < x[1]
        elif op == "Greater":
            out = x[0] > x[1]
        elif op == "LessOrEqual":
            out = x[0] <= x[1]
        elif op == "GreaterOrEqual":
            out = x[0] >= x[1]
        elif op == "And":
            out = x[0] & x[1]
        elif op == "Or":
            out = x[0] | x[1]
        elif op == "Not":
            out = ~x[0]
        elif op == "Where":
            out = np.where(x[0], x[1], x[2])
        elif op == "Reshape":
            out = x[0].reshape([int(v) for v in x[1]])
        elif op == "Transpose":
            out = np.transpose(x[0], a.get("perm"))
        elif op == "Expand":
            out = np.broadcast_to(x[0], [int(v) for v in x[1]]).copy()
        elif op == "Concat":
            out = np.concatenate(x, axis=a["axis"])
        elif op == "Cast":
            dt = {1: np.float32, 7: np.int64, 6: np.int32, 9: np.bool_}[
                a["to"]]
            out = x[0].astype(dt)
        elif op == "Slice":
            starts, ends = x[1], x[2]
            axes = x[3] if len(x) > 3 else np.arange(len(starts))
            steps = x[4] if len(x) > 4 else np.ones(len(starts), np.int64)
            idx = [slice(None)] * x[0].ndim
            for s, e, ax, st in zip(starts, ends, axes, steps):
                idx[int(ax)] = slice(int(s), int(e), int(st))
            out = x[0][tuple(idx)]
        elif op == "Gather":
            out = np.take(x[0], x[1].astype(np.int64), axis=a.get(
                "axis", 0))
        elif op in ("ReduceSum", "ReduceMax", "ReduceMin", "ReduceProd"):
            axes = tuple(int(v) for v in x[1]) if len(x) > 1 else None
            keep = bool(a.get("keepdims", 1))
            fn = {"ReduceSum": np.sum, "ReduceMax": np.max,
                  "ReduceMin": np.min, "ReduceProd": np.prod}[op]
            out = fn(x[0], axis=axes, keepdims=keep)
        elif op == "Conv":
            out = _conv(x[0], x[1], x[2] if len(x) > 2 else None, a)
        elif op in ("MaxPool", "AveragePool"):
            out = _pool(x[0], a, op)
        elif op == "Pad":
            pads = x[1]
            n2 = x[0].ndim
            cfg = [(int(pads[i]), int(pads[i + n2])) for i in range(n2)]
            cval = float(x[2]) if len(x) > 2 else 0.0
            out = np.pad(x[0], cfg, constant_values=cval)
        elif op == "Gemm":
            y = x[0] @ (x[1].T if a.get("transB") else x[1])
            if len(x) > 2:
                y = y + x[2]
            out = y
        elif op == "Relu":
            out = np.maximum(x[0], 0)
        else:
            raise NotImplementedError(f"onnx.runtime: op {op}")
        env[nd["outputs"][0]] = out

    return [env[o["name"]] for o in g["outputs"]]


def _conv(x, w, b, a):
    import jax
    import jax.numpy as jnp
    strides = a.get("strides", [1] * (x.ndim - 2))
    dil = a.get("dilations", [1] * (x.ndim - 2))
    pads = a.get("pads", [0] * (2 * (x.ndim - 2)))
    nsp = x.ndim - 2
    padding = [(int(pads[i]), int(pads[i + nsp])) for i in range(nsp)]
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape,
                                        ("NCHW", "OIHW", "NCHW")
                                        if nsp == 2 else None)
    out = jax.lax.conv_general_dilated(
        jnp.asarray(x, jnp.float32), jnp.asarray(w, jnp.float32),
        window_strides=[int(s) for s in strides], padding=padding,
        rhs_dilation=[int(d) for d in dil], dimension_numbers=dn,
        feature_group_count=int(a.get("group", 1)))
    out = np.asarray(out)
    if b is not None:
        out = out + b.reshape((1, -1) + (1,) * nsp)
    return out


def _pool(x, a, kind):
    kh, kw = a["kernel_shape"]
    sh, sw = a.get("strides", [1, 1])
    pads = a.get("pads", [0, 0, 0, 0])
    pt, pl, pb, pr = (int(p) for p in pads)
    fill = -np.inf if kind == "MaxPool" else 0.0
    xp = np.pad(x, ((0, 0), (0, 0), (pt, pb), (pl, pr)),
                constant_values=fill)
    n, c, h, w = xp.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    out = np.empty((n, c, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            win = xp[:, :, i * sh:i * sh + kh, j * sw:j * sw + kw]
            out[:, :, i, j] = win.max((2, 3)) if kind == "MaxPool" \
                else win.mean((2, 3))
    return out
