"""ONNX export for arbitrary traced models — jaxpr → ONNX graph.

Reference: python/paddle/onnx/export.py delegates to paddle2onnx, which
walks the static Program op-by-op and maps each op to ONNX nodes. The
TPU-native analog walks the model's *jaxpr* (the traced forward is the
program; there is no ProgramDesc) and maps each lax primitive to ONNX —
so any model the tracer can stage exports, not just Sequential stacks.

Design:
* constant folding — an equation whose inputs are all known constants
  (weights are closed-over constants of the trace) is evaluated eagerly
  and becomes an initializer; position ids, causal masks, iota etc.
  disappear from the graph;
* call primitives (pjit, custom_jvp, remat) are inlined recursively;
* unsupported primitives raise with the primitive's name (the reference's
  paddle2onnx contract: a clear per-op error, never a silent skip).
"""
from __future__ import annotations

import numpy as np

from . import proto

__all__ = ["export_traced", "UnsupportedOpError"]

_BOOL, _INT32 = 9, 6


class UnsupportedOpError(NotImplementedError):
    def __init__(self, prim, detail=""):
        super().__init__(
            f"ONNX export: jax primitive '{prim}' is not mapped to an ONNX "
            f"op{(' (' + detail + ')') if detail else ''}; supported set: "
            f"{sorted(_HANDLERS)}")


def _np_dtype_to_onnx(dt):
    dt = np.dtype(dt)
    if dt == np.float32 or dt == np.float64 or dt == np.float16 \
            or str(dt) == "bfloat16":
        return proto.FLOAT
    if dt == np.bool_:
        return _BOOL
    if dt == np.int32:
        return _INT32
    return proto.INT64


def _np_for_onnx(arr):
    """Normalize to the dtypes the initializer writer emits."""
    arr = np.asarray(arr)
    code = _np_dtype_to_onnx(arr.dtype)
    if code == proto.FLOAT:
        return arr.astype(np.float32), proto.FLOAT
    if code == _BOOL:
        return arr.astype(np.bool_), _BOOL
    if code == _INT32:
        return arr.astype(np.int32), _INT32
    return arr.astype(np.int64), proto.INT64


class _GraphBuilder:
    def __init__(self):
        self.nodes = []
        self.inits = []
        self.count = 0

    def name(self, base):
        self.count += 1
        return f"{base}_{self.count}"

    def add_init(self, base, arr):
        arr, code = _np_for_onnx(arr)
        nm = self.name(base)
        self.inits.append(proto.tensor_proto(
            nm, arr.shape, code, np.ascontiguousarray(arr).tobytes()))
        return nm

    def emit(self, op, inputs, attrs=(), n_out=1):
        outs = [self.name(op.lower()) for _ in range(n_out)]
        self.nodes.append(proto.node(op, inputs, outs,
                                     name=self.name(op), attrs=attrs))
        return outs[0] if n_out == 1 else outs


class _Ctx:
    """var -> ('c', np array) constant or ('n', str) graph edge."""

    def __init__(self, gb):
        self.gb = gb
        self.env = {}

    def read(self, var):
        if hasattr(var, "val"):  # jax Literal
            return ("c", np.asarray(var.val))
        return self.env[var]

    def name_of(self, v):
        """Graph-edge name for a value, materializing constants."""
        kind, val = v
        if kind == "n":
            return val
        return self.gb.add_init("const", val)


def _all_const(vals):
    return all(k == "c" for k, _ in vals)


def _fold(eqn, vals):
    """Evaluate a fully-constant equation eagerly."""
    args = [v for _, v in vals]
    sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
    if sub is not None:
        try:
            from jax.core import eval_jaxpr
        except ImportError:
            from jax.extend.core import eval_jaxpr
        jx = sub.jaxpr if hasattr(sub, "jaxpr") else sub
        consts = sub.consts if hasattr(sub, "consts") else []
        out = eval_jaxpr(jx, consts, *args)
        return [np.asarray(o) for o in out]
    out = eqn.primitive.bind(*args, **eqn.params)
    outs = out if isinstance(out, (list, tuple)) else [out]
    return [np.asarray(o) for o in outs]


# -- primitive handlers ---------------------------------------------------

def _ew(op):
    def h(ctx, eqn, ins):
        return ctx.gb.emit(op, [ctx.name_of(v) for v in ins])
    return h


def _h_integer_pow(ctx, eqn, ins):
    y = eqn.params["y"]
    x = ctx.name_of(ins[0])
    if y == 2:
        return ctx.gb.emit("Mul", [x, x])
    e = ctx.gb.add_init("exp", np.asarray(float(y), np.float32))
    return ctx.gb.emit("Pow", [x, e])


def _h_select_n(ctx, eqn, ins):
    # select_n(pred, case0, case1): pred True -> case1
    pred, a, b = [ctx.name_of(v) for v in ins]
    return ctx.gb.emit("Where", [pred, b, a])


def _h_broadcast_in_dim(ctx, eqn, ins):
    shape = eqn.params["shape"]
    bdims = eqn.params["broadcast_dimensions"]
    src = ins[0]
    # reshape to rank(shape) with singletons, then Expand
    inter = [1] * len(shape)
    in_aval = eqn.invars[0].aval
    for i, d in enumerate(bdims):
        inter[d] = in_aval.shape[i]
    x = ctx.name_of(src)
    if list(in_aval.shape) != inter:
        shp = ctx.gb.add_init("shape", np.asarray(inter, np.int64))
        x = ctx.gb.emit("Reshape", [x, shp])
    tgt = ctx.gb.add_init("shape", np.asarray(shape, np.int64))
    return ctx.gb.emit("Expand", [x, tgt])


def _h_reshape(ctx, eqn, ins):
    shp = ctx.gb.add_init(
        "shape", np.asarray(eqn.params["new_sizes"], np.int64))
    return ctx.gb.emit("Reshape", [ctx.name_of(ins[0]), shp])


def _h_shape_to(ctx, eqn, ins):
    """squeeze/expand_dims — both are reshapes to the output aval."""
    shp = ctx.gb.add_init(
        "shape", np.asarray(eqn.outvars[0].aval.shape, np.int64))
    return ctx.gb.emit("Reshape", [ctx.name_of(ins[0]), shp])


def _h_transpose(ctx, eqn, ins):
    perm = [int(p) for p in eqn.params["permutation"]]
    return ctx.gb.emit("Transpose", [ctx.name_of(ins[0])],
                       attrs=[proto.attribute("perm", ints=perm)])


def _h_concatenate(ctx, eqn, ins):
    return ctx.gb.emit(
        "Concat", [ctx.name_of(v) for v in ins],
        attrs=[proto.attribute("axis", i=int(eqn.params["dimension"]))])


def _h_slice(ctx, eqn, ins):
    p = eqn.params
    starts = ctx.gb.add_init("starts",
                             np.asarray(p["start_indices"], np.int64))
    ends = ctx.gb.add_init("ends", np.asarray(p["limit_indices"], np.int64))
    axes = ctx.gb.add_init(
        "axes", np.arange(len(p["start_indices"]), dtype=np.int64))
    args = [ctx.name_of(ins[0]), starts, ends, axes]
    if p.get("strides") is not None:
        args.append(ctx.gb.add_init("steps",
                                    np.asarray(p["strides"], np.int64)))
    return ctx.gb.emit("Slice", args)


def _h_convert(ctx, eqn, ins):
    code = _np_dtype_to_onnx(eqn.params["new_dtype"])
    return ctx.gb.emit("Cast", [ctx.name_of(ins[0])],
                       attrs=[proto.attribute("to", i=code)])


def _h_reduce(onnx_op):
    def h(ctx, eqn, ins):
        axes = ctx.gb.add_init("axes",
                               np.asarray(eqn.params["axes"], np.int64))
        return ctx.gb.emit(onnx_op, [ctx.name_of(ins[0]), axes],
                           attrs=[proto.attribute("keepdims", i=0)])
    return h


def _h_dot_general(ctx, eqn, ins):
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    la, ra = eqn.invars[0].aval, eqn.invars[1].aval

    def arrange(v, aval, batch, contract, contract_last):
        free = [d for d in range(len(aval.shape))
                if d not in batch and d not in contract]
        perm = list(batch) + (free + list(contract) if contract_last
                              else list(contract) + free)
        x = ctx.name_of(v)
        if perm != list(range(len(aval.shape))):
            x = ctx.gb.emit("Transpose", [x],
                            attrs=[proto.attribute("perm",
                                                   ints=[int(p) for p
                                                         in perm])])
        b = int(np.prod([aval.shape[d] for d in batch])) if batch else 1
        k = int(np.prod([aval.shape[d] for d in contract]))
        f = int(np.prod([aval.shape[d] for d in free])) if free else 1
        shape = ([b, f, k] if contract_last else [b, k, f])
        shp = ctx.gb.add_init("shape", np.asarray(shape, np.int64))
        return ctx.gb.emit("Reshape", [x, shp]), f

    lx, m = arrange(ins[0], la, lb, lc, True)
    rx, n = arrange(ins[1], ra, rb, rc, False)
    mm = ctx.gb.emit("MatMul", [lx, rx])
    out_shape = [int(s) for s in eqn.outvars[0].aval.shape]
    shp = ctx.gb.add_init("shape", np.asarray(out_shape, np.int64))
    return ctx.gb.emit("Reshape", [mm, shp])


def _h_conv(ctx, eqn, ins):
    p = eqn.params
    dn = p["dimension_numbers"]
    if tuple(dn.lhs_spec) != tuple(range(len(dn.lhs_spec))) or \
            tuple(dn.rhs_spec) != tuple(range(len(dn.rhs_spec))):
        raise UnsupportedOpError("conv_general_dilated",
                                 f"dimension_numbers {dn} (need NCHW/OIHW)")
    if p.get("lhs_dilation") and any(d != 1 for d in p["lhs_dilation"]):
        raise UnsupportedOpError("conv_general_dilated",
                                 "transposed conv (lhs_dilation)")
    pads_pairs = p["padding"]
    pads = [int(lo) for lo, _ in pads_pairs] + [int(hi) for _, hi
                                                in pads_pairs]
    attrs = [proto.attribute("strides",
                             ints=[int(s) for s in p["window_strides"]]),
             proto.attribute("pads", ints=pads),
             proto.attribute("dilations",
                             ints=[int(d) for d in p["rhs_dilation"]]),
             proto.attribute("group", i=int(p["feature_group_count"]))]
    return ctx.gb.emit("Conv", [ctx.name_of(ins[0]), ctx.name_of(ins[1])],
                       attrs=attrs)


def _h_reduce_window_max(ctx, eqn, ins):
    p = eqn.params
    wd = p["window_dimensions"]
    if len(wd) < 3 or wd[0] != 1 or wd[1] != 1:
        raise UnsupportedOpError("reduce_window_max",
                                 f"window {wd} (need NCHW pooling)")
    pads_pairs = p["padding"][2:]
    pads = [int(lo) for lo, _ in pads_pairs] + [int(hi) for _, hi
                                                in pads_pairs]
    attrs = [proto.attribute("kernel_shape",
                             ints=[int(w) for w in wd[2:]]),
             proto.attribute("strides",
                             ints=[int(s) for s in
                                   p["window_strides"][2:]]),
             proto.attribute("pads", ints=pads)]
    return ctx.gb.emit("MaxPool", [ctx.name_of(ins[0])], attrs=attrs)


def _h_reduce_window_add(ctx, eqn, ins):
    # sum-pool = AveragePool * window_size (count_include_pad=1)
    p = eqn.params
    wd = p["window_dimensions"]
    if len(wd) < 3 or wd[0] != 1 or wd[1] != 1:
        raise UnsupportedOpError("reduce_window_sum",
                                 f"window {wd} (need NCHW pooling)")
    pads_pairs = p["padding"][2:]
    pads = [int(lo) for lo, _ in pads_pairs] + [int(hi) for _, hi
                                                in pads_pairs]
    attrs = [proto.attribute("kernel_shape",
                             ints=[int(w) for w in wd[2:]]),
             proto.attribute("strides",
                             ints=[int(s) for s in
                                   p["window_strides"][2:]]),
             proto.attribute("pads", ints=pads),
             proto.attribute("count_include_pad", i=1)]
    ap = ctx.gb.emit("AveragePool", [ctx.name_of(ins[0])], attrs=attrs)
    k = ctx.gb.add_init("winsize",
                        np.asarray(float(np.prod(wd)), np.float32))
    return ctx.gb.emit("Mul", [ap, k])


def _h_pad(ctx, eqn, ins):
    p = eqn.params["padding_config"]
    if any(inner != 0 for _, _, inner in p) or \
            any(lo < 0 or hi < 0 for lo, hi, _ in p):
        raise UnsupportedOpError("pad", "interior/negative padding")
    pads = [lo for lo, _, _ in p] + [hi for _, hi, _ in p]
    pn = ctx.gb.add_init("pads", np.asarray(pads, np.int64))
    cv = ctx.name_of(ins[1])
    return ctx.gb.emit("Pad", [ctx.name_of(ins[0]), pn, cv])


def _h_gather(ctx, eqn, ins):
    """The embedding-lookup shape of lax.gather → ONNX Gather(axis=0)."""
    p = eqn.params["dimension_numbers"]
    op_aval = eqn.invars[0].aval
    idx_aval = eqn.invars[1].aval
    ss = eqn.params["slice_sizes"]
    if (tuple(p.collapsed_slice_dims) == (0,)
            and tuple(p.start_index_map) == (0,)
            and ss[0] == 1 and tuple(ss[1:]) == tuple(op_aval.shape[1:])):
        idx = ctx.name_of(ins[1])
        if idx_aval.shape and idx_aval.shape[-1] == 1:
            shp = ctx.gb.add_init(
                "shape", np.asarray(idx_aval.shape[:-1], np.int64))
            idx = ctx.gb.emit("Reshape", [idx, shp])
        return ctx.gb.emit("Gather", [ctx.name_of(ins[0]), idx],
                           attrs=[proto.attribute("axis", i=0)])
    raise UnsupportedOpError("gather", "general gather (only embedding "
                             "lookup pattern supported)")


def _h_erfc(ctx, eqn, ins):
    e = ctx.gb.emit("Erf", [ctx.name_of(ins[0])])
    one = ctx.gb.add_init("one", np.asarray(1.0, np.float32))
    return ctx.gb.emit("Sub", [one, e])


def _h_rsqrt(ctx, eqn, ins):
    s = ctx.gb.emit("Sqrt", [ctx.name_of(ins[0])])
    return ctx.gb.emit("Reciprocal", [s])


def _h_stop_gradient(ctx, eqn, ins):
    return ctx.name_of(ins[0])


def _h_square(ctx, eqn, ins):
    x = ctx.name_of(ins[0])
    return ctx.gb.emit("Mul", [x, x])


_HANDLERS = {
    "add": _ew("Add"), "sub": _ew("Sub"), "mul": _ew("Mul"),
    "div": _ew("Div"), "max": _ew("Max"), "min": _ew("Min"),
    "pow": _ew("Pow"), "neg": _ew("Neg"), "exp": _ew("Exp"),
    "log": _ew("Log"), "tanh": _ew("Tanh"), "logistic": _ew("Sigmoid"),
    "erf": _ew("Erf"), "erfc": _h_erfc, "sqrt": _ew("Sqrt"),
    "abs": _ew("Abs"),
    "sign": _ew("Sign"), "floor": _ew("Floor"), "ceil": _ew("Ceil"),
    "round": _ew("Round"),
    "eq": _ew("Equal"), "lt": _ew("Less"), "gt": _ew("Greater"),
    "le": _ew("LessOrEqual"), "ge": _ew("GreaterOrEqual"),
    "and": _ew("And"), "or": _ew("Or"), "not": _ew("Not"),
    "rsqrt": _h_rsqrt, "integer_pow": _h_integer_pow,
    "square": _h_square,
    "select_n": _h_select_n, "broadcast_in_dim": _h_broadcast_in_dim,
    "reshape": _h_reshape, "squeeze": _h_shape_to,
    "expand_dims": _h_shape_to, "transpose": _h_transpose,
    "concatenate": _h_concatenate, "slice": _h_slice,
    "convert_element_type": _h_convert,
    "reduce_sum": _h_reduce("ReduceSum"),
    "reduce_max": _h_reduce("ReduceMax"),
    "reduce_min": _h_reduce("ReduceMin"),
    "reduce_prod": _h_reduce("ReduceProd"),
    "dot_general": _h_dot_general,
    "conv_general_dilated": _h_conv,
    "reduce_window_max": _h_reduce_window_max,
    "reduce_window_sum": _h_reduce_window_add,
    "pad": _h_pad, "gather": _h_gather,
    "stop_gradient": _h_stop_gradient,
    "copy": _h_stop_gradient,
}

_CALL_PRIMS = {"pjit", "closed_call", "custom_jvp_call", "custom_vjp_call",
               "custom_jvp_call_jaxpr", "remat", "checkpoint",
               "custom_vjp_call_jaxpr", "jit"}


def _walk(ctx, jaxpr, consts, in_vals):
    for var, c in zip(jaxpr.constvars, consts):
        ctx.env[var] = ("c", np.asarray(c))
    for var, v in zip(jaxpr.invars, in_vals):
        ctx.env[var] = v

    for eqn in jaxpr.eqns:
        ins = [ctx.read(v) for v in eqn.invars]
        pname = eqn.primitive.name
        if _all_const(ins):
            try:
                outs = _fold(eqn, ins)
                for var, o in zip(eqn.outvars, outs):
                    ctx.env[var] = ("c", o)
                continue
            except Exception:
                pass  # fall through to symbolic emission
        if pname in _CALL_PRIMS:
            sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
            jx = sub.jaxpr if hasattr(sub, "jaxpr") else sub
            consts_sub = sub.consts if hasattr(sub, "consts") else []
            n_consts = eqn.params.get("num_consts", 0)
            call_ins = ins
            if pname.startswith("custom_jvp") or \
                    pname.startswith("custom_vjp"):
                call_ins = ins[n_consts:] if n_consts else ins
            sub_ctx_env = dict(ctx.env)
            outs = _walk_sub(ctx, jx, consts_sub, call_ins)
            ctx.env.update(sub_ctx_env)
            for var, o in zip(eqn.outvars, outs):
                ctx.env[var] = o
            continue
        handler = _HANDLERS.get(pname)
        if handler is None:
            raise UnsupportedOpError(pname)
        if len(eqn.outvars) > 1:
            raise UnsupportedOpError(pname, "multi-output primitive")
        out = handler(ctx, eqn, ins)
        ctx.env[eqn.outvars[0]] = ("n", out)
    return [ctx.read(v) for v in jaxpr.outvars]


def _walk_sub(ctx, jaxpr, consts, in_vals):
    sub = _Ctx(ctx.gb)
    sub.env = ctx.env  # share: names/constants remain valid
    return _walk(sub, jaxpr, consts, in_vals)


def export_traced(fn, example_inputs, path, opset_version=13,
                  input_names=None):
    """Trace ``fn`` (a Layer or python callable over Tensors) on
    ``example_inputs`` and write an ONNX model mapping the whole traced
    graph. Returns the output path."""
    import jax

    from ..core.tensor import Tensor

    tensors = [x if isinstance(x, Tensor) else Tensor(np.asarray(x))
               for x in example_inputs]

    def pure(*arrs):
        from ..core import autograd
        with autograd.no_grad():
            outs = fn(*[Tensor(a, stop_gradient=True) for a in arrs])
        out = outs[0] if isinstance(outs, (list, tuple)) else outs
        return out._data

    closed = jax.make_jaxpr(pure)(*[t._data for t in tensors])

    gb = _GraphBuilder()
    ctx = _Ctx(gb)
    in_names = input_names or [f"input_{i}" for i in range(len(tensors))]
    in_vals = [("n", nm) for nm in in_names]
    outs = _walk(ctx, closed.jaxpr, closed.consts, in_vals)
    out_kind, out_val = outs[0]
    if out_kind == "c":
        out_name = gb.add_init("const_out", out_val)
    else:
        out_name = out_val

    g_inputs = [proto.value_info(nm, _np_dtype_to_onnx(t._data.dtype),
                                 list(t.shape))
                for nm, t in zip(in_names, tensors)]
    out_aval = closed.jaxpr.outvars[0].aval
    g_outputs = [proto.value_info(out_name,
                                  _np_dtype_to_onnx(out_aval.dtype),
                                  list(out_aval.shape))]
    g = proto.graph(gb.nodes, "paddle_tpu_traced", gb.inits, g_inputs,
                    g_outputs)
    blob = proto.model(g, opset=opset_version)
    out_path = path if str(path).endswith(".onnx") else str(path) + ".onnx"
    with open(out_path, "wb") as f:
        f.write(blob)
    return out_path
