"""Minimal protobuf wire-format writer/reader for ONNX messages.

Reference capability: python/paddle/onnx/export.py (paddle2onnx emits
ONNX ModelProto). No onnx/protobuf package exists in this environment, so
the wire format (varint tags + length-delimited submessages — the stable
protobuf encoding) is written directly against onnx.proto3's field
numbers. The reader covers the same subset for round-trip verification.
"""
from __future__ import annotations

import struct

# onnx.proto3 field numbers (stable public schema)
# ModelProto: ir_version=1 producer_name=2 graph=7 opset_import=8
# GraphProto: node=1 name=2 initializer=5 input=11 output=12
# NodeProto: input=1 output=2 name=3 op_type=4 attribute=5
# AttributeProto: name=1 f=2 i=3 s=4 t=5 floats=7 ints=8 type=20
# TensorProto: dims=1 data_type=2 name=8 raw_data=9
# ValueInfoProto: name=1 type=2 ; TypeProto: tensor_type=1
# TypeProto.Tensor: elem_type=1 shape=2
# TensorShapeProto: dim=1 ; Dimension: dim_value=1 dim_param=2

FLOAT, INT64 = 1, 7          # TensorProto.DataType
ATTR_FLOAT, ATTR_INT, ATTR_STRING, ATTR_TENSOR = 1, 2, 3, 4
ATTR_FLOATS, ATTR_INTS = 6, 7


def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def field_varint(num: int, value: int) -> bytes:
    return _varint(num << 3) + _varint(int(value))


def field_bytes(num: int, payload: bytes) -> bytes:
    return _varint((num << 3) | 2) + _varint(len(payload)) + payload


def field_string(num: int, s: str) -> bytes:
    return field_bytes(num, s.encode())


def tensor_proto(name, dims, data_type, raw: bytes) -> bytes:
    out = b""
    for d in dims:
        out += field_varint(1, d)
    out += field_varint(2, data_type)
    out += field_string(8, name)
    out += field_bytes(9, raw)
    return out


def attribute(name, *, i=None, f=None, s=None, ints=None, floats=None,
              t=None) -> bytes:
    out = field_string(1, name)
    if i is not None:
        out += field_varint(3, i) + field_varint(20, ATTR_INT)
    elif f is not None:
        out += _varint((2 << 3) | 5) + struct.pack("<f", f)
        out += field_varint(20, ATTR_FLOAT)
    elif s is not None:
        out += field_bytes(4, s.encode()) + field_varint(20, ATTR_STRING)
    elif ints is not None:
        for v in ints:
            out += field_varint(8, v)
        out += field_varint(20, ATTR_INTS)
    elif floats is not None:
        for v in floats:
            out += _varint((7 << 3) | 5) + struct.pack("<f", v)
        out += field_varint(20, ATTR_FLOATS)
    elif t is not None:
        out += field_bytes(5, t) + field_varint(20, ATTR_TENSOR)
    return out


def node(op_type, inputs, outputs, name="", attrs=()) -> bytes:
    out = b""
    for x in inputs:
        out += field_string(1, x)
    for x in outputs:
        out += field_string(2, x)
    if name:
        out += field_string(3, name)
    out += field_string(4, op_type)
    for a in attrs:
        out += field_bytes(5, a)
    return out


def value_info(name, elem_type, shape) -> bytes:
    dims = b""
    for d in shape:
        if d is None:
            dims += field_bytes(1, field_string(2, "batch"))
        else:
            dims += field_bytes(1, field_varint(1, d))
    ttype = field_varint(1, elem_type) + field_bytes(2, dims)
    return field_string(1, name) + field_bytes(2, field_bytes(1, ttype))


def graph(nodes, name, initializers, inputs, outputs) -> bytes:
    out = b""
    for n in nodes:
        out += field_bytes(1, n)
    out += field_string(2, name)
    for t in initializers:
        out += field_bytes(5, t)
    for v in inputs:
        out += field_bytes(11, v)
    for v in outputs:
        out += field_bytes(12, v)
    return out


def model(graph_bytes, opset=13, producer="paddle_tpu") -> bytes:
    opset_b = field_string(1, "") + field_varint(2, opset)
    return (field_varint(1, 8)              # ir_version 8
            + field_string(2, producer)
            + field_bytes(7, graph_bytes)
            + field_bytes(8, opset_b))


# ---------------- reader (round-trip verification) ----------------

def _read_varint(buf, pos):
    out = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def parse_fields(buf):
    """Yield (field_number, wire_type, value) over a message buffer."""
    pos, end = 0, len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        num, wt = tag >> 3, tag & 7
        if wt == 0:
            val, pos = _read_varint(buf, pos)
        elif wt == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            val = buf[pos:pos + 4]
            pos += 4
        elif wt == 1:
            val = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield num, wt, val


def parse_model(buf):
    """Decode the subset written above into plain dicts."""
    m = {"opset": None, "producer": None, "graph": None}
    for num, _, val in parse_fields(buf):
        if num == 2:
            m["producer"] = val.decode()
        elif num == 7:
            m["graph"] = _parse_graph(val)
        elif num == 8:
            for n2, _, v2 in parse_fields(val):
                if n2 == 2:
                    m["opset"] = v2
    return m


def _parse_graph(buf):
    g = {"name": None, "nodes": [], "initializers": [], "inputs": [],
         "outputs": []}
    for num, _, val in parse_fields(buf):
        if num == 1:
            g["nodes"].append(_parse_node(val))
        elif num == 2:
            g["name"] = val.decode()
        elif num == 5:
            g["initializers"].append(_parse_tensor(val))
        elif num == 11:
            g["inputs"].append(_parse_value_info(val))
        elif num == 12:
            g["outputs"].append(_parse_value_info(val))
    return g


def _parse_node(buf):
    n = {"op_type": None, "name": "", "inputs": [], "outputs": [],
         "attrs": {}}
    for num, _, val in parse_fields(buf):
        if num == 1:
            n["inputs"].append(val.decode())
        elif num == 2:
            n["outputs"].append(val.decode())
        elif num == 3:
            n["name"] = val.decode()
        elif num == 4:
            n["op_type"] = val.decode()
        elif num == 5:
            a = _parse_attr(val)
            n["attrs"][a[0]] = a[1]
    return n


def _parse_attr(buf):
    name, ints, floats, value = None, [], [], None
    for num, wt, val in parse_fields(buf):
        if num == 1:
            name = val.decode()
        elif num == 3:
            value = val
        elif num == 2:
            value = struct.unpack("<f", val)[0]
        elif num == 4:
            value = val.decode()
        elif num == 8:
            ints.append(val)
        elif num == 7:
            floats.append(struct.unpack("<f", val)[0])
    if ints:
        value = ints
    elif floats:
        value = floats
    return name, value


def _parse_tensor(buf):
    import numpy as np
    t = {"name": None, "dims": [], "data_type": None, "array": None}
    raw = b""
    for num, _, val in parse_fields(buf):
        if num == 1:
            t["dims"].append(val)
        elif num == 2:
            t["data_type"] = val
        elif num == 8:
            t["name"] = val.decode()
        elif num == 9:
            raw = val
    dt = {FLOAT: np.float32, INT64: np.int64, 6: np.int32,
          9: np.bool_}.get(t["data_type"], np.float32)
    t["array"] = np.frombuffer(raw, dt).reshape(t["dims"])
    return t


def _parse_value_info(buf):
    v = {"name": None, "shape": []}
    for num, _, val in parse_fields(buf):
        if num == 1:
            v["name"] = val.decode()
        elif num == 2:
            for n2, _, v2 in parse_fields(val):
                if n2 == 1:  # tensor_type
                    for n3, _, v3 in parse_fields(v2):
                        if n3 == 2:  # shape
                            for n4, _, v4 in parse_fields(v3):
                                if n4 == 1:  # dim
                                    dim = None
                                    for n5, _, v5 in parse_fields(v4):
                                        if n5 == 1:
                                            dim = v5
                                    v["shape"].append(dim)
    return v
