"""paddle_tpu.onnx (reference: python/paddle/onnx — delegates to paddle2onnx).

The TPU-native deployment format is serialized StableHLO (paddle_tpu.jit.save
via jax.export), which every XLA runtime consumes directly; ONNX export would
require the external paddle2onnx-equivalent converter, which is unavailable
in this environment.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "ONNX export is not available (no converter in this environment). "
        "Use paddle_tpu.jit.save(layer, path, input_spec=...) — it emits a "
        "portable serialized-StableHLO artifact, the TPU-native deployment "
        "format.")
