"""paddle_tpu.onnx (reference: python/paddle/onnx — paddle2onnx export).

``export`` emits genuine ONNX ModelProto bytes (hand-written wire format,
opset 13) for Sequential MLP/CNN models — see export.py for the supported
layer set. The TPU-native deployment format remains serialized StableHLO
(paddle_tpu.jit.save via jax.export), which every XLA runtime consumes
directly; use it for arbitrary models.
"""
from __future__ import annotations

from . import proto  # noqa: F401
from .export import export  # noqa: F401

__all__ = ["export", "proto"]
