"""XPlane analysis: compute/communication breakdown + overlap.

Reference: the profiler statistic tables (profiler_statistic.py:
Communication/Computation overlap summaries) and CrossStackProfiler. The
jax profiler writes XLA's xplane.pb; comm ops (all-reduce / all-gather /
reduce-scatter / collective-permute / all-to-all) and compute ops are
classified by event name and their wall-clock intervals intersected —
overlap% is how much collective time hides under compute, the number the
allreduce_matmul_grad_overlapping pass optimizes for in the reference.
"""
from __future__ import annotations

import glob
import os

__all__ = ["parse_xplane", "comm_compute_breakdown", "to_chrome_trace"]

_COMM_MARKERS = ("all-reduce", "all-gather", "reduce-scatter",
                 "collective-permute", "all-to-all", "psum",
                 "rendezvous", "ncclKernel", "send", "recv")
_SKIP = ("ThreadpoolListener", "ThunkExecutor", "Wait for",
         "ExecuteHelper", "Handle inputs", "CreateOutputs",
         "StartRegion", "StopRegion", "CollectGarbage", "end:")


def _latest_xplane(logdir):
    pbs = sorted(glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                           recursive=True), key=os.path.getmtime)
    if not pbs:
        raise FileNotFoundError(f"no xplane.pb under {logdir}")
    return pbs[-1]


def parse_xplane(path_or_logdir):
    """-> list of (thread_line_name, event_name, start_ps, dur_ps) for the
    device-execution lines of the newest trace."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    path = path_or_logdir
    if os.path.isdir(path):
        path = _latest_xplane(path)
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())
    events = []
    for plane in xs.planes:
        meta = {k: v.name for k, v in plane.event_metadata.items()}
        for line in plane.lines:
            # device-execution lines: TPU streams or CPU client threads.
            # The CPU client thread-line name varies by jax/xla version:
            # "XLAPjRtCpuClient" (older), "XLATfrtCpuClient" (jax 0.4.3x
            # TFRT CPU client, e.g. "tf_XLATfrtCpuClient/<tid>").
            is_dev = ("XLAPjRtCpuClient" in line.name
                      or "XLATfrtCpuClient" in line.name
                      or plane.name.startswith("/device:"))
            if not is_dev:
                continue
            base_ps = line.timestamp_ns * 1000
            for ev in line.events:
                name = meta.get(ev.metadata_id, "")
                if not name or any(s in name for s in _SKIP):
                    continue
                events.append((line.name, name,
                               base_ps + ev.offset_ps, ev.duration_ps))
    return events


def _merge(intervals):
    out = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _total(intervals):
    return sum(e - s for s, e in intervals)


def _intersect(a, b):
    out, i, j = [], 0, 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if s < e:
            out.append((s, e))
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return out


def to_chrome_trace(path_or_logdir, pid=0, label="device", shift_us=0.0):
    """Convert the device-execution lines of an xplane trace into a
    chrome-trace dict, mergeable with the host-span export of
    :mod:`paddle_tpu.observability.tracing` via
    ``python -m paddle_tpu.tools.merge_profiles`` (which accepts xplane
    log dirs directly). Each device line becomes a tid lane; comm ops are
    categorized ``collective`` so they share a color with the host-side
    collective events.

    ``shift_us`` offsets every event timestamp — the clock-alignment
    hook: xplane stamps come from the profiler's own clock domain (device
    clocks calibrated to the XLA host timer), while host spans stamp
    ``time.time()``; the merge tool's ``--align`` computes the shift so
    both lanes line up in one Perfetto view. The returned dict carries
    the applied shift and the raw first-event stamp in a
    ``clock_domain`` metadata event so the alignment is auditable."""
    events = parse_xplane(path_or_logdir)
    tids = {}
    out = [{"name": "process_name", "ph": "M", "pid": pid,
            "args": {"name": label}}]
    first_raw_us = min((s / 1e6 for _, _, s, _ in events), default=None)
    out.append({"name": "clock_domain", "ph": "M", "pid": pid,
                "args": {"domain": "xplane", "shift_us": float(shift_us),
                         "first_event_raw_us": first_raw_us}})
    for line_name, name, start_ps, dur_ps in events:
        tid = tids.setdefault(line_name, len(tids))
        lo = name.lower()
        cat = "collective" if any(m in lo for m in _COMM_MARKERS) \
            else "device"
        out.append({"name": name, "ph": "X", "pid": pid, "tid": tid,
                    "ts": start_ps / 1e6 + shift_us, "dur": dur_ps / 1e6,
                    "cat": cat})
    for line_name, tid in tids.items():
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": line_name}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def comm_compute_breakdown(path_or_logdir):
    """-> dict with compute_us, comm_us, overlap_us, comm_overlap_pct
    (fraction of collective time hidden under concurrent compute)."""
    events = parse_xplane(path_or_logdir)
    comm, compute = [], []
    for _line, name, start, dur in events:
        lo = name.lower()
        (comm if any(m in lo for m in _COMM_MARKERS)
         else compute).append((start, start + dur))
    comm_m = _merge(comm)
    compute_m = _merge(compute)
    overlap = _total(_intersect(comm_m, compute_m))
    comm_t = _total(comm_m)
    return {
        "compute_us": _total(compute_m) / 1e6,
        "comm_us": comm_t / 1e6,
        "overlap_us": overlap / 1e6,
        "comm_overlap_pct": (100.0 * overlap / comm_t) if comm_t else 0.0,
        "n_events": len(events),
    }
