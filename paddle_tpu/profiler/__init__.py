"""paddle_tpu.profiler — tracing/profiling over the jax/XLA profiler.

Reference: python/paddle/profiler/profiler.py:346 (Profiler) over the C++
host/CUPTI tracers (SURVEY §5 tracing). TPU-native: device timelines come
from the XLA profiler (xplane → TensorBoard/Perfetto); ``RecordEvent`` user
scopes map onto jax.profiler.TraceAnnotation so they appear inline in the
device trace. ``benchmark``-style summaries are derived host-side.
"""
from __future__ import annotations

import contextlib
import time

import jax

__all__ = ["Profiler", "RecordEvent", "ProfilerTarget", "profiler_guard",
           "load_profiler_result"]


class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"
    CUSTOM_DEVICE = "tpu"
    TPU = "tpu"


class RecordEvent:
    """User-scope annotation (reference: profiler/utils.py RecordEvent).
    Appears in the xplane trace and accumulates host-side timing."""

    _stats: dict = {}

    def __init__(self, name, event_type=None):
        self.name = name
        self._ann = jax.profiler.TraceAnnotation(name)
        self._t0 = None

    def begin(self):
        self.__enter__()

    def end(self):
        self.__exit__(None, None, None)

    def __enter__(self):
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        total, count = RecordEvent._stats.get(self.name, (0.0, 0))
        RecordEvent._stats[self.name] = (total + dt, count + 1)
        self._ann.__exit__(*exc)
        return False


class Profiler:
    """Reference: paddle.profiler.Profiler (profiler/profiler.py:346).

    on_trace_ready/export write an XLA trace directory consumable by
    TensorBoard (xplane) — the chrome-trace export of the reference.
    """

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False, log_dir="./profiler_log"):
        self.log_dir = log_dir
        self.timer_only = timer_only
        self.on_trace_ready = on_trace_ready
        self._running = False
        self._step_times = []
        self._last_step = None

    def start(self):
        if not self.timer_only:
            jax.profiler.start_trace(self.log_dir)
        self._running = True
        self._last_step = time.perf_counter()
        return self

    def stop(self):
        if self._running and not self.timer_only:
            jax.profiler.stop_trace()
        self._running = False
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last_step is not None:
            self._step_times.append(now - self._last_step)
        self._last_step = now

    def step_info(self, unit=None):
        if not self._step_times:
            return "no steps recorded"
        import numpy as np
        arr = np.array(self._step_times)
        return (f"steps: {len(arr)}  avg: {arr.mean()*1e3:.2f} ms  "
                f"p50: {np.percentile(arr, 50)*1e3:.2f} ms  "
                f"max: {arr.max()*1e3:.2f} ms")

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        lines = ["---- paddle_tpu profiler summary (host scopes) ----"]
        for name, (total, count) in sorted(RecordEvent._stats.items(),
                                           key=lambda kv: -kv[1][0]):
            lines.append(f"{name:40s} calls={count:6d} "
                         f"total={total*1e3:10.2f} ms "
                         f"avg={total/max(count,1)*1e3:8.3f} ms")
        lines.append(self.step_info())
        out = "\n".join(lines)
        print(out)
        return out

    def export(self, path=None, format=None):  # noqa: A002
        return self.log_dir

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


@contextlib.contextmanager
def profiler_guard(**kwargs):
    p = Profiler(**kwargs)
    p.start()
    try:
        yield p
    finally:
        p.stop()


def load_profiler_result(path):
    raise NotImplementedError(
        "open the exported trace directory with TensorBoard "
        "(xplane format) instead")
