"""paddle_tpu.profiler — tracing/profiling over the jax/XLA profiler.

Reference: python/paddle/profiler/profiler.py:346 (Profiler) over the C++
host/CUPTI tracers (SURVEY §5 tracing). TPU-native: device timelines come
from the XLA profiler (xplane → TensorBoard/Perfetto); ``RecordEvent`` user
scopes map onto jax.profiler.TraceAnnotation so they appear inline in the
device trace. ``benchmark``-style summaries are derived host-side.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time

import jax

__all__ = ["Profiler", "RecordEvent", "ProfilerTarget", "profiler_guard",
           "load_profiler_result", "merge_profiler_results"]


class _OpTracer:
    """Host-side per-op tracer fed by the dispatch hook (reference: the
    host tracer half of platform/profiler — op events with timestamps,
    durations, call counts, and input signatures).

    profile_memory: framework-level allocation accounting (reference:
    platform/profiler/mem_tracing.h) — each op's output bytes count as an
    allocation, a weakref finalizer on the output Tensor records the free,
    and (live, peak) counters produce the memory timeline."""

    def __init__(self, record_shapes=False, profile_memory=False):
        self.events = []          # (name, t0, t1, shapes, out_bytes)
        self.record_shapes = record_shapes
        self.profile_memory = profile_memory
        self.live_bytes = 0
        self.peak_bytes = 0
        self.mem_events = []      # (ts, live_bytes)
        self.mem_table: dict = {}  # op -> total allocated bytes
        self._lock = threading.Lock()

    def _on_free(self, nbytes):
        with self._lock:
            self.live_bytes -= nbytes
            self.mem_events.append((time.perf_counter(), self.live_bytes))

    def _note_outputs(self, name, result):
        import weakref

        import jax as _jax
        out_bytes = 0
        res = result if isinstance(result, (tuple, list)) else (result,)
        for t in res:
            arr = getattr(t, "_data", None)
            if arr is None or isinstance(arr, _jax.core.Tracer):
                continue
            nb = int(getattr(arr, "nbytes", 0) or 0)
            if nb and t is not None:
                out_bytes += nb
                weakref.finalize(t, self._on_free, nb)
        with self._lock:
            self.live_bytes += out_bytes
            self.peak_bytes = max(self.peak_bytes, self.live_bytes)
            self.mem_events.append((time.perf_counter(), self.live_bytes))
            self.mem_table[name] = self.mem_table.get(name, 0) + out_bytes
        return out_bytes

    def __call__(self, name, t0, t1, inputs, result=None):
        shapes = None
        if self.record_shapes:
            shapes = [tuple(getattr(t, "shape", ())) for t in inputs]
        out_bytes = 0
        if self.profile_memory and result is not None:
            out_bytes = self._note_outputs(name, result)
        with self._lock:
            self.events.append((name, t0, t1, shapes, out_bytes))

    def op_table(self):
        agg = {}
        for name, t0, t1, _, _ in self.events:
            total, count, mx = agg.get(name, (0.0, 0, 0.0))
            dt = t1 - t0
            agg[name] = (total + dt, count + 1, max(mx, dt))
        return agg


class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"
    CUSTOM_DEVICE = "tpu"
    TPU = "tpu"


class RecordEvent:
    """User-scope annotation (reference: profiler/utils.py RecordEvent).
    Appears in the xplane trace and accumulates host-side timing."""

    _stats: dict = {}

    def __init__(self, name, event_type=None):
        self.name = name
        self._ann = jax.profiler.TraceAnnotation(name)
        self._t0 = None

    def begin(self):
        self.__enter__()

    def end(self):
        self.__exit__(None, None, None)

    def __enter__(self):
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        total, count = RecordEvent._stats.get(self.name, (0.0, 0))
        RecordEvent._stats[self.name] = (total + dt, count + 1)
        self._ann.__exit__(*exc)
        return False


class Profiler:
    """Reference: paddle.profiler.Profiler (profiler/profiler.py:346).

    on_trace_ready/export write an XLA trace directory consumable by
    TensorBoard (xplane) — the chrome-trace export of the reference.
    """

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False, log_dir="./profiler_log"):
        self.log_dir = log_dir
        self.timer_only = timer_only
        self.on_trace_ready = on_trace_ready
        self._running = False
        self._step_times = []
        self._last_step = None
        self.profile_memory = profile_memory
        self._step_device_mem = []   # per-step device memory_stats rows
        self._op_tracer = _OpTracer(record_shapes=record_shapes,
                                    profile_memory=profile_memory)

    def start(self):
        if not self.timer_only:
            jax.profiler.start_trace(self.log_dir)
        from ..core import dispatch as _dispatch
        _dispatch._op_profiler = self._op_tracer
        self._running = True
        self._last_step = time.perf_counter()
        return self

    def stop(self):
        from ..core import dispatch as _dispatch
        if _dispatch._op_profiler is self._op_tracer:  # only clear our own
            _dispatch._op_profiler = None
        if self._running and not self.timer_only:
            jax.profiler.stop_trace()
        self._running = False
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last_step is not None:
            self._step_times.append(now - self._last_step)
        self._last_step = now
        if self.profile_memory:
            # device truth when the runtime exposes it (TPU HBM), else the
            # host-side live/peak accounting stands alone
            stats = None
            try:
                stats = jax.devices()[0].memory_stats()
            except Exception:
                pass
            self._step_device_mem.append({
                "ts": now,
                "tracked_live_bytes": self._op_tracer.live_bytes,
                "tracked_peak_bytes": self._op_tracer.peak_bytes,
                "device_bytes_in_use": (stats or {}).get("bytes_in_use"),
                "device_peak_bytes_in_use":
                    (stats or {}).get("peak_bytes_in_use"),
            })

    def step_info(self, unit=None):
        if not self._step_times:
            return "no steps recorded"
        import numpy as np
        arr = np.array(self._step_times)
        return (f"steps: {len(arr)}  avg: {arr.mean()*1e3:.2f} ms  "
                f"p50: {np.percentile(arr, 50)*1e3:.2f} ms  "
                f"max: {arr.max()*1e3:.2f} ms")

    # -- memory timeline, public surface (ISSUE satellite: _OpTracer
    # collected these but nothing machine-readable surfaced them) --
    @property
    def peak_bytes(self):
        """Peak tracked live allocation bytes (profile_memory=True)."""
        return self._op_tracer.peak_bytes

    @property
    def live_bytes(self):
        """Currently tracked live allocation bytes."""
        return self._op_tracer.live_bytes

    def summary_dict(self):
        """Machine-readable companion of :meth:`summary`: op table plus
        the memory timeline peaks (``peak_bytes`` / ``live_bytes`` — the
        host-side accounting; device HBM peaks ride the per-step rows
        when the runtime exposes memory_stats)."""
        t = self._op_tracer
        out = {
            "peak_bytes": t.peak_bytes,
            "live_bytes": t.live_bytes,
            "mem_events": len(t.mem_events),
            "mem_table": dict(t.mem_table),
            "op_table": {name: {"total_s": total, "calls": count,
                                "max_s": mx}
                         for name, (total, count, mx)
                         in t.op_table().items()},
            "steps": len(self._step_times),
        }
        if self._step_times:
            out["avg_step_ms"] = (sum(self._step_times)
                                  / len(self._step_times) * 1e3)
        if self._step_device_mem:
            out["device_mem"] = list(self._step_device_mem)
        return out

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        lines = ["---- paddle_tpu profiler summary ----"]
        if op_detail and self._op_tracer.events:
            lines.append("-- op-level (host dispatch) "
                         "(reference: profiler_statistic.py op table) --")
            lines.append(f"{'op':28s} {'calls':>7s} {'total ms':>10s} "
                         f"{'avg ms':>9s} {'max ms':>9s}")
            table = self._op_tracer.op_table()
            for name, (total, count, mx) in sorted(
                    table.items(), key=lambda kv: -kv[1][0]):
                lines.append(f"{name:28s} {count:7d} {total*1e3:10.2f} "
                             f"{total/count*1e3:9.3f} {mx*1e3:9.3f}")
        if self.profile_memory:
            t = self._op_tracer
            lines.append("-- memory (reference: mem_tracing.h) --")
            lines.append(f"tracked peak: {t.peak_bytes/2**20:.2f} MB  "
                         f"live: {t.live_bytes/2**20:.2f} MB  "
                         f"alloc events: {len(t.mem_events)}")
            for name, b in sorted(t.mem_table.items(),
                                  key=lambda kv: -kv[1])[:15]:
                lines.append(f"{name:28s} allocated {b/2**20:10.3f} MB")
            for row in self._step_device_mem[-3:]:
                if row["device_peak_bytes_in_use"] is not None:
                    lines.append(
                        f"device peak bytes in use: "
                        f"{row['device_peak_bytes_in_use']/2**20:.2f} MB")
        if RecordEvent._stats:
            lines.append("-- user scopes --")
            for name, (total, count) in sorted(RecordEvent._stats.items(),
                                               key=lambda kv: -kv[1][0]):
                lines.append(f"{name:40s} calls={count:6d} "
                             f"total={total*1e3:10.2f} ms "
                             f"avg={total/max(count,1)*1e3:8.3f} ms")
        lines.append(self.step_info())
        out = "\n".join(lines)
        print(out)
        return out

    def export(self, path=None, format=None):  # noqa: A002
        """format='chrome' (or a .json path) writes a chrome://tracing /
        Perfetto-loadable trace of the host op events (reference:
        chrometracing_logger.cc); otherwise returns the xplane log dir."""
        if format == "chrome" or (path and str(path).endswith(".json")):
            path = path or os.path.join(self.log_dir, "host_trace.json")
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            events = []
            for name, t0, t1, shapes, out_bytes in self._op_tracer.events:
                ev = {"name": name, "ph": "X", "pid": 0, "tid": 0,
                      "ts": t0 * 1e6, "dur": (t1 - t0) * 1e6,
                      "cat": "op"}
                args = {}
                if shapes:
                    args["input_shapes"] = [str(s) for s in shapes]
                if out_bytes:
                    args["output_bytes"] = out_bytes
                if args:
                    ev["args"] = args
                events.append(ev)
            # memory counter track (reference: mem_tracing allocation
            # events in the chrome trace)
            for ts, live in self._op_tracer.mem_events:
                events.append({"name": "memory", "ph": "C", "pid": 0,
                               "ts": ts * 1e6, "cat": "memory",
                               "args": {"live_bytes": int(live)}})
            with open(path, "w") as f:
                json.dump({"traceEvents": events,
                           "displayTimeUnit": "ms"}, f)
            return path
        return self.log_dir

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


@contextlib.contextmanager
def profiler_guard(**kwargs):
    p = Profiler(**kwargs)
    p.start()
    try:
        yield p
    finally:
        p.stop()


def load_profiler_result(path):
    """Load a chrome-trace json exported by Profiler.export."""
    if os.path.isfile(path):
        with open(path) as f:
            return json.load(f)
    raise ValueError(
        f"{path!r} is not a chrome-trace json; xplane directories are "
        "viewed with TensorBoard instead")


def _trace_min_ts(d):
    return min((ev["ts"] for ev in d.get("traceEvents", [])
                if ev.get("ph") == "X"), default=None)


def _is_xplane_domain(d):
    for ev in d.get("traceEvents", []):
        if ev.get("ph") == "M" and ev.get("name") == "clock_domain" \
                and (ev.get("args") or {}).get("domain") == "xplane":
            return True
    return False


def merge_profiler_results(paths, out_path=None, labels=None, align=False,
                           align_threshold_s=60.0):
    """Multi-rank trace merge (reference: CrossStackProfiler — the
    multi-node profiler aggregation tool). Each input chrome trace (one
    per rank, as exported by Profiler.export on that rank, or a host-span
    export from observability.tracing, or an xplane-derived device trace)
    lands on its own pid lane, labeled ``labels[i]`` (default rank_N); a
    process_name metadata event names the lane. Returns the merged dict
    (and writes it when out_path given).

    ``align=True`` performs trace/xplane clock alignment (overlap-engine
    measurement loop): xplane-derived device traces stamp the profiler's
    clock domain, host-span traces stamp ``time.time()`` — when the two
    disagree by more than ``align_threshold_s`` (clearly different
    domains, not real skew) every device lane is shifted so its earliest
    event lands on the earliest host event, and the applied shift is
    recorded in the lane's ``clock_domain`` metadata. Same-domain traces
    are never touched (a shift there would falsify real cross-rank
    skew)."""
    merged = {"traceEvents": [], "displayTimeUnit": "ms"}
    loaded = [(p if isinstance(p, dict) else load_profiler_result(p))
              for p in paths]
    shifts = [0.0] * len(loaded)
    if align:
        host_anchor = min(
            (t for d, t in ((d, _trace_min_ts(d)) for d in loaded)
             if t is not None and not _is_xplane_domain(d)), default=None)
        if host_anchor is not None:
            for i, d in enumerate(loaded):
                if not _is_xplane_domain(d):
                    continue
                t0 = _trace_min_ts(d)
                if t0 is not None and \
                        abs(t0 - host_anchor) > align_threshold_s * 1e6:
                    shifts[i] = host_anchor - t0
    for rank, d in enumerate(loaded):
        label = labels[rank] if labels and rank < len(labels) \
            else f"rank_{rank}"
        merged["traceEvents"].append({
            "name": "process_name", "ph": "M", "pid": rank,
            "args": {"name": label}})
        for ev in d.get("traceEvents", []):
            ev = dict(ev)
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                continue  # the input's own lane label: superseded
            if ev.get("ph") == "M" and ev.get("name") == "clock_domain" \
                    and shifts[rank]:
                ev["args"] = dict(ev.get("args") or {},
                                  applied_shift_us=shifts[rank])
            if shifts[rank] and "ts" in ev:
                ev["ts"] = ev["ts"] + shifts[rank]
            ev["pid"] = rank
            merged["traceEvents"].append(ev)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(merged, f)
    return merged
