"""paddle_tpu.strings — string tensors + tokenizer kernels.

Reference: ``paddle/phi/core/string_tensor.h`` (StringTensor),
``paddle/phi/kernels/strings/`` (empty/copy/lower/upper over pstring data +
``unicode.h`` case tables), and ``paddle/fluid/operators/string/
faster_tokenizer_op.h`` (BasicTokenizer → WordPieceTokenizer pipeline that
turns raw text into input_ids/token_type_ids inside the graph).

TPU-native design: XLA has no string dtype, so string storage and
transformation are host ops by construction (they are CPU-pinned in the
reference too); the tokenizer's OUTPUT (ids/segments) is where the device
path begins. StringTensor wraps a numpy object array; FasterTokenizer
produces padded int32 jax arrays ready to feed an embedding on device.
"""
from __future__ import annotations

import unicodedata

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["StringTensor", "to_string_tensor", "empty", "empty_like",
           "copy", "lower", "upper", "BasicTokenizer", "WordPieceTokenizer",
           "FasterTokenizer"]


class StringTensor:
    """Host string tensor (reference: phi/core/string_tensor.h — pstring
    payloads with a DDim; device kernels are CPU-only there as well)."""

    def __init__(self, data, name=None):
        arr = np.asarray(data, dtype=object)
        self._data = arr
        self.name = name

    @property
    def shape(self):
        return list(self._data.shape)

    def numpy(self):
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, data={self._data!r})"


def to_string_tensor(data, name=None):
    return StringTensor(data, name=name)


def empty(shape, name=None):
    """Reference: strings_empty_kernel — a StringTensor of empty strings."""
    return StringTensor(np.full(tuple(shape), "", dtype=object))


def empty_like(x, name=None):
    return empty(x.shape)


def copy(x, name=None):
    return StringTensor(x._data.copy())


def _case_map(x, fn, use_utf8_encoding):
    # use_utf8_encoding=False: ASCII-only case map (reference
    # strings_lower_upper_kernel AsciiCaseConverter); True: full unicode
    # (UTF8CaseConverter over unicode.h tables)
    if use_utf8_encoding:
        conv = fn
    else:
        def conv(s):
            return "".join(fn(c) if ord(c) < 128 else c for c in s)
    out = np.empty_like(x._data)
    it = np.nditer(x._data, flags=["multi_index", "refs_ok"])
    for _ in it:
        out[it.multi_index] = conv(str(x._data[it.multi_index]))
    return StringTensor(out)


def lower(x, use_utf8_encoding=False, name=None):
    """Reference: phi strings_lower_upper_kernel StringLower."""
    return _case_map(x, str.lower, use_utf8_encoding)


def upper(x, use_utf8_encoding=False, name=None):
    """Reference: phi strings_lower_upper_kernel StringUpper."""
    return _case_map(x, str.upper, use_utf8_encoding)


def _is_punct(ch):
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) \
            or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_chinese_char(cp):
    return ((0x4E00 <= cp <= 0x9FFF) or (0x3400 <= cp <= 0x4DBF)
            or (0x20000 <= cp <= 0x2A6DF) or (0x2A700 <= cp <= 0x2B73F)
            or (0x2B740 <= cp <= 0x2B81F) or (0x2B820 <= cp <= 0x2CEAF)
            or (0xF900 <= cp <= 0xFAFF) or (0x2F800 <= cp <= 0x2FA1F))


class BasicTokenizer:
    """Whitespace/punctuation/CJK splitting + optional lower/strip-accents
    (reference: faster_tokenizer_op.h:45 BasicTokenizer)."""

    def __init__(self, do_lower_case=True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text):
        if self.do_lower_case:
            text = text.lower()
            text = "".join(c for c in unicodedata.normalize("NFD", text)
                           if unicodedata.category(c) != "Mn")
        out = []
        for ch in text:
            if _is_chinese_char(ord(ch)):
                out.append(f" {ch} ")
            elif _is_punct(ch):
                out.append(f" {ch} ")
            elif ch.isspace():
                out.append(" ")
            elif ord(ch) == 0 or ord(ch) == 0xFFFD:
                continue
            else:
                out.append(ch)
        return "".join(out).split()


class WordPieceTokenizer:
    """Greedy longest-match-first subword split (reference:
    faster_tokenizer_op.h:56)."""

    def __init__(self, vocab, unk_token="[UNK]", max_input_chars_per_word
                 =100):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_chars = max_input_chars_per_word

    def tokenize(self, word):
        if len(word) > self.max_chars:
            return [self.vocab.get(self.unk_token, 0)]
        ids = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    cur = self.vocab[sub]
                    break
                end -= 1
            if cur is None:
                return [self.vocab.get(self.unk_token, 0)]
            ids.append(cur)
            start = end
        return ids


class FasterTokenizer:
    """BERT-style text → (input_ids, token_type_ids) as device-ready int32
    tensors (reference: faster_tokenizer_op.h FasterTokenizerKernel — the
    op form of tokenization so serving graphs embed it; here the host op
    feeds jax arrays straight to the embedding)."""

    def __init__(self, vocab, do_lower_case=True, unk_token="[UNK]",
                 cls_token="[CLS]", sep_token="[SEP]", pad_token="[PAD]"):
        if not isinstance(vocab, dict):
            vocab = {tok: i for i, tok in enumerate(vocab)}
        self.vocab = vocab
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordPieceTokenizer(vocab, unk_token)
        self.cls_id = vocab.get(cls_token, 0)
        self.sep_id = vocab.get(sep_token, 0)
        self.pad_id = vocab.get(pad_token, 0)

    def _encode_one(self, text):
        ids = []
        for word in self.basic.tokenize(str(text)):
            ids.extend(self.wordpiece.tokenize(word))
        return ids

    def __call__(self, text, text_pair=None, max_seq_len=None,
                 is_split_into_words=False, pad_to_max_seq_len=False):
        texts = (text.tolist() if isinstance(text, StringTensor)
                 else ([text] if isinstance(text, str) else list(text)))
        pairs = None
        if text_pair is not None:
            pairs = (text_pair.tolist()
                     if isinstance(text_pair, StringTensor)
                     else ([text_pair] if isinstance(text_pair, str)
                           else list(text_pair)))
        rows, segs = [], []
        for i, tx in enumerate(texts):
            ids = [self.cls_id] + self._encode_one(tx) + [self.sep_id]
            seg = [0] * len(ids)
            if pairs is not None:
                p = self._encode_one(pairs[i]) + [self.sep_id]
                ids += p
                seg += [1] * len(p)
            if max_seq_len and len(ids) > max_seq_len:
                ids = ids[:max_seq_len - 1] + [self.sep_id]
                seg = seg[:max_seq_len]
            rows.append(ids)
            segs.append(seg)
        width = max(len(r) for r in rows)
        if pad_to_max_seq_len and max_seq_len:
            width = max_seq_len
        out = np.full((len(rows), width), self.pad_id, np.int32)
        seg_out = np.zeros((len(rows), width), np.int32)
        for i, (r, s) in enumerate(zip(rows, segs)):
            out[i, :len(r)] = r
            seg_out[i, :len(s)] = s
        return (Tensor(jnp.asarray(out)), Tensor(jnp.asarray(seg_out)))
