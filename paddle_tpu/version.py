"""Version info (reference: python/paddle/version.py, generated at build).
The rebuild tracks reference capability snapshot 2.6-dev."""
full_version = "2.6.0+tpu"
major = "2"
minor = "6"
patch = "0"
rc = "0"
commit = "tpu-native-rebuild"


def show():
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")
