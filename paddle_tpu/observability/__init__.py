"""paddle_tpu.observability — run telemetry for real training jobs.

Four small, stdlib-only-at-import pieces:

* :mod:`.metrics` — env-gated (``PADDLE_TPU_METRICS=1``) Counter/Gauge/
  Histogram registry with per-rank JSONL snapshots in the workerlog dir.
* :mod:`.telemetry` — per-step clock threaded through ``hapi.Model.fit``
  / ``Engine.fit``: step-time breakdown (data-wait/compute/sync),
  tokens/sec, MFU estimate.
* :mod:`.tracing` — ``span("fwd")`` host spans + flight-recorder
  collective events exported as Chrome-trace/Perfetto JSON
  (``PADDLE_TPU_TRACE=1``), mergeable with the xplane device timeline
  via ``python -m paddle_tpu.tools.merge_profiles``.
* :mod:`.report` — launcher-side aggregation of the per-rank JSONL into
  a one-screen cross-rank run report (slowest rank, p50/p99 collective
  latency, comm/compute, MFU).

The serving tier (``paddle_tpu/serving``) feeds the same registry:
``serving_ttft_ms`` / ``serving_inter_token_ms`` / ``serving_e2e_ms``
histograms plus QPS / tokens-per-sec / KV-occupancy gauges land in the
per-rank JSONL next to the training metrics.

Disabled (the default), every hook in the hot paths is a constant-time
no-op — asserted by tests the same way as the flight recorder's disabled
path.
"""
from . import metrics  # noqa: F401
from . import report  # noqa: F401
from . import telemetry  # noqa: F401
from . import tracing  # noqa: F401
from .metrics import MetricsRegistry, get_registry  # noqa: F401
from .telemetry import TelemetryCallback  # noqa: F401
from .tracing import span  # noqa: F401

__all__ = ["metrics", "telemetry", "tracing", "report",
           "MetricsRegistry", "TelemetryCallback", "get_registry", "span"]
