"""Cross-rank run report — the launcher's performance post-mortem.

Reads the per-rank ``metrics.<rank>.jsonl`` snapshot files the metrics
registry writes into the workerlog dir and renders a one-screen report:
per-rank step time / data wait / tokens/sec / MFU, the slowest rank (and
how many snapshot windows each rank was the straggler of — a rank that is
slowest in every window is degrading hardware, one that is slowest once
hit a GC pause), p50/p99 per-collective latency and the comm/compute
ratio. The launcher prints it at round end AND from the failure
post-mortem path, so the PR-4 node coordinator doubles as a live
straggler detector.

Also a CLI::

    python -m paddle_tpu.observability.report <log_dir>

Stdlib-only — the launcher imports this without loading jax.
"""
from __future__ import annotations

import glob
import json
import os
import sys

from .metrics import hist_mean, hist_quantile, parse_metric_key

__all__ = ["read_rank_snapshots", "build_run_report", "format_run_report",
           "main"]


def read_rank_snapshots(log_dir):
    """-> {rank: [snapshot dict, ...]} from metrics.*.jsonl under
    ``log_dir`` (unparseable lines are skipped, not fatal: a worker
    killed mid-write leaves a torn last line)."""
    out = {}
    for p in sorted(glob.glob(os.path.join(log_dir, "metrics.*.jsonl"))):
        try:
            rank = int(os.path.basename(p).split(".")[1])
        except (IndexError, ValueError):
            continue
        snaps = []
        try:
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        snaps.append(json.loads(line))
                    except ValueError:
                        pass
        except OSError:
            continue
        if snaps:
            out[rank] = snaps
    return out


def _merge_hist(a, b):
    if a is None:
        return dict(b)
    if list(a.get("bounds", [])) != list(b.get("bounds", [])):
        return a  # mismatched layouts: keep the first
    a["counts"] = [x + y for x, y in zip(a["counts"], b["counts"])]
    a["count"] = a.get("count", 0) + b.get("count", 0)
    a["sum"] = a.get("sum", 0.0) + b.get("sum", 0.0)
    for k, f in (("min", min), ("max", max)):
        if b.get(k) is not None:
            a[k] = b[k] if a.get(k) is None else f(a[k], b[k])
    return a


def _hist_delta(new, old):
    """Window histogram between two cumulative snapshots of one rank."""
    if old is None:
        return dict(new)
    if list(new.get("bounds", [])) != list(old.get("bounds", [])):
        return dict(new)
    return {"bounds": new["bounds"],
            "counts": [n - o for n, o in zip(new["counts"],
                                            old["counts"])],
            "count": new.get("count", 0) - old.get("count", 0),
            "sum": new.get("sum", 0.0) - old.get("sum", 0.0),
            "min": new.get("min"), "max": new.get("max")}


def _bucket_windows(rank_windows, default_width_s=10.0):
    """Align per-rank (ts, mean-step-ms) windows into wall-clock buckets:
    ``{bucket_index: {rank: mean}}``. Bucket width = the median
    inter-snapshot interval across all ranks (falling back to the 10s
    default flush interval), anchored at the earliest snapshot. Two
    windows of one rank landing in the same bucket (an extra step-count
    flush) are averaged, and a rank that flushed late simply lands in the
    later bucket instead of shifting every subsequent comparison."""
    deltas = []
    all_ts = []
    for wins in rank_windows.values():
        all_ts.extend(ts for ts, _ in wins)
        deltas.extend(b - a for (a, _), (b, _) in zip(wins, wins[1:])
                      if b > a)
    if not all_ts:
        return {}
    if deltas:
        deltas.sort()
        width = deltas[len(deltas) // 2]
    else:
        width = default_width_s
    width = max(width, 1e-3)
    t0 = min(all_ts)
    acc = {}   # bucket -> rank -> [sum, count]
    for rank, wins in rank_windows.items():
        for ts, m in wins:
            b = int((ts - t0) / width + 0.5)
            cell = acc.setdefault(b, {}).setdefault(rank, [0.0, 0])
            cell[0] += m
            cell[1] += 1
    return {b: {r: s / c for r, (s, c) in by_rank.items()}
            for b, by_rank in acc.items()}


def build_run_report(per_rank):
    """Aggregate per-rank snapshot lists into one report dict."""
    ranks = {}
    collectives = {}
    serving_hists = {}     # (engine, name) -> merged histogram
    serving_phases = {}    # (engine, phase) -> merged histogram
    serving_scalars = {}   # engine -> {row: value} (counters + gauges)
    integrity = {}         # anomalies by kind / rewinds / blamed ranks
    rank_windows = {}
    compute_ms_total = 0.0
    comm_us_total = 0.0
    overlap_pcts = []
    overlap_sources = set()
    for rank, snaps in sorted(per_rank.items()):
        last = snaps[-1]
        hists = last.get("histograms", {})
        gauges = last.get("gauges", {})
        counters = last.get("counters", {})
        st = hists.get("step_time_ms")
        row = {"snapshots": len(snaps),
               "steps": counters.get("steps_total", 0)}
        if st:
            row["step_ms_mean"] = hist_mean(st)
            row["step_ms_p50"] = hist_quantile(st, 0.5)
            row["step_ms_p99"] = hist_quantile(st, 0.99)
        dw = hists.get("data_wait_ms")
        if dw:
            row["data_wait_ms_mean"] = hist_mean(dw)
        cm = hists.get("compute_ms")
        if cm:
            compute_ms_total += cm.get("sum", 0.0)
        for key in ("tokens_per_sec", "mfu_pct"):
            if key in gauges:
                row[key] = gauges[key]
        if "comm_overlap_pct" in gauges:
            overlap_pcts.append(gauges["comm_overlap_pct"])
            # provenance: the overlap engine feeds the gauge in-run from
            # flight-recorder issue/wait stamps (counters present); the
            # bench xplane leg sets the bare gauge from a device trace
            if "comm_inflight_us_total" in counters:
                overlap_sources.add("in-run flight-recorder stamps")
            else:
                overlap_sources.add("device timeline")
        ranks[rank] = row
        # per-collective latency, merged across ranks. Store-backed
        # control-plane waits (TCPStore commit barriers group="store",
        # gloo barriers group="gloo", object collectives group="object"
        # — blocking store rendezvous, not wire transfer) stay in the
        # table — operators should see them — but are EXCLUDED from the
        # comm total: one store-long checkpoint barrier would otherwise
        # read as seconds of "communication"
        for key, h in hists.items():
            name, labels = parse_metric_key(key)
            if name in ("serving_ttft_ms", "serving_inter_token_ms",
                        "serving_e2e_ms", "serving_queue_wait_ms"):
                # per-engine serving tails (ISSUE 14 satellite): the
                # engine label makes N engines in one job attributable —
                # unlabeled single-engine runs aggregate under "-"
                skey = (labels.get("engine", "-"), name)
                serving_hists[skey] = _merge_hist(
                    serving_hists.get(skey), h)
                continue
            if name == "serving_phase_ms":
                # per-lifecycle-phase latency (ISSUE 20): the aggregate
                # view of the request-trace phase boundaries
                pkey = (labels.get("engine", "-"),
                        labels.get("phase", "?"))
                serving_phases[pkey] = _merge_hist(
                    serving_phases.get(pkey), h)
                continue
            if name != "collective_latency_us":
                continue
            group = labels.get("group", "?")
            ckey = (labels.get("kind", "?"), group)
            collectives[ckey] = _merge_hist(collectives.get(ckey), h)
            if group not in ("store", "gloo", "object"):
                comm_us_total += h.get("sum", 0.0)
        for key, v in counters.items():
            name, labels = parse_metric_key(key)
            if name == "serving_tokens_total":
                eng = labels.get("engine", "-")
                row = serving_scalars.setdefault(eng, {})
                row["tokens"] = row.get("tokens", 0) + int(v)
            elif name == "serving_requests_total":
                eng = labels.get("engine", "-")
                st = labels.get("status", "?")
                row = serving_scalars.setdefault(eng, {})
                k = f"requests_{st}"
                row[k] = row.get(k, 0) + int(v)
            elif name == "train_anomalies_total":
                kinds = integrity.setdefault("anomalies", {})
                k = labels.get("kind", "?")
                kinds[k] = kinds.get(k, 0) + int(v)
            elif name == "train_rewinds_total":
                integrity["rewinds"] = integrity.get("rewinds", 0) + int(v)
            elif name == "integrity_blames_total":
                blamed = integrity.setdefault("blamed", {})
                br = labels.get("rank", "?")
                blamed[br] = blamed.get(br, 0) + int(v)
        # straggler windows: mean step time per inter-snapshot window,
        # stamped with the NEW snapshot's wall-clock ts. Cross-rank
        # alignment happens below by TIMESTAMP bucket, not snapshot
        # index: ranks flushing at different times (extra step-count
        # flushes, a late joiner, a restarted worker) used to shift
        # their later windows against everyone else's, corrupting the
        # per-window straggler attribution.
        prev = None
        for snap in snaps:
            h = snap.get("histograms", {}).get("step_time_ms")
            if h is None:
                continue
            win = _hist_delta(h, prev)
            prev = h
            m = hist_mean(win)
            ts = snap.get("ts")
            if m is not None and ts is not None:
                rank_windows.setdefault(rank, []).append((float(ts), m))

    slowest = None
    with_steps = {r: row for r, row in ranks.items()
                  if row.get("step_ms_mean") is not None}
    if len(with_steps) >= 1:
        slowest = max(with_steps, key=lambda r:
                      with_steps[r]["step_ms_mean"])
    straggler_counts = {}
    for _, by_rank in _bucket_windows(rank_windows).items():
        if len(by_rank) < 2:
            continue
        worst = max(by_rank, key=lambda r: by_rank[r])
        straggler_counts[worst] = straggler_counts.get(worst, 0) + 1

    coll_rows = {}
    for (kind, group), h in sorted(collectives.items()):
        coll_rows[f"{kind}|{group}"] = {
            "count": h.get("count", 0),
            "mean_us": hist_mean(h),
            "p50_us": hist_quantile(h, 0.5),
            "p99_us": hist_quantile(h, 0.99),
        }

    serving_rows = {}
    _short = {"serving_ttft_ms": "ttft_ms",
              "serving_inter_token_ms": "itl_ms",
              "serving_e2e_ms": "e2e_ms",
              "serving_queue_wait_ms": "queue_wait_ms"}
    for (eng, name), h in sorted(serving_hists.items()):
        row = serving_rows.setdefault(eng, {})
        base = _short[name]
        row[f"{base}_p50"] = hist_quantile(h, 0.5)
        row[f"{base}_p99"] = hist_quantile(h, 0.99)
        row[f"{base}_count"] = h.get("count", 0)
    for eng, scal in serving_scalars.items():
        serving_rows.setdefault(eng, {}).update(scal)

    phase_rows = {}
    for (eng, phase), h in sorted(serving_phases.items()):
        row = phase_rows.setdefault(eng, {})
        row[phase] = {"count": h.get("count", 0),
                      "mean_ms": hist_mean(h),
                      "p50_ms": hist_quantile(h, 0.5),
                      "p99_ms": hist_quantile(h, 0.99)}

    report = {"ranks": ranks, "slowest_rank": slowest,
              "straggler_windows": straggler_counts,
              "collectives": coll_rows}
    if serving_rows:
        report["serving"] = serving_rows
    if phase_rows:
        report["serving_phases"] = phase_rows
    if integrity:
        report["integrity"] = integrity
    if compute_ms_total > 0:
        # host-visible (non-hidden) collective time vs compute time; the
        # device-truth overlap gauge (xplane-derived) wins when present
        report["comm_ms_total"] = comm_us_total / 1e3
        report["compute_ms_total"] = compute_ms_total
        report["comm_vs_compute_pct"] = (
            100.0 * (comm_us_total / 1e3) / compute_ms_total)
    if overlap_pcts:
        report["comm_overlap_pct"] = sum(overlap_pcts) / len(overlap_pcts)
        report["comm_overlap_source"] = " + ".join(sorted(overlap_sources))
    return report


def _fmt(v, nd=1):
    return "-" if v is None else f"{v:.{nd}f}"


def format_run_report(report):
    """One-screen text rendering; None when there is nothing to say."""
    ranks = report.get("ranks") or {}
    if not ranks:
        return None
    lines = [f"[telemetry] run report ({len(ranks)} rank(s)):"]
    lines.append("[telemetry]   rank  steps  step_ms(mean/p50/p99)  "
                 "data_wait_ms  tok/s     mfu%")
    for rank, row in sorted(ranks.items()):
        triple = "/".join(_fmt(row.get(k)) for k in
                          ("step_ms_mean", "step_ms_p50", "step_ms_p99"))
        lines.append(
            "[telemetry]   %-5d %-6d %-22s %-12s %-9s %s" % (
                rank, row.get("steps", 0), triple,
                _fmt(row.get("data_wait_ms_mean"), 2),
                _fmt(row.get("tokens_per_sec"), 0),
                _fmt(row.get("mfu_pct"), 2)))
    slowest = report.get("slowest_rank")
    if slowest is not None and len(ranks) > 1:
        row = ranks[slowest]
        wins = report.get("straggler_windows", {}).get(slowest, 0)
        lines.append(
            f"[telemetry] slowest rank {slowest}: mean step "
            f"{_fmt(row.get('step_ms_mean'))} ms"
            + (f", straggler in {wins} window(s)" if wins else ""))
    colls = report.get("collectives") or {}
    if colls:
        lines.append("[telemetry]   collective latency (us): "
                     "count  p50  p99")
        for key, row in sorted(colls.items()):
            lines.append(
                "[telemetry]     %-36s %-6d %-8s %s" % (
                    key, row.get("count", 0), _fmt(row.get("p50_us")),
                    _fmt(row.get("p99_us"))))
    serving = report.get("serving") or {}
    if serving:
        lines.append("[telemetry]   serving engines: "
                     "tokens  reqs_ok  ttft_p99_ms  itl_p99_ms")
        for eng, row in sorted(serving.items()):
            lines.append(
                "[telemetry]     %-10s %-7d %-8d %-12s %s" % (
                    eng, row.get("tokens", 0),
                    row.get("requests_ok", 0),
                    _fmt(row.get("ttft_ms_p99"), 2),
                    _fmt(row.get("itl_ms_p99"), 2)))
    phases = report.get("serving_phases") or {}
    if phases:
        lines.append("[telemetry]   serving phase latency "
                     "(p50/p99 ms):")
        for eng, row in sorted(phases.items()):
            cells = "  ".join(
                f"{ph}={_fmt(st.get('p50_ms'), 1)}/"
                f"{_fmt(st.get('p99_ms'), 1)}"
                for ph, st in sorted(row.items()))
            lines.append(f"[telemetry]     {eng:<10} {cells}")
    slo = report.get("slo_attribution") or []
    if slo:
        lines.append("[telemetry]   slowest traced requests "
                     "(phase-attributed, ms):")
        for r in slo:
            cells = "  ".join(
                f"{c}={_fmt(r.get(c + '_ms'), 1)}"
                for c in ("queue_wait", "prefill", "decode", "route")
                if r.get(c + "_ms") is not None)
            flags = ",".join(r.get("flags") or []) or "-"
            lines.append(
                f"[telemetry]     {r['trace'][:18]:<18} "
                f"e2e={_fmt(r.get('e2e_ms'), 1):<9} {cells}  "
                f"[{flags}]")
    integ = report.get("integrity") or {}
    if integ:
        anomalies = integ.get("anomalies") or {}
        an = ", ".join(f"{k}={v}" for k, v in sorted(anomalies.items())) \
            or "none"
        line = (f"[telemetry] integrity: anomalies {an}; "
                f"rewinds {integ.get('rewinds', 0)}")
        blamed = integ.get("blamed") or {}
        if blamed:
            line += "; blamed rank(s) " + ", ".join(
                f"{r} (x{n})" for r, n in sorted(blamed.items()))
        lines.append(line)
    if report.get("comm_overlap_pct") is not None:
        src = report.get("comm_overlap_source") or "device timeline"
        lines.append(f"[telemetry] comm/compute overlap: "
                     f"{report['comm_overlap_pct']:.1f}% ({src})")
    elif report.get("comm_vs_compute_pct") is not None:
        lines.append(
            f"[telemetry] host-visible comm vs compute: "
            f"{report['comm_vs_compute_pct']:.1f}% "
            f"({report['comm_ms_total']:.1f} / "
            f"{report['compute_ms_total']:.1f} ms)")
    return "\n".join(lines)


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m paddle_tpu.observability.report "
              "<log_dir> [--json]", file=sys.stderr)
        return 2
    log_dir = argv[0]
    report = build_run_report(read_rank_snapshots(log_dir))
    try:
        # per-request SLO attribution (ISSUE 20): when the log dir also
        # holds exported request traces, fold the top slowest into the
        # report — the aggregate phase tails above, the culprits below
        from . import trace_report as _tr
        rows = _tr.build_request_rows(_tr.load_events(log_dir))
        if rows:
            report["slo_attribution"] = _tr.rows_to_report(rows, top=5)
    except Exception:
        pass
    if "--json" in argv:
        print(json.dumps(report, indent=1, default=str))
        return 0
    text = format_run_report(report)
    if text is None:
        print(f"[telemetry] no metrics snapshots under {log_dir}",
              file=sys.stderr)
        return 1
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
