"""Per-step training telemetry — step-time breakdown, tokens/sec, MFU.

Threaded through ``hapi.Model.fit`` and the auto-parallel ``Engine.fit``
as a :class:`TelemetryCallback` (auto-attached when ``PADDLE_TPU_METRICS=1``;
attach explicitly to pass a known ``flops_per_step``). Per step it
records into the metrics registry:

* ``step_time_ms`` — wall time between consecutive batch completions,
  split into ``data_wait_ms`` (loader/iterator stall before the batch was
  available), ``compute_ms`` (dispatching the train step) and
  ``sync_ms`` (the blocking device→host loss fetch — under jax's async
  dispatch this is where the host actually waits for the device).
  Under the fused donated train step the fit loop AMORTIZES that fetch
  (``loss_fetch_every``): steps without a fetch observe ``sync_ms=0`` and
  a dispatch-only ``compute_ms``, while the fetch step's ``sync_ms``
  covers the whole window the device ran ahead — the split degrades
  gracefully instead of forcing a per-step pipeline drain. ``step_time_ms``
  (and therefore tokens/sec and MFU) is wall-clock between batch ends and
  stays exact either way;
* ``tokens_per_sec`` / ``tokens_total`` — tokens = batch×seq for integer
  token inputs, leading batch dim otherwise;
* ``mfu_pct`` — achieved fraction of the chip's peak FLOP/s, estimated
  from ``hapi.dynamic_flops`` on the real input shape (×3 for fwd+bwd+
  update) with a ``6·N·tokens`` parameter-count fallback, against the
  shared ``metrics.peak_flops`` table.

When tracing is on, the same measurements land as nested
``step``/``data_wait``/``compute``/``sync`` spans in the Perfetto export.

The fit loop calls :meth:`TelemetryCallback.batch_ready` when a batch
arrives and ``Model.train_batch`` calls :func:`mark_sync_begin` right
before its blocking loss fetch; both are constant-time no-ops when
metrics are off (fit never constructs the callback).

Stdlib-only at import time; jax is touched lazily (device kind for the
MFU peak) and only when metrics are on.
"""
from __future__ import annotations

import time

from . import metrics as _metrics
from . import tracing as _tracing

__all__ = ["TelemetryCallback", "EMATimer", "maybe_telemetry_callback",
           "mark_sync_begin"]


class EMATimer:
    """Exponential-moving-average interval timer (the telemetry clock
    ProgBarLogger renders ``ips``/smoothed step-time from)."""

    def __init__(self, alpha=0.3):
        self.alpha = float(alpha)
        self.ema = None
        self._last = None

    def reset(self):
        self._last = None

    def tick(self, now=None):
        """-> (dt, ema) seconds; (None, None) on the first tick."""
        now = time.perf_counter() if now is None else now
        dt = None
        if self._last is not None:
            dt = now - self._last
            self.ema = dt if self.ema is None else \
                self.alpha * dt + (1 - self.alpha) * self.ema
        self._last = now
        return dt, self.ema


_active: "TelemetryCallback | None" = None


def mark_sync_begin():
    """Hot-path hook (``Model.train_batch``): stamp where compute ends and
    the blocking device sync begins. One global ``None`` check when
    telemetry is inactive."""
    cb = _active
    if cb is not None:
        cb._sync_t0 = time.perf_counter()


def maybe_telemetry_callback(model=None):
    """A :class:`TelemetryCallback` when metrics are enabled, else None —
    the fit loops' one-line auto-attach."""
    if _metrics.get_registry() is None:
        return None
    cb = TelemetryCallback()
    if model is not None:
        cb.set_model(model)
    return cb


def _tokens_of(x):
    """Tokens in one batch: batch×seq for integer token ids (LLM-style
    inputs), the leading batch dim otherwise."""
    shape = getattr(x, "shape", None)
    if not shape:
        return 1
    try:
        dt = str(getattr(x, "dtype", ""))
        if len(shape) >= 2 and ("int" in dt or "uint" in dt):
            return int(shape[0]) * int(shape[1])
    except Exception:
        pass
    return int(shape[0])


class TelemetryCallback:
    """hapi-compatible callback (duck-typed: no import of hapi here) that
    owns the per-step clock. Reusable standalone::

        cb = TelemetryCallback(flops_per_step=6 * n_params * tokens)
        model.fit(ds, callbacks=[cb])
    """

    stop_training = False

    def __init__(self, registry=None, flops_per_step=None,
                 tokens_per_batch=None, flush_every=50):
        self._registry = registry
        self.flops_per_step = flops_per_step
        self.tokens_per_batch = tokens_per_batch
        self.flush_every = int(flush_every)
        self.model = None
        self.params = None
        self.last_step_ms = None
        self._reg = None
        self._peak = None
        self._flops_failed = flops_per_step is not None
        self._t_prev = None        # previous batch completion
        self._t_ready = None       # this batch became available
        self._sync_t0 = None
        self._steps = 0

    # ---- hapi Callback surface ------------------------------------------
    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        global _active
        self._reg = self._registry or _metrics.get_registry()
        _active = self if self._reg is not None else _active
        self._t_prev = None
        self._t_ready = None

    def on_train_end(self, logs=None):
        # idempotent: fit's error path runs this from a finally AND the
        # normal callback loop runs it on success
        global _active
        if _active is self:
            _active = None
        reg, self._reg = self._reg, None
        if reg is not None:
            reg.flush()

    def on_epoch_begin(self, epoch, logs=None):
        # an epoch boundary (eval, checkpoint, reshuffle) is not data wait
        self._t_prev = None

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    # ---- the clock -------------------------------------------------------
    def note_pause(self):
        """Non-training work between steps (an interval checkpoint save,
        an eval pass): restamp the clock so the pause lands in NEITHER
        the next step_time_ms nor its data_wait_ms — without this, a
        synchronous snapshot would read as an input-pipeline stall."""
        if self._reg is not None and self._t_prev is not None:
            self._t_prev = time.perf_counter()

    def batch_ready(self, x=None):
        """The fit loop got a batch from the loader: data wait ends."""
        self._t_ready = time.perf_counter()
        self._sync_t0 = None
        if self.tokens_per_batch is None and x is not None:
            self._batch_tokens = _tokens_of(x)
        else:
            self._batch_tokens = self.tokens_per_batch or 1
        if self.flops_per_step is None and not self._flops_failed \
                and x is not None:
            self._probe_flops(x)

    def on_train_batch_end(self, step, logs=None):
        reg = self._reg
        if reg is None:
            return
        now = time.perf_counter()
        ready = self._t_ready if self._t_ready is not None else now
        prev = self._t_prev
        self._t_prev = now
        self._t_ready = None
        data_wait = (ready - prev) if prev is not None else 0.0
        sync_t0 = self._sync_t0
        compute = ((sync_t0 or now) - ready)
        sync = (now - sync_t0) if sync_t0 is not None else 0.0
        step_time = (now - prev) if prev is not None \
            else (compute + sync)
        self.last_step_ms = step_time * 1e3
        reg.counter("steps_total").inc()
        reg.histogram("step_time_ms").observe(step_time * 1e3)
        reg.histogram("data_wait_ms").observe(max(0.0, data_wait) * 1e3)
        reg.histogram("compute_ms").observe(max(0.0, compute) * 1e3)
        reg.histogram("sync_ms").observe(max(0.0, sync) * 1e3)
        tokens = getattr(self, "_batch_tokens", 1)
        if tokens and step_time > 0:
            reg.counter("tokens_total").inc(tokens)
            reg.gauge("tokens_per_sec").set(tokens / step_time)
        if self.flops_per_step and step_time > 0:
            peak = self._peak_flops()
            if peak:
                reg.gauge("mfu_pct").set(
                    100.0 * self.flops_per_step / step_time / peak)
        if _tracing.enabled():
            wall = time.time()
            t_end = wall
            t_start = t_end - step_time
            _tracing.add_complete("step", t_start, step_time, cat="step",
                                  args={"step": step})
            if data_wait > 0:
                _tracing.add_complete("data_wait", t_start,
                                      min(data_wait, step_time))
            t_ready_wall = t_end - (compute + sync)
            _tracing.add_complete("compute", t_ready_wall,
                                  max(0.0, compute))
            if sync > 0:
                _tracing.add_complete("sync", t_end - sync, sync)
        self._steps += 1
        if self.flush_every and self._steps % self.flush_every == 0:
            reg.flush()

    # ---- MFU plumbing ----------------------------------------------------
    def _peak_flops(self):
        if self._peak is None:
            kind = ""
            try:
                import jax
                kind = jax.devices()[0].device_kind
            except Exception:
                pass
            self._peak = _metrics.peak_flops(kind)
        return self._peak

    def _probe_flops(self, x):
        """One-shot fwd-FLOPs probe on the REAL input shape via
        hapi.dynamic_flops (×3 for fwd+bwd+update), falling back to the
        6·N·tokens parameter-count rule. Any failure disables MFU rather
        than training."""
        self._flops_failed = True  # sticky: probe at most once
        net = getattr(self.model, "network", None) or self.model
        net = getattr(net, "_layers", net)  # unwrap DataParallel
        if net is None:
            return
        shape = getattr(x, "shape", None)
        try:
            from ..hapi.dynamic_flops import flops as _flops
            fwd = int(_flops(net, list(shape)))
            if fwd > 0:  # 0 = nothing hookable (e.g. a bare leaf layer)
                self.flops_per_step = 3 * fwd
                return
        except Exception:
            pass
        try:
            import numpy as np
            n_params = sum(int(np.prod(p.shape))
                           for p in net.parameters())
            tokens = getattr(self, "_batch_tokens", 1)
            if n_params and tokens:
                self.flops_per_step = 6 * n_params * tokens
        except Exception:
            pass
