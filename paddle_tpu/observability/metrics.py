"""Run-telemetry metrics core — lock-cheap counters/gauges/histograms.

A real training run previously emitted no throughput, no step-time
breakdown and no per-collective latency: MFU existed only inside bench.py
one-shots, and the flight recorder's issue→complete timestamps were thrown
away unless the job crashed. This module is the missing metrics plane:

* :class:`Counter` / :class:`Gauge` / :class:`Histogram` with labels;
  latency histograms use exponential buckets so one 24-bucket vector
  spans 1µs..8s with constant relative error.
* One process-wide :class:`MetricsRegistry`, env-gated exactly like the
  flight recorder (``PADDLE_TPU_METRICS=1``; unset = every hook is a
  constant-time no-op: one module-global ``None`` check, no allocation).
* Periodic JSONL snapshots into the launcher's workerlog scheme
  (``PADDLE_TPU_WORKERLOG_DIR/metrics.<rank>.jsonl``, interval
  ``PADDLE_TPU_METRICS_INTERVAL_S``, default 10s) plus an atexit flush —
  the launcher aggregates these per-rank files into the end-of-run
  straggler report (:mod:`paddle_tpu.observability.report`).

"Lock-cheap": metric children are created under one registry lock and
cached by the caller (or looked up by dict key); updates touch only the
child (gauge writes are single assignments; counter/histogram updates
take one short uncontended per-metric lock).

Stdlib-only at import time (like ``distributed/fault.py``) so the
launcher-side aggregation and the flight recorder can import it without
loading jax.
"""
from __future__ import annotations

import atexit
import bisect
import json
import os
import sys
import threading
import time

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "env_rank",
    "exp_buckets",
    "get_registry", "enabled", "enable", "disable", "metric_key",
    "parse_metric_key", "counter", "gauge", "histogram", "observe",
    "observe_collective", "observe_replication", "flush", "hist_quantile",
    "hist_mean", "peak_flops",
]


def env_rank() -> int:
    """This process's rank for artifact naming — the launcher-exported
    id chain (one copy, shared with the trace buffer)."""
    return int(os.environ.get(
        "PADDLE_TPU_PROCESS_ID",
        os.environ.get("PADDLE_TRAINER_ID", "0")) or 0)


def exp_buckets(start=1.0, factor=2.0, count=24):
    """Exponential bucket upper bounds ``[start, start*factor, ...]``."""
    out = []
    b = float(start)
    for _ in range(count):
        out.append(b)
        b *= factor
    return out


# default latency buckets: 1µs .. ~8.4s in microseconds
_DEFAULT_BOUNDS = tuple(exp_buckets(1.0, 2.0, 24))


def metric_key(name, labels=None):
    """Canonical flat key: ``name`` or ``name{k=v,k2=v2}`` (sorted)."""
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


def parse_metric_key(key):
    """Inverse of :func:`metric_key` -> (name, labels dict)."""
    if "{" not in key:
        return key, {}
    name, _, rest = key.partition("{")
    labels = {}
    for part in rest.rstrip("}").split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            labels[k] = v
    return name, labels


class Counter:
    """Monotonic counter. The short lock keeps cross-thread increments
    exact (a wait()-thread completing an async collective races the
    training thread; a bare ``+=`` is LOAD/ADD/STORE and can drop one)."""

    __slots__ = ("key", "value", "_lock")

    def __init__(self, key):
        self.key = key
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self.value += n


class Gauge:
    """Last-value metric."""

    __slots__ = ("key", "value")

    def __init__(self, key):
        self.key = key
        self.value = None

    def set(self, v):
        self.value = float(v)


class Histogram:
    """Fixed-bucket histogram (upper bounds + overflow) with sum/count/
    min/max, good enough for p50/p99 without keeping samples."""

    __slots__ = ("key", "bounds", "counts", "count", "sum", "min", "max",
                 "_lock")

    def __init__(self, key, bounds=None):
        self.key = key
        self.bounds = tuple(bounds) if bounds else _DEFAULT_BOUNDS
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self._lock = threading.Lock()

    def observe(self, v):
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v

    def to_dict(self):
        with self._lock:
            return {"bounds": list(self.bounds),
                    "counts": list(self.counts),
                    "count": self.count, "sum": self.sum,
                    "min": self.min, "max": self.max}


def hist_quantile(h, q):
    """Quantile estimate from a histogram dict (``Histogram.to_dict`` or a
    JSONL-deserialized one); linear within the winning bucket. Returns
    None for an empty histogram."""
    count = h.get("count") or 0
    if count <= 0:
        return None
    bounds = h["bounds"]
    counts = h["counts"]
    target = q * count
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        lo = bounds[i - 1] if i > 0 else 0.0
        hi = bounds[i] if i < len(bounds) else (h.get("max") or bounds[-1])
        if cum + c >= target:
            frac = (target - cum) / c
            return lo + (hi - lo) * max(0.0, min(1.0, frac))
        cum += c
    return h.get("max")


def hist_mean(h):
    count = h.get("count") or 0
    return (h.get("sum", 0.0) / count) if count else None


class MetricsRegistry:
    """Process-wide metric store + JSONL snapshot writer."""

    def __init__(self, rank=None, out_dir=None, interval_s=0.0):
        self.rank = env_rank() if rank is None else int(rank)
        self.out_dir = out_dir
        self.interval_s = float(interval_s or 0.0)
        self._lock = threading.Lock()
        self._metrics: dict = {}
        self._seq = 0
        self._stop = threading.Event()
        self._thread = None
        if self.out_dir and self.interval_s > 0:
            self._thread = threading.Thread(
                target=self._flusher, name="paddle-tpu-metrics",
                daemon=True)
            self._thread.start()

    # ------------------------------------------------------------ children
    def _child(self, cls, name, labels, *args):
        key = metric_key(name, labels)
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    m = cls(key, *args)
                    self._metrics[key] = m
        return m

    def counter(self, name, **labels) -> Counter:
        return self._child(Counter, name, labels)

    def gauge(self, name, **labels) -> Gauge:
        return self._child(Gauge, name, labels)

    def histogram(self, name, bounds=None, **labels) -> Histogram:
        return self._child(Histogram, name, labels, bounds)

    # ------------------------------------------------------------ snapshot
    def snapshot(self):
        """One JSON-ready dict of everything (counters cumulative)."""
        with self._lock:
            items = list(self._metrics.items())
        self._seq += 1
        out = {"ts": time.time(), "rank": self.rank, "seq": self._seq,
               "counters": {}, "gauges": {}, "histograms": {}}
        for key, m in items:
            if isinstance(m, Counter):
                out["counters"][key] = m.value
            elif isinstance(m, Gauge):
                if m.value is not None:
                    out["gauges"][key] = m.value
            else:
                out["histograms"][key] = m.to_dict()
        return out

    def out_path(self):
        if not self.out_dir:
            return None
        return os.path.join(self.out_dir, f"metrics.{self.rank}.jsonl")

    def flush(self):
        """Append one snapshot line; returns the path (None when no dir is
        configured or nothing was ever recorded)."""
        path = self.out_path()
        if path is None:
            return None
        with self._lock:
            empty = not self._metrics
        if empty:
            return None
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            with open(path, "a") as f:
                f.write(json.dumps(self.snapshot(), default=str) + "\n")
        except Exception as e:  # telemetry must never kill training
            print(f"[metrics] flush to {path} failed: {e}",
                  file=sys.stderr, flush=True)
            return None
        return path

    def _flusher(self):
        while not self._stop.wait(self.interval_s):
            self.flush()

    def close(self):
        self._stop.set()
        self.flush()


# ------------------------------------------------- module-level singleton

_state_lock = threading.Lock()
_REG: MetricsRegistry | None = None
_loaded = False
_atexit_armed = False


def _wire_dispatch():
    """Invalidate the eager-dispatch module's cached metrics handle (it
    resolves lazily; an enable/disable after its first op must take
    effect). sys.modules lookup only — never imports the jax-heavy module
    from here."""
    d = sys.modules.get("paddle_tpu.core.dispatch")
    if d is not None and hasattr(d, "_op_metrics_resolved"):
        d._op_metrics_resolved = False
        d._op_metrics = None


def _arm_atexit():
    global _atexit_armed
    if not _atexit_armed:
        _atexit_armed = True
        atexit.register(_atexit_flush)


def _atexit_flush():
    reg = _REG
    if reg is not None:
        try:
            reg.flush()
        except Exception:
            pass


def _load():
    """Resolve the env gate once: ``PADDLE_TPU_METRICS=1`` enables the
    registry; snapshots land in ``PADDLE_TPU_METRICS_DIR`` (falling back
    to the launcher's ``PADDLE_TPU_WORKERLOG_DIR``) every
    ``PADDLE_TPU_METRICS_INTERVAL_S`` seconds (default 10; 0 = explicit
    flushes only)."""
    global _REG, _loaded
    with _state_lock:
        if _loaded:
            return _REG
        on = os.environ.get("PADDLE_TPU_METRICS", "")
        if on not in ("", "0", "false", "False"):
            out_dir = (os.environ.get("PADDLE_TPU_METRICS_DIR")
                       or os.environ.get("PADDLE_TPU_WORKERLOG_DIR"))
            try:
                interval = float(
                    os.environ.get("PADDLE_TPU_METRICS_INTERVAL_S", "10")
                    or 0)
            except ValueError:
                interval = 10.0
            _REG = MetricsRegistry(out_dir=out_dir, interval_s=interval)
            _arm_atexit()
        else:
            _REG = None
        _loaded = True
        _wire_dispatch()
        return _REG


def get_registry() -> MetricsRegistry | None:
    """The env-gated singleton registry, or None when metrics are off."""
    return _REG if _loaded else _load()


def enabled() -> bool:
    return get_registry() is not None


def enable(out_dir=None, interval_s=0.0, rank=None) -> MetricsRegistry:
    """Programmatic gate (tests / bench) — replaces the singleton."""
    global _REG, _loaded
    with _state_lock:
        if _REG is not None:
            _REG.close()
        _REG = MetricsRegistry(rank=rank, out_dir=out_dir,
                               interval_s=interval_s)
        _loaded = True
        _arm_atexit()
        _wire_dispatch()
        return _REG


def disable():
    global _REG, _loaded
    with _state_lock:
        if _REG is not None:
            _REG.close()
        _REG = None
        _loaded = True
        _wire_dispatch()


def _reset_state():
    """Test hook: back to the unresolved env-gated state."""
    global _REG, _loaded
    with _state_lock:
        if _REG is not None:
            _REG._stop.set()
        _REG = None
        _loaded = False
        _wire_dispatch()


# ------------------------------------------------------ no-op-safe helpers

def counter(name, **labels) -> Counter | None:
    reg = _REG if _loaded else _load()
    return reg.counter(name, **labels) if reg is not None else None


def gauge(name, **labels) -> Gauge | None:
    reg = _REG if _loaded else _load()
    return reg.gauge(name, **labels) if reg is not None else None


def histogram(name, bounds=None, **labels) -> Histogram | None:
    reg = _REG if _loaded else _load()
    return reg.histogram(name, bounds, **labels) if reg is not None \
        else None


def observe(name, value, **labels):
    reg = _REG if _loaded else _load()
    if reg is not None:
        reg.histogram(name, **labels).observe(value)


def flush():
    reg = _REG if _loaded else _load()
    return reg.flush() if reg is not None else None


def observe_collective(entry):
    """Feed one completed flight-recorder ring entry into the per-
    kind×group latency histogram (+ wire-volume counter). Called from
    ``FlightRecorder.complete``; the disabled fast path is the one
    ``None`` check below. ``step``-group marker entries (heartbeats,
    resume markers) are bookkeeping — skipped; ``pipe``-group entries
    (pp_forward/pp_backward micro-batches) are COMPUTE, so they get
    their own ``pipeline_latency_us`` family instead of polluting the
    collective table / comm-vs-compute ratio."""
    reg = _REG if _loaded else _load()
    if reg is None or entry is None:
        return
    group = entry.get("group", "?")
    if group == "step" or entry.get("aborted"):
        return
    t0, t1 = entry.get("t_issue"), entry.get("t_complete")
    if t0 is None or t1 is None:
        return
    kind = entry.get("kind", "?")
    family = "pipeline_latency_us" if group == "pipe" \
        else "collective_latency_us"
    reg.histogram(family, kind=kind, group=group).observe(
        (t1 - t0) * 1e6)
    if group != "pipe":
        nbytes = entry.get("nbytes")
        if nbytes:
            reg.counter("collective_bytes_total",
                        kind=kind).inc(int(nbytes))
        # in-run overlap sampler (overlap engine, ROADMAP item 2): an
        # AWAITED async collective carries t_wait — the t_issue→t_wait
        # window is time the collective was in flight while the host kept
        # dispatching work (communication hidden under compute); the
        # t_wait→t_complete remainder is the blocking drain. The gauge is
        # the cumulative hidden fraction, the same comm_overlap_pct key
        # bench's xplane leg reports — but measured IN-RUN, from flight-
        # recorder stamps, with no trace collection. Only device-synced
        # entries count (the waiter blocked until the result was ready):
        # a bookkeeping-only wait() stamps t_complete == t_wait and would
        # pollute the gauge with fake 100%-hidden samples.
        t_w = entry.get("t_wait")
        if t_w is not None and entry.get("device_synced"):
            inflight_us = (t1 - t0) * 1e6
            hidden_us = min(max((t_w - t0) * 1e6, 0.0), inflight_us)
            c_in = reg.counter("comm_inflight_us_total")
            c_hid = reg.counter("comm_overlapped_us_total")
            c_in.inc(inflight_us)
            c_hid.inc(hidden_us)
            if c_in.value > 0:
                reg.gauge("comm_overlap_pct").set(
                    100.0 * c_hid.value / c_in.value)


def observe_replication(head_seq, acked_seq, shipped=0, torn=0):
    """Replication-plane telemetry for the log-shipped registry failover
    (ISSUE 10): ``store_replication_lag`` gauge (primary WAL head minus
    the standby's acked seq — the ops a failover right now would hand to
    the on_failover gap-filler) plus shipped/torn counters. Called from
    ``tcp_store.LogShipper.ship_once``; one ``None`` check when metrics
    are off, same contract as :func:`observe_collective`."""
    reg = _REG if _loaded else _load()
    if reg is None:
        return
    reg.gauge("store_replication_lag").set(
        max(0, int(head_seq) - int(acked_seq)))
    if shipped:
        reg.counter("store_wal_shipped_total").inc(int(shipped))
    if torn:
        reg.counter("store_wal_torn_total").inc(int(torn))


# ---------------------------------------------------------- hardware table

def peak_flops(device_kind=""):
    """Per-chip bf16 peak FLOP/s by device kind — the ONE copy of the
    table bench.py and the MFU gauge share. ``PADDLE_TPU_PEAK_FLOPS``
    overrides (useful on CPU plumbing runs)."""
    env = os.environ.get("PADDLE_TPU_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    kind = str(device_kind).lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    return 197e12
