"""Per-request SLO attribution from exported request traces.

The reading half of ISSUE 20: ``tracing.py`` stamps every lifecycle
phase of a served request into the per-process Chrome-trace JSON
(merged across processes by ``tools/merge_profiles``); this module
folds those span streams back into a per-request table — which phase
ate the latency — and renders the top-N slowest as a text waterfall:

* one row per trace id (the context minted at the front door), with
  the per-phase milliseconds summed from the spans: ``queue_wait``,
  ``prefill`` (chunk spans summed when the rollup span is absent),
  ``decode``, ``route``, ``ledger``, ``migrate``;
* attribution flags folded from the instant events: ``hedged`` /
  ``hedge_won`` / ``hedge_lost`` (did the duplicate leg pay off),
  ``evicted``/``readmit``, ``prefix_hit``, ``migrated``, ``error``;
* ``procs`` — how many processes contributed spans (a cross-process
  waterfall shows >= 2: router + engine).

Also a CLI (exercised in tests)::

    python -m paddle_tpu.observability.trace_report <dir-or-json...> \
        [--top N] [--json]

Accepts directories (every ``trace*.json`` under them, including a
``merge_profiles`` output) or explicit trace files. Stdlib-only.
"""
from __future__ import annotations

import glob
import json
import os
import sys

__all__ = ["load_events", "build_request_rows", "rows_to_report",
           "format_request_rows", "main"]

# span name -> phase column (durations are summed per request)
_PHASE_OF = {"queue_wait": "queue_wait",
             "prefill": "prefill",
             "prefill_chunk": "prefill_chunk",
             "decode": "decode",
             "route": "route",
             "ledger_accept": "ledger",
             "client_submit": "client",
             "kv_migrate": "migrate"}

# instant-event name -> attribution flag
_FLAG_OF = {"hedge_fired": "hedged",
            "hedge_won": "hedge_won",
            "hedge_lost": "hedge_lost",
            "evicted": "evicted",
            "readmit": "readmit",
            "prefix_hit": "prefix_hit",
            "kv_migrate": "migrated",
            "ledger_replay": "replayed"}

_PHASE_COLS = ("queue_wait", "prefill", "decode", "route", "migrate")


def load_events(*sources):
    """Flatten trace events from files and/or directories. Directories
    contribute every ``trace*.json``/``merged*.json`` under them; torn
    or non-trace JSON files are skipped, not fatal."""
    paths = []
    for src in sources:
        if os.path.isdir(src):
            for pat in ("trace*.json", "merged*.json", "*.trace.json"):
                paths.extend(sorted(glob.glob(os.path.join(src, pat))))
        else:
            paths.append(src)
    events = []
    for p in dict.fromkeys(paths):    # de-dup, keep order
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        evs = doc.get("traceEvents") if isinstance(doc, dict) else None
        if isinstance(evs, list):
            events.extend(e for e in evs if isinstance(e, dict))
    return events


def build_request_rows(events):
    """-> {trace_id: row} folded from the request-lane events (those
    carrying ``args.trace``). Durations in ms; ``e2e_ms`` spans the
    earliest event start to the latest event end, which across merged
    processes is the client-visible wall time (one shared wall clock —
    the tracer's deliberate clock-domain choice)."""
    rows = {}
    seen = set()
    for ev in events:
        args = ev.get("args")
        tid = args.get("trace") if isinstance(args, dict) else None
        if tid is None:
            continue
        ts = float(ev.get("ts", 0.0))          # µs wall
        dur = float(ev.get("dur", 0.0) or 0.0)
        # a directory often holds BOTH the per-process trace.N.json files
        # and the merge_profiles output built from them — the same event
        # twice, differing only in pid (the merge rewrites it). De-dup on
        # everything BUT pid, else every phase sum doubles.
        key = (ev.get("name"), ts, dur,
               str(sorted(args.items(), key=repr)))
        if key in seen:
            continue
        seen.add(key)
        row = rows.get(tid)
        if row is None:
            row = rows[tid] = {"trace": str(tid), "t0_us": ts,
                               "t1_us": ts + dur, "phases": {},
                               "flags": set(), "procs": set(),
                               "events": 0, "tokens": 0}
        row["events"] += 1
        row["t0_us"] = min(row["t0_us"], ts)
        row["t1_us"] = max(row["t1_us"], ts + dur)
        row["procs"].add(ev.get("pid"))
        name = ev.get("name")
        phase = _PHASE_OF.get(name)
        if phase is not None and dur > 0:
            row["phases"][phase] = row["phases"].get(phase, 0.0) \
                + dur / 1e3
        flag = _FLAG_OF.get(name)
        if flag is not None:
            row["flags"].add(flag)
        if name == "stream_token":
            row["tokens"] += 1
        elif name in ("request_done", "fleet_done"):
            state = args.get("state")
            if state == "failed":
                row["flags"].add("error")
            if name == "fleet_done" and args.get("hedged"):
                row["flags"].add("hedged")
    for row in rows.values():
        ph = row["phases"]
        # the rollup prefill span wins; chunk spans are the fallback
        # (chunked prefill overlaps decode rounds — summing BOTH would
        # double-count the prefill wall time)
        if "prefill" not in ph and "prefill_chunk" in ph:
            ph["prefill"] = ph["prefill_chunk"]
        ph.pop("prefill_chunk", None)
        row["e2e_ms"] = (row["t1_us"] - row["t0_us"]) / 1e3
        row["procs"] = len(row["procs"])
        row["flags"] = sorted(row["flags"])
    return rows


def rows_to_report(rows, top=10):
    """Top-N slowest as a JSON-friendly list (report.py embeds this as
    the ``slo_attribution`` section)."""
    ordered = sorted(rows.values(), key=lambda r: -r["e2e_ms"])[:top]
    out = []
    for r in ordered:
        rec = {"trace": r["trace"],
               "e2e_ms": round(r["e2e_ms"], 3),
               "procs": r["procs"], "events": r["events"],
               "tokens": r["tokens"], "flags": r["flags"]}
        for c in _PHASE_COLS:
            v = r["phases"].get(c)
            if v is not None:
                rec[f"{c}_ms"] = round(v, 3)
        out.append(rec)
    return out


def format_request_rows(rows, top=10):
    """Text waterfall table of the top-N slowest requests; None when
    there is nothing to say."""
    recs = rows_to_report(rows, top=top)
    if not recs:
        return None
    lines = [f"[trace] slowest {len(recs)} of {len(rows)} request(s) "
             "(phase ms):"]
    lines.append("[trace]   %-18s %9s %6s %8s %8s %8s %7s %s" % (
        "trace", "e2e", "queue", "prefill", "decode", "route",
        "procs", "flags"))

    def _f(v):
        return "-" if v is None else f"{v:.1f}"

    for r in recs:
        lines.append("[trace]   %-18s %9s %6s %8s %8s %8s %7d %s" % (
            r["trace"][:18], _f(r["e2e_ms"]), _f(r.get("queue_wait_ms")),
            _f(r.get("prefill_ms")), _f(r.get("decode_ms")),
            _f(r.get("route_ms")), r["procs"],
            ",".join(r["flags"]) or "-"))
    return "\n".join(lines)


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m paddle_tpu.observability.trace_report "
              "<dir-or-json...> [--top N] [--json]", file=sys.stderr)
        return 2
    top = 10
    as_json = False
    sources = []
    it = iter(argv)
    for a in it:
        if a == "--top":
            top = int(next(it, "10"))
        elif a == "--json":
            as_json = True
        else:
            sources.append(a)
    rows = build_request_rows(load_events(*sources))
    if as_json:
        print(json.dumps(rows_to_report(rows, top=top), indent=1))
        return 0
    text = format_request_rows(rows, top=top)
    if text is None:
        print(f"[trace] no request events under {' '.join(sources)}",
              file=sys.stderr)
        return 1
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
