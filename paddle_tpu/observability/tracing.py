"""Host-side span tracing — Chrome-trace/Perfetto JSON export.

The xplane trace answers "what did the DEVICE do"; this module answers
"what did the HOST do around it": ``span("fwd")`` context managers in the
fit/pipeline paths become ``ph: "X"`` complete events, completed
flight-recorder collectives become ``cat: "collective"`` events, and the
export loads directly in chrome://tracing / ui.perfetto.dev. Merge with a
device timeline via ``python -m paddle_tpu.tools.merge_profiles`` (which
also accepts xplane log dirs).

Gating mirrors the metrics core: ``PADDLE_TPU_TRACE=1`` (export path from
``PADDLE_TPU_TRACE_PATH``, default ``trace.<rank>.json`` under
``PADDLE_TPU_WORKERLOG_DIR``; ``PADDLE_TPU_TRACE=/path.json`` sets both),
or programmatic :func:`start` / :func:`stop`. Disabled (the default),
``span()`` yields immediately off one module-global ``None`` check and
event feeds return without allocating.

Timestamps are ``time.time()`` µs — the same wall clock the flight
recorder stamps, so collective events and spans line up in one timeline.
Nesting needs no explicit parent ids: Perfetto nests same-thread "X"
events by interval containment.

Stdlib-only at import time.
"""
from __future__ import annotations

import atexit
import contextlib
import json
import os
import sys
import threading
import time

__all__ = ["TraceBuffer", "span", "add_complete", "collective_event",
           "enabled", "get_buffer", "start", "stop", "export",
           "_reset_state"]

_MAX_EVENTS = 200_000  # runaway guard: ~40MB of JSON at most


class TraceBuffer:
    """Append-only buffer of chrome-trace events for ONE process."""

    def __init__(self, rank=None, path=None):
        from .metrics import env_rank
        self.rank = env_rank() if rank is None else int(rank)
        self.path = path
        self.events = []
        self._lock = threading.Lock()
        self.dropped = 0

    def add(self, name, ts_s, dur_s, cat="host", tid=None, args=None):
        ev = {"name": str(name), "ph": "X", "pid": self.rank,
              "tid": tid if tid is not None else threading.get_ident(),
              "ts": ts_s * 1e6, "dur": max(0.0, dur_s) * 1e6, "cat": cat}
        if args:
            ev["args"] = dict(args)
        with self._lock:
            if len(self.events) >= _MAX_EVENTS:
                self.dropped += 1
                return
            self.events.append(ev)

    def to_dict(self):
        with self._lock:
            events = list(self.events)
            dropped = self.dropped
        meta = [{"name": "process_name", "ph": "M", "pid": self.rank,
                 "args": {"name": f"rank_{self.rank} host"}},
                # clock provenance for the merge tool's --align: host
                # spans stamp time.time() µs (the same wall clock the
                # flight recorder uses), so device lanes from another
                # clock domain can be shifted onto this one
                {"name": "clock_domain", "ph": "M", "pid": self.rank,
                 "args": {"domain": "wall", "export_wall_us":
                          time.time() * 1e6}}]
        d = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
        if dropped:
            d["droppedEvents"] = dropped
        return d

    def export(self, path=None):
        path = path or self.path
        if not path:
            return None
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_dict(), f)
        return path


# ------------------------------------------------- module-level singleton

_state_lock = threading.Lock()
_TR: TraceBuffer | None = None
_loaded = False
_atexit_armed = False


def _default_path(rank):
    d = os.environ.get("PADDLE_TPU_WORKERLOG_DIR") or "."
    return os.path.join(d, f"trace.{rank}.json")


def _load():
    global _TR, _loaded
    with _state_lock:
        if _loaded:
            return _TR
        raw = os.environ.get("PADDLE_TPU_TRACE", "")
        if raw in ("", "0", "false", "False"):
            _TR = None
        else:
            buf = TraceBuffer()
            if raw not in ("1", "true", "True"):
                buf.path = raw  # PADDLE_TPU_TRACE=/path.json
            else:
                buf.path = (os.environ.get("PADDLE_TPU_TRACE_PATH")
                            or _default_path(buf.rank))
            _TR = buf
            _arm_atexit()
        _loaded = True
        return _TR


def _arm_atexit():
    global _atexit_armed
    if not _atexit_armed:
        _atexit_armed = True
        atexit.register(_atexit_export)


def _atexit_export():
    buf = _TR
    if buf is not None and buf.path:
        try:
            buf.export()
        except Exception:
            pass


def get_buffer() -> TraceBuffer | None:
    return _TR if _loaded else _load()


def enabled() -> bool:
    return get_buffer() is not None


def start(path=None, rank=None) -> TraceBuffer:
    """Programmatic gate (tests / bench) — replaces the singleton."""
    global _TR, _loaded
    with _state_lock:
        _TR = TraceBuffer(rank=rank, path=path)
        _loaded = True
        _arm_atexit()
        return _TR


def stop(path=None):
    """Export (when a path is known) and disable; returns the path."""
    global _TR, _loaded
    with _state_lock:
        buf = _TR
        _TR = None
        _loaded = True
    if buf is None:
        return None
    try:
        return buf.export(path)
    except Exception as e:
        print(f"[trace] export failed: {e}", file=sys.stderr, flush=True)
        return None


def export(path=None):
    buf = _TR if _loaded else _load()
    return buf.export(path) if buf is not None else None


def _reset_state():
    """Test hook: back to the unresolved env-gated state."""
    global _TR, _loaded
    with _state_lock:
        _TR = None
        _loaded = False


# ------------------------------------------------------------------ feeds

@contextlib.contextmanager
def span(name, cat="host", **args):
    """Trace one host scope; a constant-time no-op when tracing is off."""
    buf = _TR if _loaded else _load()
    if buf is None:
        yield None
        return
    t0 = time.time()
    try:
        yield buf
    finally:
        buf.add(name, t0, time.time() - t0, cat=cat, args=args or None)


def add_complete(name, ts_s, dur_s, cat="host", tid=None, args=None):
    buf = _TR if _loaded else _load()
    if buf is not None:
        buf.add(name, ts_s, dur_s, cat=cat, tid=tid, args=args)


def collective_event(entry):
    """Feed one completed flight-recorder entry as a trace event. Ring
    bookkeeping markers (``step`` group) are skipped; pipeline
    micro-batch entries keep their own category so the collective lane
    stays collectives-only."""
    buf = _TR if _loaded else _load()
    if buf is None or entry is None:
        return
    group = entry.get("group")
    if group == "step" or entry.get("aborted"):
        return
    t0, t1 = entry.get("t_issue"), entry.get("t_complete")
    if t0 is None or t1 is None:
        return
    cat = "pipeline" if group == "pipe" else "collective"
    args = {"group": group, "seq": entry.get("seq"),
            "gseq": entry.get("gseq")}
    if entry.get("shape") is not None:
        args["shape"] = str(entry["shape"])
    buf.add(entry.get("kind", "?"), t0, t1 - t0, cat=cat, args=args)
