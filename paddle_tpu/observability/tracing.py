"""Host-side span tracing — Chrome-trace/Perfetto JSON export.

The xplane trace answers "what did the DEVICE do"; this module answers
"what did the HOST do around it": ``span("fwd")`` context managers in the
fit/pipeline paths become ``ph: "X"`` complete events, completed
flight-recorder collectives become ``cat: "collective"`` events, and the
export loads directly in chrome://tracing / ui.perfetto.dev. Merge with a
device timeline via ``python -m paddle_tpu.tools.merge_profiles`` (which
also accepts xplane log dirs).

Gating mirrors the metrics core: ``PADDLE_TPU_TRACE=1`` (export path from
``PADDLE_TPU_TRACE_PATH``, default ``trace.<rank>.json`` under
``PADDLE_TPU_WORKERLOG_DIR``; ``PADDLE_TPU_TRACE=/path.json`` sets both),
or programmatic :func:`start` / :func:`stop`. Disabled (the default),
``span()`` yields immediately off one module-global ``None`` check and
event feeds return without allocating.

Timestamps are ``time.time()`` µs — the same wall clock the flight
recorder stamps, so collective events and spans line up in one timeline.
Nesting needs no explicit parent ids: Perfetto nests same-thread "X"
events by interval containment.

Request tracing (ISSUE 20): :func:`mint_context` mints a trace context
(``{"tid": <hex id>, "ps": <parent span, 0 = root>}``) that rides the
fleet wire; every process feeds that request's spans through
:func:`req_event` into a per-trace pending buffer, and the terminal
:func:`finish_request` applies TAIL-BASED sampling — the trace is
retained (flushed onto the main buffer, on its own per-request lane)
only when the request erred, hedged, evicted, aborted, was slow
(``PADDLE_TPU_TRACE_SLOW_MS``), or hits the deterministic sample
(``PADDLE_TPU_TRACE_SAMPLE=<rate>``, hashed from the trace id so every
process makes the SAME decision without extra wire bits). Everything
else is dropped before export. Undecided traces still pending at export
time are flushed as-is so a shutdown mid-request stays visible.

Stdlib-only at import time.
"""
from __future__ import annotations

import atexit
import collections
import contextlib
import json
import os
import sys
import threading
import time
import zlib

__all__ = ["TraceBuffer", "span", "add_complete", "collective_event",
           "mint_context", "req_event", "finish_request",
           "enabled", "get_buffer", "start", "stop", "export",
           "_reset_state"]

_MAX_EVENTS = 200_000  # runaway guard: ~40MB of JSON at most
_DECIDED_CAP = 4096    # remembered tail-sampling verdicts (FIFO)
_PENDING_CAP = 1024    # simultaneously-undecided request traces

_SAMPLE_ENV = "PADDLE_TPU_TRACE_SAMPLE"
_SLOW_ENV = "PADDLE_TPU_TRACE_SLOW_MS"


def _env_float(name):
    raw = os.environ.get(name, "")
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


def _tid_bucket(tid):
    """Deterministic 32-bit hash of a trace id — identical in every
    process, so the sampling verdict needs no coordination."""
    return zlib.crc32(str(tid).encode("utf-8", "replace"))


def _metric_drop(n=1):
    try:
        from .metrics import counter
        c = counter("trace_events_dropped_total")
        if c is not None:
            c.inc(n)
    except Exception:
        pass


class TraceBuffer:
    """Append-only buffer of chrome-trace events for ONE process."""

    def __init__(self, rank=None, path=None):
        from .metrics import env_rank
        self.rank = env_rank() if rank is None else int(rank)
        self.path = path
        self.events = []
        self._lock = threading.Lock()
        self.dropped = 0
        # -------- request tracing (tail-based sampling) state
        self._req = {}              # tid -> pending event list
        self._decided = {}          # tid -> kept? (post-terminal verdict)
        self._decided_order = collections.deque()
        self._named_lanes = set()   # tids whose lane got a thread_name
        self.req_traces_dropped = 0
        self.sample_rate = _env_float(_SAMPLE_ENV)
        self.slow_ms = _env_float(_SLOW_ENV)

    def _append_locked(self, ev):
        """Append under self._lock; at the cap the FIRST drop leaves one
        over-cap metadata marker so a truncated export never silently
        looks complete. Returns False when the event was dropped."""
        if len(self.events) >= _MAX_EVENTS:
            if self.dropped == 0:
                self.events.append({
                    "name": "trace_truncated", "ph": "M",
                    "pid": self.rank,
                    "args": {"at_events": _MAX_EVENTS,
                             "wall_us": time.time() * 1e6}})
            self.dropped += 1
            return False
        self.events.append(ev)
        return True

    def add(self, name, ts_s, dur_s, cat="host", tid=None, args=None):
        ev = {"name": str(name), "ph": "X", "pid": self.rank,
              "tid": tid if tid is not None else threading.get_ident(),
              "ts": ts_s * 1e6, "dur": max(0.0, dur_s) * 1e6, "cat": cat}
        if args:
            ev["args"] = dict(args)
        with self._lock:
            ok = self._append_locked(ev)
        if not ok:
            _metric_drop()

    # ---------------------------------------------- request-trace feeds

    def _lane(self, tid):
        return _tid_bucket(tid)

    def _name_lane_locked(self, tid):
        if tid in self._named_lanes:
            return
        self._named_lanes.add(tid)
        self._append_locked({
            "name": "thread_name", "ph": "M", "pid": self.rank,
            "tid": self._lane(tid), "args": {"name": f"req {tid}"}})

    def req_add(self, tid, name, ts_s, dur_s, cat="request", args=None):
        """Buffer one span for request ``tid`` pending its tail-sampling
        verdict; post-verdict events append (kept) or vanish (dropped)
        directly."""
        a = {"trace": tid}
        if args:
            a.update(args)
        ev = {"name": str(name), "ph": "X", "pid": self.rank,
              "tid": self._lane(tid), "ts": ts_s * 1e6,
              "dur": max(0.0, dur_s) * 1e6, "cat": cat, "args": a}
        dropped = False
        with self._lock:
            verdict = self._decided.get(tid)
            if verdict is False:
                return
            if verdict is True:
                dropped = not self._append_locked(ev)
            else:
                pend = self._req.get(tid)
                if pend is None:
                    if len(self._req) >= _PENDING_CAP:
                        dropped = True    # overflow: runaway guard
                    else:
                        self._req[tid] = pend = []
                if pend is not None:
                    pend.append(ev)
        if dropped:
            _metric_drop()

    def req_finish(self, tid, keep):
        """Apply the tail-sampling verdict for ``tid``: flush (keep) or
        discard its pending spans. A later ``keep`` upgrades an earlier
        drop verdict for FUTURE events (the already-dropped ones are
        gone). Returns the effective verdict."""
        lost = 0
        with self._lock:
            pending = self._req.pop(tid, None)
            prior = self._decided.get(tid)
            if prior is True:
                keep = True
            elif prior is None:
                self._decided[tid] = bool(keep)
                self._decided_order.append(tid)
                while len(self._decided_order) > _DECIDED_CAP:
                    old = self._decided_order.popleft()
                    self._decided.pop(old, None)
                    self._named_lanes.discard(old)
            elif keep:
                self._decided[tid] = True
            if not keep:
                if pending:
                    self.req_traces_dropped += 1
                return False
            if pending:
                self._name_lane_locked(tid)
                for ev in pending:
                    if not self._append_locked(ev):
                        lost += 1
        if lost:
            _metric_drop(lost)
        return True

    def _flush_pending_locked(self):
        """Export-time flush of still-undecided traces (process exiting
        mid-request): keep them so the shutdown stays visible."""
        for tid, pending in list(self._req.items()):
            self._name_lane_locked(tid)
            for ev in pending:
                self._append_locked(ev)
        self._req.clear()

    def to_dict(self):
        with self._lock:
            self._flush_pending_locked()
            events = list(self.events)
            dropped = self.dropped
        meta = [{"name": "process_name", "ph": "M", "pid": self.rank,
                 "args": {"name": f"rank_{self.rank} host"}},
                # clock provenance for the merge tool's --align: host
                # spans stamp time.time() µs (the same wall clock the
                # flight recorder uses), so device lanes from another
                # clock domain can be shifted onto this one
                {"name": "clock_domain", "ph": "M", "pid": self.rank,
                 "args": {"domain": "wall", "export_wall_us":
                          time.time() * 1e6}}]
        d = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
        if dropped:
            d["droppedEvents"] = dropped
        return d

    def export(self, path=None):
        path = path or self.path
        if not path:
            return None
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_dict(), f)
        return path


# ------------------------------------------------- module-level singleton

_state_lock = threading.Lock()
_TR: TraceBuffer | None = None
_loaded = False
_atexit_armed = False


def _default_path(rank):
    d = os.environ.get("PADDLE_TPU_WORKERLOG_DIR") or "."
    return os.path.join(d, f"trace.{rank}.json")


def _load():
    global _TR, _loaded
    with _state_lock:
        if _loaded:
            return _TR
        raw = os.environ.get("PADDLE_TPU_TRACE", "")
        if raw in ("", "0", "false", "False"):
            _TR = None
        else:
            buf = TraceBuffer()
            if raw not in ("1", "true", "True"):
                buf.path = raw  # PADDLE_TPU_TRACE=/path.json
            else:
                buf.path = (os.environ.get("PADDLE_TPU_TRACE_PATH")
                            or _default_path(buf.rank))
            _TR = buf
            _arm_atexit()
        _loaded = True
        return _TR


def _arm_atexit():
    global _atexit_armed
    if not _atexit_armed:
        _atexit_armed = True
        atexit.register(_atexit_export)


def _atexit_export():
    buf = _TR
    if buf is not None and buf.path:
        try:
            buf.export()
        except Exception:
            pass


def get_buffer() -> TraceBuffer | None:
    return _TR if _loaded else _load()


def enabled() -> bool:
    return get_buffer() is not None


def start(path=None, rank=None) -> TraceBuffer:
    """Programmatic gate (tests / bench) — replaces the singleton."""
    global _TR, _loaded
    with _state_lock:
        _TR = TraceBuffer(rank=rank, path=path)
        _loaded = True
        _arm_atexit()
        return _TR


def stop(path=None):
    """Export (when a path is known) and disable; returns the path."""
    global _TR, _loaded
    with _state_lock:
        buf = _TR
        _TR = None
        _loaded = True
    if buf is None:
        return None
    try:
        return buf.export(path)
    except Exception as e:
        print(f"[trace] export failed: {e}", file=sys.stderr, flush=True)
        return None


def export(path=None):
    buf = _TR if _loaded else _load()
    return buf.export(path) if buf is not None else None


def _reset_state():
    """Test hook: back to the unresolved env-gated state."""
    global _TR, _loaded
    with _state_lock:
        _TR = None
        _loaded = False


# ------------------------------------------------------------------ feeds

@contextlib.contextmanager
def span(name, cat="host", **args):
    """Trace one host scope; a constant-time no-op when tracing is off."""
    buf = _TR if _loaded else _load()
    if buf is None:
        yield None
        return
    t0 = time.time()
    try:
        yield buf
    finally:
        buf.add(name, t0, time.time() - t0, cat=cat, args=args or None)


def add_complete(name, ts_s, dur_s, cat="host", tid=None, args=None):
    buf = _TR if _loaded else _load()
    if buf is not None:
        buf.add(name, ts_s, dur_s, cat=cat, tid=tid, args=args)


# -------------------------------------------------- request-trace feeds
#
# Hot-path discipline (the standing contract): tracing off, a request
# never gets a context minted, so every hook in scheduler/engine/router
# gates on ``req.trace is not None`` — one attribute check, no
# allocation, no call into this module.

def mint_context():
    """-> a fresh trace context ``{"tid", "ps"}`` (``ps`` 0 = root) when
    tracing is on, else None. The None is what makes the off path free:
    downstream hooks check the attribute, not this module."""
    buf = _TR if _loaded else _load()
    if buf is None:
        return None
    return {"tid": os.urandom(8).hex(), "ps": 0}


def _ctx_tid(ctx):
    if type(ctx) is dict:
        tid = ctx.get("tid")
        return str(tid) if tid else None
    return None


def req_event(ctx, name, ts_s, dur_s, cat="request", args=None):
    """Feed one span for the request identified by trace context ``ctx``
    into the tail-sampling pending buffer. No-op off / ctx-less."""
    buf = _TR if _loaded else _load()
    if buf is None or ctx is None:
        return
    tid = _ctx_tid(ctx)
    if tid is not None:
        buf.req_add(tid, name, ts_s, dur_s, cat=cat, args=args)


def sampled(tid, rate):
    """Deterministic head-of-trace sample: every process hashes the same
    trace id to the same verdict — no coordination, no wire bits."""
    if not rate or rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    return (_tid_bucket(tid) % 100_000) / 100_000.0 < rate


def finish_request(ctx, dur_s=None, error=False, hedged=False,
                   evicted=False, aborted=False, migrated=False):
    """Terminal-state tail-sampling decision for one request trace:
    retain when interesting (errored / hedged / evicted / aborted /
    migrated), slow (``PADDLE_TPU_TRACE_SLOW_MS``), or explicitly
    sampled (``PADDLE_TPU_TRACE_SAMPLE``); else drop the pending spans
    before they ever reach the export. Returns the verdict."""
    buf = _TR if _loaded else _load()
    if buf is None or ctx is None:
        return False
    tid = _ctx_tid(ctx)
    if tid is None:
        return False
    keep = bool(error or hedged or evicted or aborted or migrated)
    if not keep and buf.slow_ms is not None and dur_s is not None \
            and dur_s * 1e3 >= buf.slow_ms:
        keep = True
    if not keep:
        keep = sampled(tid, buf.sample_rate)
    return buf.req_finish(tid, keep)


def collective_event(entry):
    """Feed one completed flight-recorder entry as a trace event. Ring
    bookkeeping markers (``step`` group) are skipped; pipeline
    micro-batch entries keep their own category so the collective lane
    stays collectives-only."""
    buf = _TR if _loaded else _load()
    if buf is None or entry is None:
        return
    group = entry.get("group")
    if group == "step" or entry.get("aborted"):
        return
    t0, t1 = entry.get("t_issue"), entry.get("t_complete")
    if t0 is None or t1 is None:
        return
    cat = "pipeline" if group == "pipe" else "collective"
    args = {"group": group, "seq": entry.get("seq"),
            "gseq": entry.get("gseq")}
    if entry.get("shape") is not None:
        args["shape"] = str(entry["shape"])
    buf.add(entry.get("kind", "?"), t0, t1 - t0, cat=cat, args=args)
