"""paddle_tpu.hapi — high-level Model API.

Reference: python/paddle/hapi/model.py:1054 (Model, fit:1756) with the
dynamic-graph adapter. TPU-native: fit() compiles the whole train step via
jit.to_static capture, so the Keras-style loop runs at staged-XLA speed.
"""
from .model import Model  # noqa: F401
from . import callbacks  # noqa: F401
from .summary import summary  # noqa: F401
from .dynamic_flops import flops  # noqa: F401
