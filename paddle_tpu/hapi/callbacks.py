"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler"]


class Callback:
    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class ProgBarLogger(Callback):
    """Reference: hapi/callbacks.py ProgBarLogger — plus throughput: every
    log line carries ``ips`` (steps/sec) and the smoothed step time from
    the telemetry clock (an EMA over batch-end intervals), not just the
    loss."""

    def __init__(self, log_freq=1, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose
        from ..observability.telemetry import EMATimer
        self._timer = EMATimer()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        # eval/checkpoint pauses at epoch boundaries are not step time
        self._timer.reset()

    def on_train_batch_end(self, step, logs=None):
        _, ema = self._timer.tick()
        if self.verbose and step % self.log_freq == 0:
            shown = dict(logs or {})
            if ema:
                shown["step_ms"] = ema * 1e3
                shown["ips"] = 1.0 / ema
            items = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                               else f"{k}: {v}"
                               for k, v in shown.items())
            print(f"epoch {self.epoch} step {step}: {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            items = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                               else f"{k}: {v}"
                               for k, v in (logs or {}).items())
            print(f"epoch {epoch} done: {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="min", patience=0, min_delta=0,
                 baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.mode = mode
        self.min_delta = min_delta
        self.best = None
        self.wait = 0
        self.stop_training = False

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        better = self.best is None or (
            cur < self.best - self.min_delta if self.mode == "min"
            else cur > self.best + self.min_delta)
        if better:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched
        lr = getattr(self.model._optimizer, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s is not None and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s is not None and self.by_epoch:
            s.step()


class ReduceLROnPlateau(Callback):
    """Reference: hapi/callbacks.py ReduceLROnPlateau — shrink the optimizer
    lr when the monitored metric stops improving."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, mode="min",
                 min_delta=1e-4, cooldown=0, min_lr=0.0, verbose=1):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.mode = mode
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.verbose = verbose
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        if self._better(cur):
            self.best = cur
            self.wait = 0
            return
        if self.cooldown_counter > 0:
            # cooldown suppresses wait accrual entirely (Keras/reference)
            self.cooldown_counter -= 1
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is not None:
                lr = opt.get_lr()
                new_lr = max(lr * self.factor, self.min_lr)
                if new_lr < lr:
                    opt.set_lr(new_lr)
                    if self.verbose:
                        print(f"ReduceLROnPlateau: lr {lr:.2e} -> "
                              f"{new_lr:.2e}")
            self.cooldown_counter = self.cooldown
            self.wait = 0
