"""paddle.flops — per-layer FLOPs estimation.

Reference: python/paddle/hapi/dynamic_flops.py (flops(net, input_size)
walks sublayers with hooks and a per-type FLOPs table)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["flops"]


def _linear_flops(layer, x, y):
    return int(np.prod(x.shape)) * layer.weight.shape[-1]


def _conv_flops(layer, x, y):
    kernel_ops = int(np.prod(layer.weight.shape[1:]))  # Cin/g * k...
    bias_ops = 1 if getattr(layer, "bias", None) is not None else 0
    return int(np.prod(y.shape)) * (kernel_ops + bias_ops)


def _norm_flops(layer, x, y):
    return 2 * int(np.prod(x.shape))


def _act_flops(layer, x, y):
    return int(np.prod(x.shape))


def _pool_flops(layer, x, y):
    return int(np.prod(y.shape))


def _emb_flops(layer, x, y):
    return int(np.prod(y.shape))


_TABLE = {
    "Linear": _linear_flops,
    "Conv1D": _conv_flops, "Conv2D": _conv_flops, "Conv3D": _conv_flops,
    "BatchNorm1D": _norm_flops, "BatchNorm2D": _norm_flops,
    "BatchNorm3D": _norm_flops, "LayerNorm": _norm_flops,
    "GroupNorm": _norm_flops, "RMSNorm": _norm_flops,
    "ReLU": _act_flops, "ReLU6": _act_flops, "GELU": _act_flops,
    "Sigmoid": _act_flops, "Tanh": _act_flops, "Softmax": _act_flops,
    "MaxPool2D": _pool_flops, "AvgPool2D": _pool_flops,
    "AdaptiveAvgPool2D": _pool_flops, "MaxPool1D": _pool_flops,
    "MaxPool3D": _pool_flops,
    "Embedding": _emb_flops,
}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Total multiply-accumulate count for one forward pass at input_size
    (INCLUDING the batch dim; -1 means 1). Returns an int."""
    import jax.numpy as jnp

    table = dict(_TABLE)
    if custom_ops:
        table.update({getattr(k, "__name__", str(k)): v
                      for k, v in custom_ops.items()})
    shape = [1 if d == -1 else int(d) for d in input_size]
    x = Tensor(jnp.zeros(shape, jnp.float32))

    rows = []
    hooks = []

    def mk(name, layer, fn):
        def hook(lyr, ins, out):
            o = out[0] if isinstance(out, (tuple, list)) else out
            n = int(fn(lyr, ins[0], o))
            params = sum(int(np.prod(p.shape)) for p in
                         lyr.parameters(include_sublayers=False))
            rows.append((f"{type(lyr).__name__}-{name}", params, n))
        return hook

    for name, layer in net.named_sublayers():
        fn = table.get(type(layer).__name__)
        if fn is not None and not list(layer.children()):
            hooks.append(layer.register_forward_post_hook(
                mk(name, layer, fn)))
    if not hooks:
        # bare-layer model: named_sublayers never yields the net itself,
        # so a plain nn.Linear used as the whole network counted 0 (and
        # telemetry read MFU=0). Hook the net when it is itself a leaf
        # with a table entry.
        fn = table.get(type(net).__name__)
        if fn is not None and not list(net.children()):
            hooks.append(net.register_forward_post_hook(
                mk("net", net, fn)))
    was_training = net.training
    net.eval()
    try:
        net(x)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = sum(r[2] for r in rows)
    if print_detail:
        print(f"{'Layer':<30}{'Params':>12}{'FLOPs':>16}")
        for name, params, n in rows:
            print(f"{name:<30}{params:>12,}{n:>16,}")
        print(f"Total GFLOPs: {total / 1e9:.4f}")
    return total
