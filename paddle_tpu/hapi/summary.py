"""paddle.summary — layer-by-layer model summary.

Reference: python/paddle/hapi/model_summary.py (summary walks sublayers
with forward hooks, prints a table of output shapes and parameter counts).
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["summary"]


def summary(net, input_size=None, dtypes=None, input=None):
    """Print a per-layer table; returns {'total_params', 'trainable_params'}.

    input_size: tuple (or list of tuples) INCLUDING the batch dim, with -1
    meaning 1 (reference semantics)."""
    import jax.numpy as jnp

    if input is None:
        assert input_size is not None, "input_size or input required"
        sizes = [input_size] if isinstance(input_size[0], int) \
            else list(input_size)
        dts = dtypes if isinstance(dtypes, (list, tuple)) else \
            [dtypes or "float32"] * len(sizes)
        inputs = [Tensor(jnp.zeros([1 if d == -1 else d for d in s],
                                   dt)) for s, dt in zip(sizes, dts)]
    else:
        inputs = [input] if isinstance(input, Tensor) else list(input)

    rows = []
    hooks = []

    def mk_hook(name, layer):
        def hook(lyr, ins, out):
            shape = list(out.shape) if isinstance(out, Tensor) else \
                [list(o.shape) for o in out if isinstance(o, Tensor)]
            n_params = sum(int(np.prod(p.shape))
                           for p in lyr.parameters(include_sublayers=False))
            rows.append((f"{type(lyr).__name__}-{name}", shape, n_params))
        return hook

    for name, layer in net.named_sublayers():
        if not list(layer.children()):  # leaves only, reference behavior
            hooks.append(layer.register_forward_post_hook(
                mk_hook(name, layer)))
    was_training = net.training
    net.eval()
    try:
        net(*inputs)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    header = f"{'Layer (type)':<28}{'Output Shape':<26}{'Param #':>12}"
    sep = "=" * len(header)
    lines = [sep, header, sep]
    for name, shape, n in rows:
        lines.append(f"{name:<28}{str(shape):<26}{n:>12,}")
    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)
    lines += [sep, f"Total params: {total:,}",
              f"Trainable params: {trainable:,}",
              f"Non-trainable params: {total - trainable:,}", sep]
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
