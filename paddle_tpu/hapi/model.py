"""hapi.Model — Keras-style fit/evaluate/predict.

Reference: python/paddle/hapi/model.py:1054 (Model), fit at :1756, dynamic
adapter at :821. The train step is staged once via jit.to_static capture and
reused across the whole fit loop.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..io import DataLoader, Dataset
from ..jit.api import StaticFunction, to_static
from ..observability import telemetry as _telemetry
from ..observability import tracing as _tracing

__all__ = ["Model"]


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = list(inputs) if inputs is not None else None
        self._labels = list(labels) if labels is not None else None
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._step_fn = None

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        """Reference: Model.prepare (hapi/model.py:2006) — including the
        distributed adapter (:821): when the parallel env is initialized,
        the network is wrapped in DataParallel so fit() trains
        data-parallel, and amp_configs ('O1'/'O2' or {'level': ...})
        stages the train step under auto_cast."""
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _as_list(metrics)
        self._amp_level = None
        if amp_configs is not None:
            if isinstance(amp_configs, str):
                self._amp_level = amp_configs
            elif isinstance(amp_configs, dict):
                self._amp_level = amp_configs.get("level", "O1")
        from ..distributed import env as _denv
        from ..distributed.parallel import DataParallel
        if _denv.is_initialized() and _denv.get_world_size() > 1 and \
                not isinstance(self.network, DataParallel):
            self.network = DataParallel(self.network)

    # ---- single-batch entry points (reference: train_batch/eval_batch) ----
    def _build_step(self):
        net, loss_fn, opt = self.network, self._loss, self._optimizer
        amp_level = getattr(self, "_amp_level", None)

        def train_step(x, y):
            if amp_level:
                from ..amp import auto_cast
                with auto_cast(level=amp_level, dtype="bfloat16"):
                    out = net(x)
                    loss = loss_fn(out, y)
            else:
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss, out

        from ..nn import Layer
        capture_net = net if isinstance(net, Layer) else net._layers
        self._step_fn = to_static(train_step, capture=(capture_net, opt))
        return self._step_fn

    def train_batch(self, inputs, labels=None, update=True, sync=True):
        """One train step. ``sync=False`` (the fit loop's fast path, only
        taken when no user metrics are attached) returns the loss as a LAZY
        scalar Tensor without the blocking device→host fetch — under jax's
        async dispatch that fetch is what serializes the step pipeline, so
        the fit loop amortizes it over ``loss_fetch_every`` steps."""
        x = inputs[0] if isinstance(inputs, (list, tuple)) else inputs
        y = labels[0] if isinstance(labels, (list, tuple)) else labels
        if not update:
            # gradient accumulation: eager fwd/bwd without the staged update
            with _tracing.span("fwd"):
                out = self.network(x)
                loss = self._loss(out, y)
            with _tracing.span("bwd"):
                loss.backward()
        else:
            step = self._step_fn or self._build_step()
            loss, out = step(x, y)
        if not sync and not self._metrics:
            return loss
        # under async dispatch the fetch below is where the host really
        # waits for the device: telemetry splits it out as sync time
        _telemetry.mark_sync_begin()
        metrics = [self._fetch_scalar(loss)]
        for m in self._metrics:
            self._update_metric(m, out, y)
        return metrics[0] if len(metrics) == 1 else metrics

    def _guarded_step(self, guard, x, y, epoch, step):
        """One train step under gradient-fingerprint verification. EAGER
        on purpose: a staged step places in-program psums, leaving no
        pre-collective host payload to fingerprint. A mismatch raises out
        of ``backward()`` BEFORE any leaf writeback (parameters are still
        the synced pre-step values on every rank), so after blame/strike
        bookkeeping the step is simply redone — every rank sees the same
        store records and redoes in lockstep."""
        from ..distributed.integrity import GradFingerprintMismatch
        from ..distributed.parallel import DataParallel, shard_batch
        net, loss_fn, opt = self.network, self._loss, self._optimizer
        if isinstance(net, DataParallel):
            # forward() shard-batches the inputs itself; the labels meet
            # the (global) output inside the loss, so they need the same
            # dp-axis placement here on the eager path
            y = shard_batch(y, net._group)
        amp_level = getattr(self, "_amp_level", None)
        while True:
            if amp_level:
                from ..amp import auto_cast
                with auto_cast(level=amp_level, dtype="bfloat16"):
                    out = net(x)
                    loss = loss_fn(out, y)
            else:
                out = net(x)
                loss = loss_fn(out, y)
            try:
                loss.backward()
            except GradFingerprintMismatch as err:
                guard.on_mismatch(err, epoch, step)  # raises past max_redos
                opt.clear_grad()
                continue
            opt.step()
            opt.clear_grad()
            for m in self._metrics:
                self._update_metric(m, out, y)
            return loss

    # the ONE funnel for blocking loss fetches — the bounded-host-sync
    # regression test counts calls here, so a reintroduced per-step fetch
    # fails structurally instead of by wall clock
    @staticmethod
    def _fetch_scalar(loss):
        return float(loss.numpy())

    @staticmethod
    def _fetch_scalars(losses):
        """Fetch a batch of pending scalar losses with ONE host sync."""
        if not losses:
            return []
        import jax.numpy as jnp
        vals = np.asarray(jnp.stack(
            [ls._data if isinstance(ls, Tensor) else jnp.asarray(ls)
             for ls in losses]))
        return [float(v) for v in vals]

    @classmethod
    def _resolve_losses(cls, losses):
        """Turn a mixed float/lazy-Tensor loss list into floats — the
        Tensors (steps between amortized fetches) resolve in one sync."""
        idx = [i for i, ls in enumerate(losses) if isinstance(ls, Tensor)]
        if not idx:
            return losses
        vals = cls._fetch_scalars([losses[i] for i in idx])
        out = list(losses)
        for i, v in zip(idx, vals):
            out[i] = v
        return out

    @staticmethod
    def _update_metric(m, out, y):
        res = m.compute(out, y)
        if isinstance(res, tuple):
            m.update(*res)
        else:
            m.update(res)

    def eval_batch(self, inputs, labels=None):
        x = inputs[0] if isinstance(inputs, (list, tuple)) else inputs
        y = labels[0] if isinstance(labels, (list, tuple)) else labels
        out = self.network(x)
        loss = self._loss(out, y) if self._loss else None
        for m in self._metrics:
            self._update_metric(m, out, y)
        return float(loss.numpy()) if loss is not None else None

    def predict_batch(self, inputs):
        x = inputs[0] if isinstance(inputs, (list, tuple)) else inputs
        return self.network(x)

    # ---- loops ----
    def _loader(self, data, batch_size, shuffle, epoch_keyed=False):
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            from ..distributed import env as _denv
            import jax as _jax
            if _denv.is_initialized() and _jax.process_count() > 1:
                # multi-controller: each process loads its own shard
                # (reference: fit's DistributedBatchSampler path)
                from ..io import DistributedBatchSampler
                sampler = DistributedBatchSampler(
                    data, batch_size=batch_size, shuffle=shuffle)
                return DataLoader(data, batch_sampler=sampler)
            if epoch_keyed and shuffle:
                # resumable fit: the plain RandomSampler draws from the
                # numpy global RNG, which snapshots do not capture — a
                # resumed incarnation would iterate a DIFFERENT
                # permutation and skip the wrong batches. The sharded
                # sampler at nranks=1 shuffles epoch-keyed
                # (RandomState(epoch)), identical across incarnations.
                from ..io import DistributedBatchSampler
                sampler = DistributedBatchSampler(
                    data, batch_size=batch_size, num_replicas=1, rank=0,
                    shuffle=True)
                return DataLoader(data, batch_sampler=sampler)
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle)
        raise TypeError(f"expected Dataset or DataLoader, got {type(data)}")

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            num_iters=None, lineage=None, snapshot_interval=None,
            async_snapshot=False, loss_fetch_every=None, integrity=None):
        """Reference: Model.fit (hapi/model.py:1756).

        ``loss_fetch_every`` amortizes the blocking device→host loss fetch:
        with no user metrics attached the loop keeps the loss as a lazy
        device scalar and fetches every N steps (default: each attached
        ProgBarLogger's log_freq, else 50) plus once per epoch, so the
        compiled train step streams back-to-back instead of the host
        draining the device every step. Pass ``1`` to restore the strict
        per-step fetch. Per-step ``logs["loss"]`` holds the most recently
        fetched value between fetches; epoch means and ``history`` are
        exact either way.

        ``lineage`` (a ``distributed.fault.CheckpointLineage`` or a root
        directory path) makes the loop RESUMABLE: on entry the newest
        verified snapshot restores model/optimizer/RNG and the exact
        epoch+batch position (already-consumed batches of the resumed
        epoch are skipped, never double-counted), snapshots land every
        ``snapshot_interval`` steps and at every epoch boundary
        (``async_snapshot=True`` overlaps serialization, IO and the
        commit barrier with training), and SIGTERM converts into a
        synchronized save + exit 75 which the launcher resumes without
        consuming its restart budget. When ``train_data`` is a Dataset
        the loop makes the iteration order deterministic itself (an
        epoch-keyed shuffle, identical across incarnations); a
        user-supplied DataLoader must provide that determinism for exact
        batch-skip resume (shuffle=False or a seeded/epoch-keyed
        shuffle).

        ``integrity`` (True / a dict of ``TrainingGuard`` knobs / a
        guard instance) arms the training integrity guard
        (``distributed.integrity``): per-step loss health gates
        (median+MAD z-score with NaN/Inf folded in), optional
        cross-rank gradient fingerprints with rank blame + step redo
        under eager DP (``fingerprints=True`` — needs comm overlap and
        ``PADDLE_TPU_FR_STORE``), and automatic rewind-and-skip through
        ``lineage`` on a sustained anomaly. The guard needs the host
        loss value every step, so it forces the blocking fetch the
        amortized cadence otherwise avoids — a documented cost of
        ``integrity=``; with it unset (the default) the loop is
        structurally unchanged."""
        from .callbacks import Callback, ProgBarLogger
        cbs = _as_list(callbacks)
        if verbose and not any(isinstance(c, ProgBarLogger) for c in cbs):
            cbs.append(ProgBarLogger(log_freq, verbose))
        # per-step telemetry (PADDLE_TPU_METRICS=1): step-time breakdown,
        # tokens/sec and MFU into the metrics registry; attach a
        # TelemetryCallback yourself to override flops/tokens
        tm = next((c for c in cbs
                   if isinstance(c, _telemetry.TelemetryCallback)), None)
        if tm is None:
            tm = _telemetry.maybe_telemetry_callback()
            if tm is not None:
                cbs.append(tm)
        for c in cbs:
            c.set_model(self)
        loader = self._loader(train_data, batch_size, shuffle,
                              epoch_keyed=lineage is not None)
        rt = None
        if lineage is not None:
            from ..distributed.resumable import ResumableTraining
            rt = ResumableTraining(
                lineage, network=self.network, optimizer=self._optimizer,
                interval=snapshot_interval, async_snapshot=async_snapshot)
            rt.restore()
        guard = None
        if integrity is not None and integrity is not False:
            from ..distributed.integrity import make_guard
            guard = make_guard(integrity)
            guard.attach_fingerprints(self.network)
            if rt is not None:
                # a rewind target must exist even if an anomaly trips
                # before the first interval snapshot
                rt.ensure_baseline()
        history = {"loss": []}
        # amortized loss-fetch cadence: align with the tightest progress
        # logger so every PRINTED loss is fresh, never force a per-step
        # device drain just to fill a logs dict nobody reads
        if loss_fetch_every is None:
            freqs = [c.log_freq for c in cbs
                     if isinstance(c, ProgBarLogger) and c.verbose]
            loss_fetch_every = min(freqs) if freqs else 50
        loss_fetch_every = max(1, int(loss_fetch_every))
        lazy_loss = not self._metrics
        for c in cbs:
            c.on_train_begin()
        it = rt.global_step if rt is not None else 0
        done = False
        try:
            # explicit epoch cursor (not a range): the integrity guard's
            # rewind restores rt to an earlier epoch/step and the loop
            # must re-enter there to replay with the window skipped
            epoch = rt.epoch if rt is not None else 0
            rewound = False
            while epoch < epochs:
                if done:
                    break
                self.network.train()
                sampler = getattr(loader, "batch_sampler", None)
                if hasattr(sampler, "set_epoch"):
                    # per-epoch reshuffle (reference set_epoch idiom) — and
                    # the key a resumed incarnation replays the same
                    # permutation from
                    sampler.set_epoch(epoch)
                for c in cbs:
                    c.on_epoch_begin(epoch)
                for m in self._metrics:
                    m.reset()
                epoch_losses = []
                shown_loss = None  # most recently FETCHED loss float
                suspect = False    # guard flagged the latest step
                for step, batch in enumerate(loader):
                    if rt is not None and rt.skip_batch(epoch, step):
                        continue  # consumed before the restart
                    if num_iters is not None and it >= num_iters:
                        done = True
                        break
                    if rt is not None:
                        rt.poll_preempt(epoch, step)
                    x, y = batch[0], batch[1]
                    if guard is not None:
                        y = guard.maybe_poison(y)
                    if tm is not None:
                        tm.batch_ready(x)  # data wait ends here
                    for c in cbs:
                        c.on_train_batch_begin(step)
                    if guard is not None and guard.fingerprints_active():
                        loss = self._guarded_step(guard, x, y, epoch, step)
                    else:
                        loss = self.train_batch(x, y, sync=not lazy_loss)
                    if guard is not None and isinstance(loss, Tensor):
                        # the health gate scores every step's HOST value:
                        # integrity= pays the per-step fetch (documented
                        # cost), through the one counted funnel
                        _telemetry.mark_sync_begin()
                        loss = self._fetch_scalar(loss)
                        shown_loss = loss
                    if isinstance(loss, Tensor):
                        # lazy loss: fetch on the cadence, keep the device
                        # pipeline full in between. shown_loss None means
                        # no fetch has happened yet THIS epoch (e.g. a
                        # mid-epoch resume skipped past step 0): fetch so
                        # callbacks never see logs={"loss": None}
                        if step % loss_fetch_every == 0 or \
                                shown_loss is None:
                            _telemetry.mark_sync_begin()
                            loss = self._fetch_scalar(loss)
                            shown_loss = loss
                    else:
                        shown_loss = loss
                    if guard is not None:
                        verdict = guard.observe_loss(loss, epoch, step, it)
                        if verdict == "rewind":
                            guard.rewind(rt, epoch, step)
                            it = rt.global_step
                            rewound = True
                            break
                        suspect = verdict is not None
                    epoch_losses.append(loss)
                    logs = {"loss": shown_loss}
                    for m in self._metrics:
                        logs[m.name()] = m.accumulate()
                    for c in cbs:
                        c.on_train_batch_end(step, logs)
                    it += 1
                    if rt is not None:
                        try:
                            last = step + 1 == len(loader)
                        except TypeError:  # unsized iterable loader
                            last = False
                        rt.step_done(epoch, step, defer_to_epoch=last,
                                     suspect=suspect)
                        if tm is not None:
                            # a sync interval snapshot must not read as
                            # data wait in the next step's split
                            tm.note_pause()
                if rewound:
                    rewound = False
                    epoch = rt.epoch
                    continue  # replay from the restored snapshot state
                if not epoch_losses:
                    if rt is not None and epoch == rt.epoch \
                            and rt.step_in_epoch > 0:
                        epoch += 1
                        continue  # resumed exactly at this epoch's end
                    break
                epoch_losses = self._resolve_losses(epoch_losses)
                logs = {"loss": float(np.mean(epoch_losses))}
                for m in self._metrics:
                    logs[m.name()] = m.accumulate()
                history["loss"].append(logs["loss"])
                if eval_data is not None and (epoch + 1) % eval_freq == 0:
                    eval_logs = self.evaluate(eval_data, batch_size=batch_size,
                                              verbose=0)
                    logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
                    for c in cbs:
                        c.on_eval_end(eval_logs)
                for c in cbs:
                    c.on_epoch_end(epoch, logs)
                if save_dir and (epoch + 1) % save_freq == 0:
                    self.save(f"{save_dir}/{epoch}")
                if rt is not None and not done and not suspect:
                    # a num_iters cut mid-epoch must NOT snapshot the epoch
                    # as complete — resuming would silently skip its tail;
                    # a guard-suspect tail must not snapshot possibly-
                    # corrupted parameters as the boundary either
                    rt.epoch_done(epoch)
                if any(getattr(c, "stop_training", False) for c in cbs):
                    break
                epoch += 1
        except BaseException:
            if rt is not None:
                # drain the in-flight overlapped snapshot so the
                # error path still leaves a complete, committed
                # last snapshot on disk
                try:
                    rt.finalize()
                except Exception:
                    pass  # never mask the training error
            raise
        finally:
            if tm is not None:
                # the error path must clear the module-global telemetry
                # clock and flush the last window too (idempotent: the
                # success path's on_train_end below becomes a no-op)
                try:
                    tm.on_train_end()
                except Exception:
                    pass
        for c in cbs:
            c.on_train_end()
        if rt is not None:
            rt.finalize()
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        self.network.eval()
        loader = self._loader(eval_data, batch_size, False)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            loss = self.eval_batch(batch[0], batch[1])
            if loss is not None:
                losses.append(loss)
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            logs[m.name()] = m.accumulate()
        self.network.train()
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        self.network.eval()
        loader = self._loader(test_data, batch_size, False)
        outs = []
        for batch in loader:
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            outs.append(self.predict_batch(x).numpy())
        self.network.train()
        if stack_outputs:
            return [np.concatenate(outs, axis=0)]
        return outs

    # ---- persistence ----
    def save(self, path, training=True):
        """training=True: params (+ optimizer) checkpoints; training=False:
        AOT inference export via jit.save (StableHLO — the reference's
        save_inference_model analog)."""
        if not training:
            from ..jit.save_load import save as _jit_save
            _jit_save(self.network, path, input_spec=self._inputs)
            return
        from .. import save as _save
        _save(self.network.state_dict(), path + ".pdparams")
        if self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from .. import load as _load
        self.network.set_state_dict(_load(path + ".pdparams"))
        import os
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(_load(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        """Reference: hapi/model.py Model.summary → model_summary.summary."""
        from .summary import summary as _summary
        if input_size is None and self._inputs:
            input_size = [tuple(s.shape) for s in self._inputs]
        return _summary(self.network, input_size, dtypes=dtype)
