"""Weight-decay regularizers (reference: python/paddle/regularizer.py)."""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __repr__(self):
        return f"L1Decay({self.coeff})"


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __repr__(self):
        return f"L2Decay({self.coeff})"
