"""paddle_tpu.metric (reference: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()

    def compute(self, pred, label, *args):
        return pred, label


class Accuracy(Metric):
    """Top-k accuracy (reference: metrics.py Accuracy)."""

    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        p = _np(pred)
        l = _np(label).reshape(-1)
        order = np.argsort(-p, axis=-1)[:, :self.maxk]
        correct = order == l[:, None]
        return correct

    def update(self, correct):
        correct = _np(correct)
        res = []
        for i, k in enumerate(self.topk):
            c = correct[:, :k].sum()
            self.total[i] += c
            self.count[i] += correct.shape[0]
            res.append(c / correct.shape[0])
        return res[0] if len(res) == 1 else res

    def accumulate(self):
        out = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return out[0] if len(out) == 1 else out

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).reshape(-1)
        l = _np(labels).reshape(-1).astype(bool)
        self.tp += int((p & l).sum())
        self.fp += int((p & ~l).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).reshape(-1)
        l = _np(labels).reshape(-1).astype(bool)
        self.tp += int((p & l).sum())
        self.fn += int((~p & l).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via threshold buckets (reference: metrics.py Auc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self.num_thresholds = num_thresholds
        self._name = name or "auc"
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        l = _np(labels).reshape(-1)
        idx = np.clip((p * self.num_thresholds).astype(int), 0,
                      self.num_thresholds)
        for i, li in zip(idx, l):
            if li:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # integrate over descending thresholds
        pos = np.cumsum(self._stat_pos[::-1])
        neg = np.cumsum(self._stat_neg[::-1])
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        trapz = getattr(np, 'trapezoid', None) or np.trapz
        return float(trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1):
    """Functional top-k accuracy (paddle.metric.accuracy)."""
    p = _np(input)
    l = _np(label).reshape(-1)
    order = np.argsort(-p, axis=-1)[:, :k]
    correct = (order == l[:, None]).any(axis=1).mean()
    return Tensor(np.asarray(correct, np.float32))
