"""Post-training quantization — observer framework + PTQ driver.

Reference: python/paddle/quantization/{ptq.py,observer.py,
observers/abs_max.py} (PTQ.quantize inserts observers, sample data flows
through, convert() folds observed scales into quantized layers). TPU notes:
int8 inference math is emulated as fake-quant (quant-dequant) around
matmuls — XLA folds the scales into fused kernels; true int8 matmul on TPU
arrives via quantized HLO and keeps this same observer/scale interface.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import nn
from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["BaseObserver", "AbsmaxObserver", "EMAObserver",
           "HistObserver", "KLObserver", "PTQ", "QuantedLinearPTQ"]


class BaseObserver(nn.Layer):
    """Reference: quantization/factory.py ObserverFactory product — an
    observer watches activations flowing through and derives a scale."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self.quant_bits = quant_bits

    def forward(self, x):
        self._observe(np.asarray(jnp.abs(x._data).max()))
        return x

    def _observe(self, absmax):
        raise NotImplementedError

    def scale(self):
        raise NotImplementedError

    def quant_axis(self):
        return -1


class AbsmaxObserver(BaseObserver):
    """Running max of |x| (reference: observers/abs_max.py)."""

    def __init__(self, quant_bits=8):
        super().__init__(quant_bits)
        self._max = 0.0

    def _observe(self, absmax):
        self._max = max(self._max, float(absmax))

    def scale(self):
        return self._max / (2 ** (self.quant_bits - 1) - 1) or 1e-8


class EMAObserver(BaseObserver):
    """Exponential-moving-average absmax (reference: emd/mse family)."""

    def __init__(self, quant_bits=8, decay=0.9):
        super().__init__(quant_bits)
        self.decay = decay
        self._ema = None

    def _observe(self, absmax):
        a = float(absmax)
        self._ema = a if self._ema is None else \
            self.decay * self._ema + (1 - self.decay) * a

    def scale(self):
        return (self._ema or 0.0) / (2 ** (self.quant_bits - 1) - 1) \
            or 1e-8


class HistObserver(BaseObserver):
    """Histogram percentile scale (reference: observers/hist.py)."""

    def __init__(self, quant_bits=8, bins=2048, percent=0.999):
        super().__init__(quant_bits)
        self.bins = bins
        self.percent = percent
        self._samples: list = []

    def forward(self, x):
        self._samples.append(np.abs(np.asarray(x._data)).reshape(-1))
        return x

    def _observe(self, absmax):
        pass

    def scale(self):
        if not self._samples:
            return 1e-8
        allv = np.concatenate(self._samples)
        hist, edges = np.histogram(allv, bins=self.bins)
        cum = np.cumsum(hist) / max(len(allv), 1)
        idx = int(np.searchsorted(cum, self.percent))
        vmax = edges[min(idx + 1, len(edges) - 1)]
        return float(vmax) / (2 ** (self.quant_bits - 1) - 1) or 1e-8


class KLObserver(HistObserver):
    """KL-divergence calibration (reference: observers/kl.py): pick the
    clip threshold whose quantized distribution diverges least."""

    def scale(self):
        if not self._samples:
            return 1e-8
        allv = np.concatenate(self._samples)
        hist, edges = np.histogram(allv, bins=self.bins)
        p_full = hist / max(hist.sum(), 1)
        levels = 2 ** (self.quant_bits - 1)
        best_kl, best_edge = np.inf, edges[-1]
        for cut_idx in range(levels, self.bins + 1, self.bins // 32 or 1):
            p = hist[:cut_idx].astype(np.float64).copy()
            p[-1] += hist[cut_idx:].sum()  # clip mass into the last bin
            # quantize the histogram into `levels` buckets and expand back
            factor = cut_idx / levels
            q = np.zeros_like(p)
            for i in range(levels):
                lo, hi = int(i * factor), max(int((i + 1) * factor),
                                              int(i * factor) + 1)
                q[lo:hi] = p[lo:hi].sum() / (hi - lo)
            mask = p > 0
            pm = p[mask] / p.sum()
            qm = np.maximum(q[mask], 1e-12)
            qm = qm / qm.sum()
            kl = float((pm * np.log(pm / qm)).sum())
            if kl < best_kl:
                best_kl, best_edge = kl, edges[cut_idx]
        return float(best_edge) / (2 ** (self.quant_bits - 1) - 1) or 1e-8


class QuantedLinearPTQ(nn.Layer):
    """Converted inference layer: weights stored int8 + scale, activations
    fake-quantized with the observed scale."""

    def __init__(self, linear, act_scale, quant_bits=8):
        super().__init__()
        w = linear.weight
        qmax = 2 ** (quant_bits - 1) - 1
        self.w_scale = float(np.abs(np.asarray(w._data)).max() / qmax) \
            or 1e-8
        wq = np.clip(np.round(np.asarray(w._data) / self.w_scale),
                     -qmax - 1, qmax).astype(np.int8)
        self.register_buffer("w_int8", Tensor(wq))
        self.bias = linear.bias
        self.act_scale = act_scale
        self.quant_bits = quant_bits

    def forward(self, x):
        bits = self.quant_bits

        def f(xa, wq, *rest):
            s = self.act_scale
            qmax = 2 ** (bits - 1) - 1
            xq = jnp.clip(jnp.round(xa / s), -qmax - 1, qmax)
            out = (xq * s) @ (wq.astype(jnp.float32) * self.w_scale)
            if rest:
                out = out + rest[0]
            return out

        ins = [x, self.w_int8] + ([self.bias] if self.bias is not None
                                  else [])
        return apply("quanted_linear", f, ins)


class PTQ:
    """Reference: quantization/ptq.py PTQ — quantize() inserts observers,
    calibration data flows, convert() emits the quantized model."""

    def __init__(self, config=None, observer_cls=AbsmaxObserver,
                 quant_bits=8):
        self.observer_cls = observer_cls
        self.quant_bits = quant_bits

    def quantize(self, model, inplace=False):
        assert inplace, "pass inplace=True (functional copy not supported)"
        self._observed = []
        for name, layer in list(model.named_sublayers()):
            if isinstance(layer, nn.Linear):
                obs = self.observer_cls(self.quant_bits)
                layer._ptq_observer = obs
                hook = layer.register_forward_pre_hook(
                    lambda lyr, ins, _o=obs: (_o(ins[0]),) + tuple(ins[1:]))
                self._observed.append((model, name, layer, obs, hook))
        return model

    def convert(self, model, inplace=False):
        assert inplace, "pass inplace=True"
        for owner, name, layer, obs, hook in self._observed:
            hook.remove()
            quanted = QuantedLinearPTQ(layer, obs.scale(), self.quant_bits)
            parent = owner
            parts = name.split(".")
            for p in parts[:-1]:
                parent = parent._sub_layers[p] if p in \
                    getattr(parent, "_sub_layers", {}) else getattr(parent,
                                                                    p)
            leaf = parts[-1]
            if leaf in getattr(parent, "_sub_layers", {}):
                parent._sub_layers[leaf] = quanted  # Sequential et al.
            else:
                setattr(parent, leaf, quanted)
        self._observed = []
        return model
