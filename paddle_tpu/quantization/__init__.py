"""paddle_tpu.quantization — QAT/PTQ (reference: python/paddle/quantization).

TPU-native: fake-quant is a straight-through-estimator op (round in forward,
identity gradient) that XLA fuses into the surrounding computation; int8
deployment maps onto XLA int8 matmuls (and the Pallas quantization-kernel
pattern in the guide).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor
from .. import nn

__all__ = ["quant_aware", "FakeQuanterWithAbsMax", "QuantConfig", "QAT",
           "quantize", "dequantize"]


def _ste_fake_quant(x, scale, bits):
    """Round-through-STE fake quantization (reference:
    quantization/quanters/abs_max.py FakeQuanterWithAbsMaxObserver)."""
    qmax = 2 ** (bits - 1) - 1

    def fwd(a, s):
        s = jnp.maximum(s, 1e-9)
        q = jnp.clip(jnp.round(a / s * qmax), -qmax, qmax)
        deq = q * s / qmax
        # straight-through: forward quantized, gradient identity
        return a + jax.lax.stop_gradient(deq - a)

    return apply("fake_quant", fwd, [x, scale])


class FakeQuanterWithAbsMax(nn.Layer):
    def __init__(self, bit_length=8, moving_rate=0.9, name=None):
        super().__init__()
        self.bits = bit_length
        self.moving_rate = moving_rate
        self.register_buffer("scale",
                             Tensor(np.ones((), np.float32)))

    def forward(self, x):
        if self.training:
            cur = float(jnp.max(jnp.abs(x._data)))
            prev = float(self.scale.numpy())
            self.scale._data = jnp.asarray(
                self.moving_rate * prev + (1 - self.moving_rate) * cur,
                jnp.float32)
        return _ste_fake_quant(x, self.scale, self.bits)


class QuantConfig:
    """Reference: quantization/config.py QuantConfig (subset)."""

    def __init__(self, activation=None, weight=None):
        self.activation_bits = activation or 8
        self.weight_bits = weight or 8


class _QuantedLinear(nn.Layer):
    def __init__(self, linear, config):
        super().__init__()
        self.inner = linear
        self.act_q = FakeQuanterWithAbsMax(config.activation_bits)
        self.w_q = FakeQuanterWithAbsMax(config.weight_bits)

    def forward(self, x):
        from ..nn import functional as F
        xq = self.act_q(x)
        wq = self.w_q(self.inner.weight)
        return F.linear(xq, wq, self.inner.bias)


class QAT:
    """Reference: quantization/qat.py QAT — wraps quantizable layers with
    fake quanters."""

    def __init__(self, config: QuantConfig = None):
        self.config = config or QuantConfig()

    def quantize(self, model, inplace=True):
        for name, sub in list(model._sub_layers.items()):
            if isinstance(sub, nn.Linear):
                model._sub_layers[name] = _QuantedLinear(sub, self.config)
            else:
                self.quantize(sub, inplace=True)
        return model


def quant_aware(model, config=None):
    return QAT(config).quantize(model)


def quantize(x, scale, bits=8):
    qmax = 2 ** (bits - 1) - 1
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    s = jnp.maximum(jnp.asarray(scale, jnp.float32), 1e-9)
    return Tensor(jnp.clip(jnp.round(arr / s * qmax), -qmax,
                           qmax).astype(jnp.int8))


def dequantize(q, scale, bits=8):
    qmax = 2 ** (bits - 1) - 1
    arr = q._data if isinstance(q, Tensor) else jnp.asarray(q)
    return Tensor(arr.astype(jnp.float32) * scale / qmax)


from .ptq import (  # noqa: E402,F401
    AbsmaxObserver, BaseObserver, EMAObserver, HistObserver, KLObserver,
    PTQ, QuantedLinearPTQ,
)
