"""paddle.hub — load models/entrypoints from a hubconf.py.

Reference: python/paddle/hub.py (list/help/load over a github/gitee repo or
local dir's hubconf.py). TPU-native environment has zero egress, so the
'github'/'gitee' sources raise with guidance; 'local' source has full
reference semantics (the reference uses the same _load_entry_from_local
path).
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_local_hubconf(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(
            f"no {_HUBCONF} found in {repo_dir} (reference: hub.py "
            "_import_module)")
    name = "paddle_tpu_hubconf_" + str(abs(hash(repo_dir)) % 10 ** 8)
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    return mod


def _check_source(source):
    if source not in ("local", "github", "gitee"):
        raise ValueError(
            f"unknown source {source!r}: expected 'local', 'github' or "
            "'gitee'")
    if source != "local":
        raise RuntimeError(
            f"source={source!r} needs network access, unavailable on this "
            "deployment; clone the repo and use source='local'")


def list(repo_dir, source="github", force_reload=False):
    """Reference: paddle.hub.list — entrypoint names in hubconf.py."""
    _check_source("local" if os.path.isdir(repo_dir) else source)
    mod = _load_local_hubconf(repo_dir)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="github", force_reload=False):
    """Reference: paddle.hub.help — the entrypoint's docstring."""
    _check_source("local" if os.path.isdir(repo_dir) else source)
    mod = _load_local_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise RuntimeError(f"no entrypoint named {model!r} in {repo_dir}")
    return getattr(mod, model).__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    """Reference: paddle.hub.load — call the entrypoint."""
    _check_source("local" if os.path.isdir(repo_dir) else source)
    mod = _load_local_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise RuntimeError(f"no entrypoint named {model!r} in {repo_dir}")
    return getattr(mod, model)(**kwargs)
