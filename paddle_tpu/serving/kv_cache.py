"""Paged KV cache — fixed-size blocks in a preallocated device pool.

Reference capability: the block-table KV layout of
``block_multi_head_attention`` (paddle/phi/kernels/fusion/gpu) and vLLM's
PagedAttention; TPU-native shape per Ragged Paged Attention
(arxiv 2604.15464): per-layer pools ``[num_pages, page_size, H, Dh]``, a
per-request **block table** of physical page ids, and a host-side
free-list allocator. This replaces the dense ``[B, T, H, Dh]`` buffers of
``models/gpt.py``'s compiled decode for serving: memory is bounded by
*tokens actually cached* (rounded up to one page), not by
``batch × max_seq_len``, so slots with short requests don't reserve the
worst case and the continuous-batching scheduler can admit until the pool
— not the batch shape — is full.

Physical page 0 is reserved as the **scrap page**: padded block-table
entries and inactive decode slots point at it, so masked lanes of the
batched decode step have a legal write/read target without branching.
All pool updates are functional (``.at[].set``) so the decode step can be
one jitted XLA program with donated pool buffers.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = ["BlockAllocator", "PagedKVCache", "pages_for", "OutOfPages"]


class OutOfPages(RuntimeError):
    """The pool cannot satisfy an allocation (caller may evict + retry)."""


def pages_for(n_tokens, page_size):
    """Pages needed to hold ``n_tokens`` (ceil division; 0 tokens -> 0)."""
    return -(-int(n_tokens) // int(page_size))


class BlockAllocator:
    """Refcounted free-list page allocator over ``num_pages`` physical
    pages.

    Page ids ``[0, reserved)`` are never handed out (page 0 is the scrap
    page). Purely host-side — allocation happens between decode steps on
    the scheduler thread, never inside the compiled step.

    **Refcounts + prefix sharing** (ISSUE 9): every live page carries a
    refcount (1 at :meth:`alloc`; :meth:`ref` adds readers — prefix-cache
    hits share one physical page across requests). :meth:`free` is a
    *deref*: the page returns to circulation only when the last reader
    drops it. A refcount-0 page whose content is still indexed by a
    :class:`~.prefix_cache.PrefixCache` (``self.cache``) parks in a
    **reclaimable LRU** instead of the free list — it stays a warm cache
    hit until the pool runs dry, at which point :meth:`alloc` reclaims
    LRU-oldest reclaimable pages (telling the cache to drop their index
    entries). A page with live readers is NEVER reclaimed — eviction
    pressure can only consume refcount-0 cached pages.
    """

    def __init__(self, num_pages, reserved=1):
        if num_pages <= reserved:
            raise ValueError(f"num_pages={num_pages} must exceed "
                             f"reserved={reserved}")
        self.num_pages = int(num_pages)
        self.reserved = int(reserved)
        # LIFO free list: recently-freed (still-warm) pages are reused first
        self._free = list(range(self.num_pages - 1, self.reserved - 1, -1))
        self._refs: dict[int, int] = {}      # page -> live reader count
        # refcount-0 pages still holding indexed prefix-cache content,
        # insertion order == LRU order (oldest first)
        self._reclaimable: dict[int, None] = {}
        self.cache = None                    # PrefixCache collaborator

    @property
    def capacity(self):
        """Allocatable pages (excludes the reserved scrap pages)."""
        return self.num_pages - self.reserved

    @property
    def free_pages(self):
        """Pages allocatable right now (truly free + reclaimable cached)."""
        return len(self._free) + len(self._reclaimable)

    @property
    def used_pages(self):
        """Pages held by live readers (cached-but-unreferenced excluded)."""
        return self.capacity - self.free_pages

    @property
    def cached_pages(self):
        """Refcount-0 pages parked for prefix-cache reuse."""
        return len(self._reclaimable)

    def refcount(self, page):
        return self._refs.get(int(page), 0)

    def shared_pages(self):
        """Pages with more than one live reader (prefix-shared)."""
        return sum(1 for rc in self._refs.values() if rc > 1)

    def occupancy_pct(self):
        return 100.0 * self.used_pages / self.capacity if self.capacity \
            else 0.0

    def can_alloc(self, n):
        return n <= self.free_pages

    def alloc(self, n):
        """-> list of ``n`` page ids, each with refcount 1; raises
        :class:`OutOfPages` when free + reclaimable pages are short
        (all-or-nothing: no partial grants). Reclaims LRU-oldest cached
        pages only after the free list is exhausted."""
        n = int(n)
        if n > self.free_pages:
            raise OutOfPages(
                f"need {n} page(s), {self.free_pages} free "
                f"of {self.capacity}")
        out = []
        for _ in range(n):
            if self._free:
                p = self._free.pop()
            else:
                p = next(iter(self._reclaimable))   # LRU oldest
                del self._reclaimable[p]
                if self.cache is not None:
                    self.cache.on_reclaim(p)
            self._refs[p] = 1
            out.append(p)
        return out

    def ref(self, pages):
        """Add one reader to each live page (prefix-cache sharing)."""
        for p in pages:
            p = int(p)
            rc = self._refs.get(p, 0)
            if rc <= 0:
                raise ValueError(
                    f"ref on page {p} with no live reader (free or "
                    "reclaimable pages must go through reuse_cached)")
            self._refs[p] = rc + 1

    def reuse_cached(self, page):
        """A prefix-cache hit on ``page``: add a reader, reactivating it
        from the reclaimable LRU if it was parked there. -> bool (False
        when the page is no longer available — stale index entry)."""
        page = int(page)
        if page in self._reclaimable:
            del self._reclaimable[page]
            self._refs[page] = 1
            return True
        rc = self._refs.get(page, 0)
        if rc > 0:
            self._refs[page] = rc + 1
            return True
        return False

    def free(self, pages):
        """Drop one reader per page. The last reader returns the page to
        the free list — or parks it in the reclaimable LRU when the
        prefix cache still indexes its content."""
        for p in pages:
            p = int(p)
            if p < self.reserved or p >= self.num_pages:
                raise ValueError(f"page {p} outside allocatable range")
            rc = self._refs.get(p, 0)
            if rc <= 0:
                raise ValueError(f"double free of page {p}")
            if rc > 1:
                self._refs[p] = rc - 1
                continue
            del self._refs[p]
            if self.cache is not None and self.cache.holds(p):
                self._reclaimable[p] = None     # newest = LRU tail
            else:
                self._free.append(p)


class PagedKVCache:
    """Per-layer K/V page pools + the allocator that parcels them out.

    ``k[l]`` / ``v[l]`` are jnp arrays ``[num_pages, page_size, H, Dh]``
    where ``H`` is the model's **KV** head count — for GQA models
    (``num_kv_heads < num_heads``) the pool carries only the KV heads, an
    ``H/KVH`` memory cut that directly raises how many concurrent
    requests the pool can hold.
    Decode-step writes happen *inside* the model's paged attention branch
    (functional scatter, see ``models/gpt.py``); this class owns prefill
    writes, the allocator, and test/debug gathers.
    """

    def __init__(self, num_layers, num_pages, page_size, num_heads,
                 head_dim, dtype=jnp.float32, reserved=1):
        self.num_layers = int(num_layers)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        shape = (self.num_pages, self.page_size, self.num_heads,
                 self.head_dim)
        self.k = [jnp.zeros(shape, dtype) for _ in range(self.num_layers)]
        self.v = [jnp.zeros(shape, dtype) for _ in range(self.num_layers)]
        self.allocator = BlockAllocator(num_pages, reserved=reserved)

    def nbytes(self):
        return 2 * self.num_layers * self.k[0].size * self.k[0].dtype.itemsize

    def occupancy_pct(self):
        return self.allocator.occupancy_pct()

    def write_prefill(self, layer, k_new, v_new, pages, length):
        """Write one request's prefill K/V (``[S, H, Dh]`` with
        ``S >= length``; rows past ``length`` are padding and dropped)
        into its ``pages``. The tail of the last page stays whatever it
        was — reads are masked by ``context_lens``."""
        n = len(pages)
        cap = n * self.page_size
        if length > cap:
            raise ValueError(f"{length} tokens > {n} page capacity {cap}")
        idx = jnp.asarray(np.asarray(pages, np.int32))
        for pool_list, new in ((self.k, k_new), (self.v, v_new)):
            arr = jnp.asarray(new)[:length].astype(self.dtype)
            pad = cap - length
            if pad:
                arr = jnp.pad(arr, ((0, pad), (0, 0), (0, 0)))
            arr = arr.reshape(n, self.page_size, self.num_heads,
                              self.head_dim)
            pool_list[layer] = pool_list[layer].at[idx].set(arr)

    def gather(self, layer, pages, length, which="k"):
        """Debug/test readback: the first ``length`` tokens of a request's
        pages as one dense ``[length, H, Dh]`` array."""
        pool = (self.k if which == "k" else self.v)[layer]
        idx = jnp.asarray(np.asarray(pages, np.int32))
        dense = pool[idx].reshape(-1, self.num_heads, self.head_dim)
        return dense[:length]
