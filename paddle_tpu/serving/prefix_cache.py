"""Prefix cache — content-addressed KV page sharing across requests.

Reference capability: vLLM's automatic prefix caching / SGLang's RadixAttention
mapped onto the TPU paged pool (Ragged Paged Attention, arxiv 2604.15464;
the Gemma-on-TPU serving study, arxiv 2605.25645, names shared-prefix KV
reuse as a first-order serving lever): thousands of requests sharing a
system-prompt head should prefill it ONCE.

Design — a **page-granular trie** over token content:

* Every *full* page of a prefilled prompt is indexed under the key
  ``(parent_page, page_tokens)`` — the parent link makes the index a trie
  whose path from the root spells out the whole token prefix, so a hit on
  page *i* guarantees the entire preceding context matches (KV content is
  position- and prefix-dependent; a raw per-page hash would alias).
* :meth:`lookup` walks the trie at admission and returns the longest run
  of cached full pages (**capped at ``len(prompt) - 1`` tokens** so the
  last prompt token is always computed — its logits produce the first
  generated token). Hit pages get a reader refcount via the allocator;
  the request chains its private tail pages after them.
* **Copy-on-write at page granularity**: only FULL pages are ever shared,
  and writes only target positions past the shared head — the first
  divergent (or partial-tail) token lands in a freshly-allocated private
  page, never in a shared one. Shared pages are structurally read-only.
* **Refcount-aware reclamation**: a released page whose content is still
  indexed parks in the allocator's reclaimable LRU instead of the free
  list; the pool reclaims LRU-oldest *refcount-0* pages when dry and
  calls :meth:`on_reclaim` so the index drops the page (and its now
  unreachable subtree). Pages with live readers are never reclaimed.

Host-side and model-agnostic, like the scheduler. One instance serves one
engine; page ids are shared across layers (every layer's pool is indexed
by the same block table), so sharing one page id shares all layers' KV.
"""
from __future__ import annotations

__all__ = ["PrefixCache"]

_ROOT = -1


class PrefixCache:
    """Trie index of cached KV pages over the engine's BlockAllocator."""

    def __init__(self, allocator, page_size):
        self.allocator = allocator
        allocator.cache = self
        self.page_size = int(page_size)
        self._index = {}     # (parent_page, tokens tuple) -> page id
        self._entry = {}     # page id -> its key in _index
        self._children = {}  # page id -> set of keys whose parent it is
        # counters (request-level hit/miss + token/page volume) — the
        # serving metrics frontend snapshots these every engine step
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.reclaimed_pages = 0

    # ------------------------------------------------------------- queries
    def holds(self, page):
        """Is this page's content still indexed? (Allocator consults this
        on last-reader free to park the page in the reclaimable LRU.)"""
        return int(page) in self._entry

    def indexed_pages(self):
        return len(self._entry)

    def hit_rate(self):
        n = self.hits + self.misses
        return self.hits / n if n else 0.0

    # -------------------------------------------------------------- lookup
    def lookup(self, tokens):
        """Longest cached full-page head of ``tokens`` -> (pages, n_tokens)
        with one reader refcount taken on every returned page (release
        with ``allocator.free`` if admission then fails). Capped so at
        least the last prompt token is left to compute. Does NOT touch
        the hit/miss counters — call :meth:`record` once the admission
        actually goes through."""
        ps = self.page_size
        max_hit_pages = (len(tokens) - 1) // ps
        node, pages = _ROOT, []
        for i in range(max_hit_pages):
            key = (node, tuple(tokens[i * ps:(i + 1) * ps]))
            page = self._index.get(key)
            if page is None:
                break
            if not self.allocator.reuse_cached(page):
                # the page slipped out from under the index (defensive:
                # on_reclaim should have dropped this entry) — drop it now
                self._drop_entry(key, page)
                break
            pages.append(page)
            node = page
        return pages, len(pages) * ps

    def record(self, n_shared_tokens):
        """Count one admitted request against the hit/miss totals."""
        if n_shared_tokens > 0:
            self.hits += 1
            self.hit_tokens += int(n_shared_tokens)
        else:
            self.misses += 1

    # -------------------------------------------------------------- insert
    def insert(self, tokens, pages):
        """Index every full page of a freshly-prefilled prompt (the
        request keeps its own refcount; future lookups add readers).
        Re-inserting an already-indexed chain is a no-op per page — the
        first owner's pages stay canonical, and a duplicate page holding
        identical content simply goes unindexed (it frees normally)."""
        ps = self.page_size
        node = _ROOT
        for i in range(len(tokens) // ps):
            key = (node, tuple(tokens[i * ps:(i + 1) * ps]))
            existing = self._index.get(key)
            if existing is not None:
                node = existing
                continue
            page = int(pages[i])
            if page in self._entry:
                # a page is indexed under at most one key (content is
                # unique per chain position); keep the first
                node = page
                continue
            self._index[key] = page
            self._entry[page] = key
            self._children.setdefault(node, set()).add(key)
            node = page

    def clear(self):
        """Drop EVERY index entry and zero the counters (bench/test
        isolation: a warm-up run's pages must not seed the measured
        run's cache). Pages themselves are untouched — live readers keep
        their refcounts, and already-parked reclaimable pages simply
        stop being hits and drift to the free list as they recycle."""
        self._index.clear()
        self._entry.clear()
        self._children.clear()
        self.hits = self.misses = self.hit_tokens = 0
        self.reclaimed_pages = 0

    # --------------------------------------------------------- reclamation
    def _drop_entry(self, key, page):
        self._index.pop(key, None)
        self._entry.pop(page, None)
        parent = key[0]
        kids = self._children.get(parent)
        if kids is not None:
            kids.discard(key)
            if not kids:
                del self._children[parent]

    def on_reclaim(self, page):
        """The allocator repurposed a reclaimable page: drop its index
        entry AND its whole subtree — descendants' chains run through
        this page, and a later re-index of the reused page id under new
        content must not resurrect them as false hits."""
        stack = [int(page)]
        while stack:
            p = stack.pop()
            key = self._entry.get(p)
            if key is not None:
                self._drop_entry(key, p)
            for k in self._children.pop(p, ()):  # subtree unreachable
                child = self._index.get(k)
                if child is None:
                    self._index.pop(k, None)
                    continue
                # route through _drop_entry: subclasses hook it (the
                # fleet's SharedPrefixCache unpublishes dropped pages
                # from the store-wide index there)
                self._drop_entry(k, child)
                stack.append(child)
        self.reclaimed_pages += 1
