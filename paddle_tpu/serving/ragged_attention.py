# tpu-lint: hot-path
"""Ragged paged attention for serving — one kernel, one launch, no buckets.

The serving incarnation of ``ops/pallas/ragged_attention.py`` (Ragged
Paged Attention, arxiv 2604.15464; ROADMAP open item 2): the engine's
whole scheduler round — single-token decode rows, budgeted prefill
chunks, prompt tails behind prefix-cache hits — rides ONE flattened
``[total_tokens, H, Dh]`` launch described by per-row metadata
(``row_starts`` / ``row_lens`` / ``kv_lens`` / block tables). The bucket
compile matrix (``_prefill_fns`` per (batch, seq) pair, ``_chunk_fns``
per (batch, chunk) pair, the fixed-slot decode program) collapses into a
few shape-specializations of one callable: only ``total_tokens`` is
padded, up the small power-of-two schedule of :func:`pad_total_tokens`.

Backend policy is the standing kernel rule, unchanged:

* ``xla`` — :func:`~paddle_tpu.ops.pallas.ragged_attention.
  ragged_paged_attention_reference`: the gather/segment formulation XLA
  compiles anywhere (CPU-parity source of truth).
* ``pallas`` — the flat-token scalar-prefetch kernel. TPU-only.
* ``auto`` — :func:`ab_compare_ragged` times both at the engine's ragged
  shape through ``ops/pallas/_common.ab_gate`` (verdict cached under
  ``ragged_paged_attention``); Pallas serves only where it measurably
  wins and never off-TPU. Resolution order is the serving gate's:
  ``PADDLE_TPU_SERVING_ATTN`` then ``PADDLE_TPU_KERNELS`` then ``auto``
  (:func:`~.decode.resolve_backend`, one copy).

Multi-chip serving shards along **KV heads** over the fleet mesh's
``model`` axis, exactly like ``sharded_paged_attention``: query heads
stay with their GQA group's KV head, metadata replicates, no collective
in the launch (:func:`sharded_ragged_attention`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.pallas import _common as _gate
from ..ops.pallas.ragged_attention import (
    ragged_paged_attention as _pallas_ragged,
    ragged_paged_attention_reference as _xla_ragged,
)

__all__ = ["ragged_paged_attention", "sharded_ragged_attention",
           "ab_compare_ragged", "pad_total_tokens"]

# smallest padded launch: decode-only rounds of small engines all share
# one program instead of one per active-row count
PAD_FLOOR = 8


def pad_total_tokens(n, floor=PAD_FLOOR):
    """The power-of-two token-pad schedule: the ONLY shape axis the
    ragged program specializes on. Distinct programs over a serving
    lifetime are bounded by ``log2(max_round_tokens / floor) + 1`` — the
    bucket grids' ``O(|batch| x |seq|)`` product is gone."""
    n = max(int(n), int(floor))
    p = 1
    while p < n:
        p *= 2
    return p


def ragged_paged_attention(q, k_pool, v_pool, row_starts, row_lens,
                           kv_lens, block_tables, backend="xla",
                           scale=None):
    """One ragged launch: ``q`` [T, H, Dh] flat tokens; pools
    [P, page, KVH, Dh]; per-row metadata as in the ops module. Returns
    [T, H, Dh]; pad tokens (past each row's ``row_lens``) come back
    zeroed and the caller discards them."""
    if backend == "pallas":
        return _pallas_ragged(q, k_pool, v_pool, row_starts, row_lens,
                              kv_lens, block_tables, scale=scale)
    return _xla_ragged(q, k_pool, v_pool, row_starts, row_lens, kv_lens,
                       block_tables, scale=scale)


def sharded_ragged_attention(mesh, axis_name="model", backend="xla",
                             scale=None):
    """Ragged attention sharded along KV heads over ``mesh[axis_name]``
    (the ``sharded_paged_attention`` partitioning on the flat-token
    layout): each shard attends its query-head groups against its head
    slice of every page; row metadata and block tables replicate — no
    collective in the launch, the out_spec stitches heads back. Falls
    back to the unsharded fn when the axis degree is 1."""
    degree = int(mesh.shape.get(axis_name, 1))

    def _impl(q, kp, vp, rs, rl, kl, bt):
        return ragged_paged_attention(q, kp, vp, rs, rl, kl, bt,
                                      backend=backend, scale=scale)

    if degree <= 1:
        return _impl
    in_specs = (
        P(None, axis_name, None),         # q [T, H, Dh]
        P(None, None, axis_name, None),   # k_pool [P, page, KVH, Dh]
        P(None, None, axis_name, None),   # v_pool
        P(),                              # row_starts (replicated)
        P(),                              # row_lens
        P(),                              # kv_lens
        P(),                              # block_tables
    )
    out_specs = P(None, axis_name, None)
    # tpu-lint: ok[RC001] built once per engine at a fixed shape and invoked inside the engine's jitted round (nested jit inlines) — the round program is counted at its _note_program install site
    return jax.jit(jax.shard_map(_impl, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))


def ab_compare_ragged(q, k_pool, v_pool, row_starts, row_lens, kv_lens,
                      block_tables, scale=None, repeats=20):
    """Time the jitted XLA reference vs the Pallas ragged kernel at this
    exact launch shape through the generalized demotion gate — verdict
    recorded under ``ragged_paged_attention`` keyed by the leading-
    operand (q) sig, so bench rows and the engine share one cache.
    Off-TPU the Pallas leg is skipped (interpret mode measures the
    emulator, not the chip) and XLA wins by default.
    -> ``{"backend", "xla_ms", "pallas_ms", "reason"}``."""
    args = (q, k_pool, v_pool,
            jnp.asarray(row_starts, jnp.int32),
            jnp.asarray(row_lens, jnp.int32),
            jnp.asarray(kv_lens, jnp.int32),
            jnp.asarray(block_tables, jnp.int32))
    return _gate.ab_gate(
        "ragged_paged_attention",
        lambda *a: _xla_ragged(*a, scale=scale),
        lambda *a: _pallas_ragged(*a, scale=scale),
        args, repeats=repeats, sig=_gate.shape_sig(q))
