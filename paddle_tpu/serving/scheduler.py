"""Continuous-batching scheduler — admit/evict/finish between decode steps.

Reference capability: the iteration-level scheduling of Orca/vLLM mapped
onto the fixed-slot TPU decode batch: the compiled decode step always runs
the full ``[max_slots]`` batch (one XLA program, one shape), and the
scheduler re-points slots at requests between steps:

* **admit** — waiting requests take a free slot when the page pool can
  hold their prompt; admission happens every step, so a request arriving
  mid-stream joins the NEXT decode step without stalling in-flight rows.
* **evict** — when an in-flight request needs its next page and the pool
  is dry, the most-recently-admitted active request is preempted: its
  pages are freed and it returns to the FRONT of the queue with
  ``prompt + generated-so-far`` as its new prompt (recompute-on-readmit;
  greedy decode makes the continuation token-identical).
* **finish** — eos / token budget frees pages + slot immediately, so the
  page becomes admissible capacity for the same step's admission pass.

Backpressure: the waiting queue is bounded; ``submit`` blocks (or raises
:class:`QueueFull`) when producers outrun the engine.

Host-side and model-agnostic — it never touches device arrays; the engine
owns prefill/decode and calls :meth:`schedule` / :meth:`complete_step`.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque

from ..observability import tracing as _trc
from .kv_cache import OutOfPages, pages_for

__all__ = ["GenerationRequest", "ContinuousBatchingScheduler",
           "QueueFull", "EngineClosed", "OutOfSlots"]


class QueueFull(RuntimeError):
    """Admission queue at capacity (open-loop producer outran the engine)."""


class OutOfSlots(RuntimeError):
    """No free decode slot for a direct admission (fleet page migration
    adopting a request bypasses the queue; the caller falls back to
    recompute-on-readmit)."""


class EngineClosed(RuntimeError):
    """Submitted to / waited on an engine that has been closed."""


class EngineShuttingDown(EngineClosed):
    """The engine began a graceful shutdown (SIGTERM drain): admission is
    closed and queued requests are failed with THIS status — a named,
    retryable verdict the caller can route to another replica — while
    in-flight decodes drain up to the deadline. Distinct from the bare
    :class:`EngineClosed` a hard ``close()`` hands out."""


_rid = itertools.count()
# Fallback request-id namespace: in a fleet, two engine PROCESSES each
# minting rids from a bare per-process counter would alias (same rid on
# two engines corrupts merged traces, metrics labels and ledger keys).
# The pid-derived high component keeps the fallback an int — rng() folds
# request_id into its seed arithmetic — while making cross-process
# collision impossible for live pids (mod the 2^20 namespace).
_RID_NS = (os.getpid() & 0xFFFFF) << 20


class GenerationRequest:
    """One streaming generation request.

    ``on_token(req, token, finished)`` fires from the engine thread for
    every generated token (callback errors are swallowed — a slow/broken
    consumer must not stall the decode loop). ``result()`` blocks for the
    full generated-token list.
    """

    def __init__(self, prompt_ids, max_new_tokens=16, eos_token_id=None,
                 temperature=0.0, top_k=None, seed=0, on_token=None,
                 request_id=None, on_done=None, trace=None):
        self.request_id = request_id if request_id is not None \
            else (_RID_NS + next(_rid))
        # distributed trace context ({"tid", "ps"} dict, or None): minted
        # at the front door / scheduler submit, propagated over the fleet
        # wire. None whenever tracing is off — every hot-path hook gates
        # on this one attribute, which is what keeps tracing-off
        # structurally free (no allocation, no call).
        self.trace = trace
        self.prompt_ids = [int(t) for t in prompt_ids]
        if not self.prompt_ids:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.top_k = top_k
        self.seed = int(seed)
        self.on_token = on_token
        # fires once, at the terminal state (fleet router: re-dispatch a
        # retryable failure to another engine without polling result())
        self.on_done = on_done
        # fleet migration hook: set by the router on prefill-designated
        # engines — called from _finish_prompt when the prompt completes
        # but the token budget has more to go (see disagg.migrate_request)
        self.migrate_hook = None
        self.generated: list[int] = []
        self.state = "waiting"   # waiting|prefilling|active|finished|failed
        self.error = None
        self.slot = None
        self.pages: list[int] = []
        self.num_cached = 0          # tokens currently in the KV pool
        self.prefix_hit_tokens = 0   # prompt head served from the cache
        self.evictions = 0
        self.t_submit = time.perf_counter()
        self.t_enqueue = self.t_submit   # reset on eviction; the total
        # across re-admissions accumulates in queue_wait_s (an evicted
        # request's pre-eviction queue time must not vanish from the tail)
        self.queue_wait_s = 0.0
        self.t_admit = None
        self.t_first_token = None
        self.t_done = None
        self.token_times: list[float] = []
        self._done = threading.Event()
        self._rng = None

    # ---- engine-side helpers -------------------------------------------
    def effective_prompt(self):
        """Prompt for (re-)prefill: original prompt plus everything already
        generated (an evicted request recomputes its own context)."""
        return self.prompt_ids + self.generated

    def rng(self):
        if self._rng is None:
            import numpy as np
            self._rng = np.random.RandomState(
                (self.seed + self.request_id) % (2 ** 31))
        return self._rng

    def emit(self, token):
        now = time.perf_counter()
        if self.t_first_token is None:
            self.t_first_token = now
        self.token_times.append(now)
        self.generated.append(int(token))
        cb = self.on_token
        if cb is not None:
            try:
                cb(self, int(token), self.hit_stop())
            except Exception:
                pass

    def finish(self, error=None):
        self.state = "failed" if error is not None else "finished"
        self.error = error
        self.t_done = time.perf_counter()
        if self.trace is not None:
            self._trace_terminal(error)
        self._done.set()
        cb = self.on_done
        if cb is not None:
            try:
                cb(self)
            except Exception:
                pass  # a broken observer must not stall the engine

    def _trace_terminal(self, error):
        """Lifecycle spans at the terminal state plus (for a request this
        process owns outright) the tail-sampling verdict. Fleet legs
        carry ``_fleet`` and leave the verdict to the router, which alone
        knows about hedging and the end-to-end latency. Durations are
        perf_counter deltas anchored backward from the wall clock the
        trace buffer stamps."""
        ctx, now = self.trace, time.time()
        if self.t_admit is not None and self.t_first_token is not None:
            back = self.t_done - self.t_admit
            _trc.req_event(ctx, "prefill", now - back,
                           self.t_first_token - self.t_admit,
                           args={"prompt": len(self.prompt_ids),
                                 "prefix_hit": self.prefix_hit_tokens})
        if self.t_first_token is not None:
            dur = self.t_done - self.t_first_token
            _trc.req_event(ctx, "decode", now - dur, dur,
                           args={"tokens": len(self.generated)})
        _trc.req_event(ctx, "request_done", now, 0.0,
                       args={"rid": str(self.request_id),
                             "state": self.state,
                             "evictions": self.evictions})
        if getattr(self, "_fleet", None) is None:
            _trc.finish_request(ctx, dur_s=self.t_done - self.t_submit,
                                error=error is not None,
                                evicted=self.evictions > 0)

    def hit_stop(self):
        """Generation-complete test: token budget or eos."""
        if len(self.generated) >= self.max_new_tokens:
            return True
        return (self.eos_token_id is not None and self.generated
                and self.generated[-1] == int(self.eos_token_id))

    # ---- caller-side surface -------------------------------------------
    def done(self):
        return self._done.is_set()

    def result(self, timeout=60.0):
        """-> the generated token list (prompt excluded); raises on
        failure/timeout."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not done in {timeout}s "
                f"(state={self.state})")
        if self.error is not None:
            raise self.error
        return list(self.generated)

    def ttft_s(self):
        return (self.t_first_token - self.t_submit) \
            if self.t_first_token else None

    def inter_token_s(self):
        return [b - a for a, b in zip(self.token_times,
                                      self.token_times[1:])]


class ContinuousBatchingScheduler:
    """Owns the waiting queue, the slot map, and page accounting."""

    def __init__(self, allocator, max_slots, page_size, max_seq_len,
                 max_queue=256, prefix_cache=None):
        self.allocator = allocator
        self.prefix_cache = prefix_cache
        self.max_slots = int(max_slots)
        self.page_size = int(page_size)
        self.max_seq_len = int(max_seq_len)
        self.max_queue = int(max_queue)
        self.waiting: deque = deque()
        self.active: dict[int, GenerationRequest] = {}
        self._free_slots = list(range(self.max_slots - 1, -1, -1))
        self._lock = threading.Lock()
        self._space = threading.Condition(self._lock)
        self._closed = False
        self._shutting_down = False
        self.total_evictions = 0

    # ---- producer side --------------------------------------------------
    def submit(self, req, block=True, timeout=10.0):
        total = len(req.prompt_ids) + req.max_new_tokens
        if total > self.max_seq_len:
            raise ValueError(
                f"prompt ({len(req.prompt_ids)}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds max_seq_len "
                f"({self.max_seq_len})")
        if pages_for(total, self.page_size) > self.allocator.capacity:
            raise ValueError(
                f"request needs {pages_for(total, self.page_size)} pages; "
                f"pool has {self.allocator.capacity} — it could never run")
        with self._space:
            if self._closed:
                raise self._closed_error()
            if len(self.waiting) >= self.max_queue and block:
                deadline = time.perf_counter() + timeout
                while len(self.waiting) >= self.max_queue \
                        and not self._closed:
                    left = deadline - time.perf_counter()
                    if left <= 0:
                        break
                    self._space.wait(left)
                if self._closed:
                    raise self._closed_error()
            if len(self.waiting) >= self.max_queue:
                raise QueueFull(
                    f"waiting queue at capacity ({self.max_queue})")
            self.waiting.append(req)
        if req.trace is None:
            # single funnel for engine-local traces: a request arriving
            # without a fleet-minted context gets its own (None when
            # tracing is off — one call, no allocation)
            req.trace = _trc.mint_context()
        if req.trace is not None:
            _trc.req_event(req.trace, "enqueue", time.time(), 0.0,
                           args={"rid": str(req.request_id),
                                 "depth": len(self.waiting)})
        return req

    def queue_depth(self):
        with self._lock:
            return len(self.waiting)

    # ---- engine side (single engine thread) -----------------------------
    def schedule(self):
        """Admission pass: -> requests newly admitted this step (pages +
        slot assigned; the engine prefills them). Never evicts on behalf
        of a waiting request — in-flight work has priority."""
        admitted = []
        while self._free_slots:
            with self._lock:
                if not self.waiting:
                    break
                req = self.waiting[0]
            # prefix lookup + page accounting OUTSIDE the lock: a fleet
            # SharedPrefixCache lookup is a store round-trip (up to its
            # fetch timeout), and producers block on this very lock in
            # submit() — holding it here would stall every caller for
            # the duration (tpu-lint LK002). Pages/slots are engine-
            # thread-owned, so only the deque needs the lock.
            prompt = req.effective_prompt()
            shared, n_shared = [], 0
            if self.prefix_cache is not None:
                # prefix-cache hit: the shared head's pages are taken
                # by reference (no prefill compute, no page writes) —
                # only the tail needs private pages
                shared, n_shared = self.prefix_cache.lookup(prompt)
            need = pages_for(len(prompt) + 1, self.page_size) \
                - len(shared)
            if not self.allocator.can_alloc(need):
                if shared:    # un-ref the speculative hit
                    self.allocator.free(shared)
                break
            with self._lock:
                if not self.waiting or self.waiting[0] is not req:
                    # a readmission (eviction / migration fallback, maybe
                    # from another engine's thread) jumped the queue head
                    # while the lock was dropped: un-ref and re-examine
                    if shared:
                        self.allocator.free(shared)
                    continue
                self.waiting.popleft()
                self._space.notify_all()
            req.pages = shared + self.allocator.alloc(need)
            req.num_cached = n_shared
            req.prefix_hit_tokens = n_shared
            if self.prefix_cache is not None and req.evictions == 0:
                # request-level hit/miss: first admission only — a
                # readmission re-hitting its own cached head would
                # double-count the request in the hit rate
                self.prefix_cache.record(n_shared)
            req.slot = self._free_slots.pop()
            req.state = "active"
            req.t_admit = time.perf_counter()
            req.queue_wait_s += req.t_admit - req.t_enqueue
            self.active[req.slot] = req
            admitted.append(req)
            if req.trace is not None:
                self._trace_admit(req)
        return admitted

    def _trace_admit(self, req):
        """queue_wait span (anchored backward from now) + prefix-hit
        marker for one just-admitted request."""
        now = time.time()
        wait = req.t_admit - req.t_enqueue
        _trc.req_event(req.trace, "queue_wait", now - wait, wait,
                       args={"slot": req.slot,
                             "evictions": req.evictions})
        if req.prefix_hit_tokens:
            _trc.req_event(req.trace, "prefix_hit", now, 0.0,
                           args={"tokens": req.prefix_hit_tokens})

    def ensure_decode_capacity(self):
        """Before a decode step: every active request writing token
        ``num_cached`` needs page ``num_cached // page_size``. Grow block
        tables, evicting the most-recently-admitted active request when
        the pool is dry. -> (grown, evicted) request lists."""
        grown, evicted = [], []
        # oldest first: under pressure the senior requests grab pages
        # before the juniors (who are also the eviction victims)
        for req in sorted(self.active.values(),
                          key=lambda r: r.t_admit or 0.0):
            if req.state != "active":
                continue
            while req.num_cached // self.page_size >= len(req.pages):
                try:
                    req.pages += self.allocator.alloc(1)
                    grown.append(req)
                except OutOfPages:
                    victim = self._pick_victim(exclude=req)
                    if victim is None:
                        # only this request is left: nothing to reclaim —
                        # evict IT (it re-prefills once pages free up)
                        self._evict(req)
                        evicted.append(req)
                        break
                    self._evict(victim)
                    evicted.append(victim)
        return grown, evicted

    def _pick_victim(self, exclude=None):
        cands = [r for r in self.active.values()
                 if r is not exclude and r.state == "active"]
        if not cands:
            return None
        return max(cands, key=lambda r: r.t_admit or 0.0)

    def _evict(self, req):
        self._release(req)
        req.evictions += 1
        self.total_evictions += 1
        if req.trace is not None:
            _trc.req_event(req.trace, "evicted", time.time(), 0.0,
                           args={"evictions": req.evictions,
                                 "generated": len(req.generated)})
        self.readmit(req)

    def readmit(self, req):
        """Re-queue an already-released request at the FRONT of the
        waiting queue with its context reset — it re-prefills its
        ``effective_prompt()`` on admission (greedy continuation is
        token-identical). The eviction path and the fleet's
        recompute-on-migrate fallback share this one copy."""
        req.state = "waiting"
        req.num_cached = 0
        req.t_enqueue = time.perf_counter()
        if req.trace is not None:
            _trc.req_event(req.trace, "readmit", time.time(), 0.0,
                           args={"generated": len(req.generated)})
        with self._lock:
            self.waiting.appendleft(req)

    def admit_prepared(self, req):
        """Adopt a request whose pages are ALREADY allocated and whose KV
        is already written into this engine's pools (fleet page
        migration): take a free slot and join the decode batch directly —
        no queue, no prefill. Raises :class:`OutOfSlots` when every slot
        is taken (the caller falls back to :meth:`readmit`)."""
        with self._lock:
            if self._closed:
                raise self._closed_error()
            if not self._free_slots:
                raise OutOfSlots(
                    f"all {self.max_slots} slots busy — migrated request "
                    "must recompute from the queue instead")
            req.slot = self._free_slots.pop()
        req.state = "active"
        req.t_admit = time.perf_counter()
        self.active[req.slot] = req

    def release_for_migration(self, req):
        """Free a migrating request's slot + pages WITHOUT finishing it:
        the request object itself moves to another engine, and its
        waiters keep waiting on the same done event."""
        self._release(req)
        req.state = "migrating"

    def abort_request(self, req):
        """Cancel one leg SILENTLY: free its slot + pages (wherever it
        is — queued, prefilling or active) without firing its waiters or
        ``on_done``. The hedged-straggler loser of ISSUE 16: the caller
        (router) owns the request's done event through a different
        winning leg, so the loser must simply vanish from this engine.
        Returns False when the request already reached a terminal state
        (its ``on_done`` fired / will fire normally)."""
        if req.state in ("finished", "failed", "migrating", "aborted"):
            return False
        with self._lock:
            try:
                self.waiting.remove(req)
                self._space.notify_all()
            except ValueError:
                pass
        self._release(req)
        req.state = "aborted"
        ctx = req.trace
        if ctx is not None:
            _trc.req_event(ctx, "aborted", time.time(), 0.0,
                           args={"generated": len(req.generated)})
            if getattr(req, "_fleet", None) is None:
                # a locally-owned abort is its own terminal state; fleet
                # legs leave the verdict to the router's _finish_fr
                _trc.finish_request(ctx, aborted=True)
        return True

    def _release(self, req):
        if req.pages:
            self.allocator.free(req.pages)
            req.pages = []
        if req.slot is not None:
            del self.active[req.slot]
            self._free_slots.append(req.slot)
            req.slot = None

    def finish(self, req, error=None):
        self._release(req)
        req.finish(error)

    def complete_step(self, tokens_by_slot):
        """Account one decode step: ``{slot: token}`` for every slot that
        was active when the step launched. -> finished requests."""
        done = []
        for slot, token in tokens_by_slot.items():
            req = self.active.get(slot)
            if req is None or req.state != "active":
                continue
            req.num_cached += 1      # this step wrote the input token's KV
            req.emit(token)
            if req.hit_stop():
                self.finish(req)
                done.append(req)
        return done

    def has_work(self):
        with self._lock:
            return bool(self.waiting) or bool(self.active)

    def _closed_error(self):
        return EngineShuttingDown("engine is shutting down") \
            if self._shutting_down else EngineClosed("engine is closed")

    def begin_shutdown(self, error=None):
        """Graceful half of teardown: stop admitting (later submits raise
        :class:`EngineShuttingDown`), fail every QUEUED request with that
        named status, keep the in-flight ones — the engine drains them
        with further decode steps up to its deadline, then ``close()``\\ s
        whatever remains. Returns the failed queued requests (the caller
        records their terminal metrics — they must not vanish from the
        flushed counters)."""
        err = error or EngineShuttingDown(
            "engine is shutting down: request was queued, not started — "
            "safe to retry on another replica")
        with self._space:
            self._closed = True
            self._shutting_down = True
            waiting = list(self.waiting)
            self.waiting.clear()
            self._space.notify_all()
        now = time.perf_counter()
        for req in waiting:
            # a rejected-at-queue request's whole life was queue wait:
            # close out the pending segment so the cumulative-wait
            # histogram sample observed at its terminal state is honest
            req.queue_wait_s += now - req.t_enqueue
            req.finish(err)
        return waiting

    def close(self, error=None):
        """Fail everything still queued or in flight (engine teardown)."""
        err = error or self._closed_error()
        with self._space:
            self._closed = True
            waiting = list(self.waiting)
            self.waiting.clear()
            self._space.notify_all()
        for req in waiting:
            req.finish(err)
        for req in list(self.active.values()):
            self._release(req)
            req.finish(err)
