"""Cross-engine prefix-cache sharing — the fleet-wide page trie.

One engine's :class:`~..prefix_cache.PrefixCache` is per-process: every
replica prefills the same system prompt once. At fleet scale that is N
redundant prefills of the hottest tokens in the system. This module makes
the trie fleet-wide (ISSUE 14 tentpole (b); the Gemma-on-TPU serving
study, arxiv 2605.25645, names shared-prefix KV reuse as a first-order
serving lever):

* **Content-addressed chain keys** — page *i* of a prompt is published
  under ``h_i = H(h_{i-1}, page_tokens)``, the store-key mirror of the
  local trie's ``(parent_page, page_tokens)`` key: a hit on ``h_i``
  guarantees the whole preceding context matches, and the key is
  identical on every engine regardless of local page ids. Because the
  key *is* the content, a fetched payload can never be wrong for its key
  — the no-stale-resurrection property holds by construction, not by
  protocol.
* **Publish at insert** — when a prompt finishes prefilling, its first
  ``max_publish_pages`` full pages are pushed through the TCPStore
  (``pshare/<job>/pg/<h>`` payload + ``idx/<h>`` owner record), deduped
  by a check-first write (identical weights → identical KV, so a racing
  double-publish is harmless).
* **Import on local miss** — :meth:`SharedPrefixCache.lookup` walks the
  local trie first; where it runs out it continues the chain against the
  store: lease, fetch the payload (one host roundtrip), allocate a LOCAL
  page, write it into this engine's pools, and index it locally — from
  then on it is an ordinary refcounted/COW page (future local hits are
  free, reclamation parks/drops it like any other cached page).
* **Invalidation rides on_reclaim** — when the allocator repurposes a
  page this engine published, the index entry (and payload) is removed
  from the store; readers mid-fetch fall back to a miss.

The store is any TCPStore-shaped object (``set/get/check/add/
delete_key``) — a plain :class:`TCPStore`, a :class:`FailoverStore`, or
a test double.
"""
from __future__ import annotations

import hashlib
import json
import threading
import time

import numpy as np

from ..prefix_cache import PrefixCache, _ROOT
from ...distributed import keyspace

__all__ = ["PageShareClient", "SharedPrefixCache"]


def chain_hash(parent_hash, tokens):
    """Content-addressed chain key: the store-side mirror of the local
    trie's (parent page, page tokens) key."""
    h = hashlib.sha1()
    h.update(str(parent_hash).encode())
    h.update(b"|")
    h.update(",".join(str(int(t)) for t in tokens).encode())
    return h.hexdigest()


class PageShareClient:
    """Store frontend for one engine's published/imported pages."""

    def __init__(self, store, engine_id, job="fleet",
                 max_publish_pages=8, fetch_timeout=3.0):
        if engine_id is None:
            raise ValueError("page sharing needs an engine_id — the "
                             "index records which engine owns each page")
        self.store = store
        self.engine_id = str(engine_id)
        self.prefix = keyspace.page_share(job)
        self.max_publish_pages = int(max_publish_pages)
        self.fetch_timeout = float(fetch_timeout)
        # counters (engine.stats() + the fleet bench read these)
        self.published = 0
        self.unpublished = 0
        self.remote_hits = 0          # requests that imported >= 1 page
        self.remote_hit_tokens = 0
        self.stale_misses = 0
        # deferred invalidation: reclaim runs INSIDE the engine's
        # admission/decode step, and unpublish costs store roundtrips
        # (plus the lease grace) — the drop enqueues here and a daemon
        # drains it off the hot path. Content-addressed keys keep a
        # not-yet-unpublished entry harmless (its payload is still
        # correct for its key); the queue only bounds store growth.
        self._unpub_queue: list = []
        self._unpub_lock = threading.Lock()
        self._unpub_thread = None
        # the one store client is shared between the engine thread
        # (publish/fetch at admission/insert) and the unpublish daemon:
        # the native client is not thread-safe, so ops serialize here
        self._store_lock = threading.Lock()

    def _k(self, kind, h):
        return f"{self.prefix}/{kind}/{h}"

    def unpublish_async(self, h):
        """Queue an invalidation; a lazy daemon drains it off the
        caller's (hot) path."""
        with self._unpub_lock:
            self._unpub_queue.append(h)
            if self._unpub_thread is None or \
                    not self._unpub_thread.is_alive():
                self._unpub_thread = threading.Thread(
                    target=self._drain_unpublish, daemon=True,
                    name=f"pshare-unpub-{self.engine_id}")
                self._unpub_thread.start()

    def _drain_unpublish(self):
        while True:
            with self._unpub_lock:
                if not self._unpub_queue:
                    return
                h = self._unpub_queue.pop(0)
            self.unpublish(h)

    def drain_unpublish(self, timeout=5.0):
        """Block until the deferred invalidations have landed (tests /
        bench isolation)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._unpub_lock:
                t = self._unpub_thread
                if not self._unpub_queue and (t is None
                                              or not t.is_alive()):
                    return True
            time.sleep(0.01)
        return False

    def publish(self, h, payload: bytes) -> bool:
        """First-writer-wins publication of one page's KV content.
        Payload lands BEFORE the index entry so a reader that sees the
        index never races a missing payload."""
        try:
            with self._store_lock:
                if self.store.check(self._k("idx", h)):
                    return False
                self.store.set(self._k("pg", h), payload)
                self.store.set(self._k("idx", h),
                               json.dumps({"engine": self.engine_id}))
        except Exception:
            return False  # publication is best-effort: serving goes on
        self.published += 1
        return True

    def fetch(self, h):
        """Payload bytes for chain key ``h`` published by ANOTHER engine,
        or None (unpublished / our own entry / invalidated mid-flight).
        The lease counter brackets the read so an owner invalidating can
        see in-flight readers."""
        try:
            with self._store_lock:
                if not self.store.check(self._k("idx", h)):
                    return None
                owner = json.loads(self.store.get(
                    self._k("idx", h), timeout=self.fetch_timeout))
                if owner.get("engine") == self.engine_id:
                    return None  # our own entry: local trie covers it
                self.store.add(self._k("lease", h), 1)
                try:
                    if not self.store.check(self._k("pg", h)):
                        self.stale_misses += 1
                        return None
                    return self.store.get(self._k("pg", h),
                                          timeout=self.fetch_timeout)
                finally:
                    self.store.add(self._k("lease", h), -1)
        except Exception:
            self.stale_misses += 1
            return None

    def unpublish(self, h, lease_grace=0.5):
        """Invalidate one published entry (the owner's page was
        reclaimed): index first — no NEW reader can start — then wait
        (bounded) for in-flight leases to drain before the payload goes,
        so a reader mid-transfer finishes its (still content-correct)
        read; stragglers past the grace see the payload gone and miss.
        The lease key itself is GC'd with the entry."""
        try:
            with self._store_lock:
                owner = None
                if self.store.check(self._k("idx", h)):
                    owner = json.loads(self.store.get(
                        self._k("idx", h), timeout=self.fetch_timeout))
                if owner is None \
                        or owner.get("engine") != self.engine_id:
                    return False
                self.store.delete_key(self._k("idx", h))
            deadline = time.monotonic() + float(lease_grace)
            while True:
                with self._store_lock:
                    n = int(self.store.add(self._k("lease", h), 0))
                if n <= 0 or time.monotonic() >= deadline:
                    break
                time.sleep(0.02)
            with self._store_lock:
                self.store.delete_key(self._k("pg", h))
                self.store.delete_key(self._k("lease", h))
        except Exception:
            return False
        self.unpublished += 1
        return True


class SharedPrefixCache(PrefixCache):
    """A :class:`PrefixCache` whose trie extends across the fleet.

    Locally identical to the base cache (same refcount/COW/reclaim
    machinery — the engine, scheduler and allocator cannot tell the
    difference); the delta is at the edges:

    * :meth:`insert` additionally publishes the chain's first
      ``max_publish_pages`` pages through the share client;
    * :meth:`lookup` continues a broken local walk against the published
      index, importing remote pages into the local pool;
    * a reclaimed local page that this engine published is unpublished
      through the same ``_drop_entry`` funnel the base cache uses.
    """

    def __init__(self, kv, page_size, share: PageShareClient):
        super().__init__(kv.allocator, page_size)
        self.kv = kv
        self.share = share
        self._published: dict[int, str] = {}   # local page -> chain hash

    # ---------------------------------------------------------- payloads
    def _page_payload(self, page) -> bytes:
        """One page's KV across all layers as bytes:
        ``[2, L, page_size, KVH, Dh]`` in the pool dtype (identical
        config fleet-wide, so shape/dtype ride the engine, not the
        wire)."""
        kv = self.kv
        arr = np.stack([
            np.stack([np.asarray(kv.k[l][page])
                      for l in range(kv.num_layers)]),
            np.stack([np.asarray(kv.v[l][page])
                      for l in range(kv.num_layers)]),
        ])
        return arr.tobytes()

    def _write_page(self, page, payload: bytes) -> bool:
        kv = self.kv
        shape = (2, kv.num_layers, kv.page_size, kv.num_heads,
                 kv.head_dim)
        arr = np.frombuffer(payload, dtype=np.dtype(kv.k[0].dtype))
        if arr.size != int(np.prod(shape)):
            return False  # foreign/corrupt payload: treat as a miss
        arr = arr.reshape(shape)
        for l in range(kv.num_layers):
            kv.k[l] = kv.k[l].at[page].set(arr[0, l])
            kv.v[l] = kv.v[l].at[page].set(arr[1, l])
        return True

    # ------------------------------------------------------------ insert
    def insert(self, tokens, pages):
        super().insert(tokens, pages)
        ps = self.page_size
        node, h = _ROOT, "root"
        for i in range(min(len(tokens) // ps,
                           self.share.max_publish_pages)):
            seg = tuple(tokens[i * ps:(i + 1) * ps])
            h = chain_hash(h, seg)
            page = self._index.get((node, seg))
            if page is None:
                break
            if page not in self._published:
                if self.share.publish(h, self._page_payload(page)):
                    self._published[page] = h
            node = page

    # ------------------------------------------------------------ lookup
    def lookup(self, tokens):
        pages, n = super().lookup(tokens)
        ps = self.page_size
        max_hit_pages = (len(tokens) - 1) // ps
        if len(pages) >= max_hit_pages:
            return pages, n
        # continue the chain remotely: recompute the hashes over the
        # locally-covered head, then import page by page until the
        # published chain (or this pool's capacity) runs out
        h = "root"
        imported = 0
        for i in range(max_hit_pages):
            seg = tuple(tokens[i * ps:(i + 1) * ps])
            h = chain_hash(h, seg)
            if i < len(pages):
                continue
            payload = self.share.fetch(h)
            if payload is None:
                break
            try:
                page = self.allocator.alloc(1)[0]
            except Exception:
                break  # pool full: serve what we have
            if not self._write_page(page, payload):
                self.allocator.free([page])
                break
            parent = pages[i - 1] if i > 0 else _ROOT
            key = (parent, seg)
            self._index[key] = page
            self._entry[page] = key
            self._children.setdefault(parent, set()).add(key)
            pages.append(page)
            imported += 1
        if imported:
            self.share.remote_hits += 1
            self.share.remote_hit_tokens += imported * ps
        return pages, len(pages) * ps

    # ------------------------------------------------------ invalidation
    def _drop_entry(self, key, page):
        super()._drop_entry(key, page)
        h = self._published.pop(int(page), None)
        if h is not None:
            # reclaim runs inside the engine step: defer the store
            # roundtrips (correctness doesn't need them synchronous —
            # the keys are content-addressed)
            self.share.unpublish_async(h)

    def clear(self):
        for h in list(self._published.values()):
            self.share.unpublish(h)
        self._published.clear()
        self.share.drain_unpublish()
        super().clear()
