# tpu-lint: hot-path
"""Fleet router — one serving front-end over N ``ServingEngine`` replicas.

The dispatch tier of ISSUE 14: callers talk to ONE :class:`FleetRouter`;
behind it N engines (in-process replicas, or store-RPC remotes via
:mod:`.remote`) share the load:

* **session affinity** — requests whose prompt opens with the same full
  first page (or an explicit ``session=`` key) stick to the engine that
  already holds those pages, so the prefix cache hits locally instead of
  paying a cross-engine import per request;
* **least-loaded balancing** — candidates are ordered by queue depth +
  active slots (the same numbers the engines' ``active_slots``/
  ``kv_occupancy`` gauges export), KV occupancy breaking ties;
* **backpressure propagation** — an engine's ``QueueFull`` rotates to
  the next candidate; when EVERY engine is saturated the caller gets
  :class:`FleetSaturated` (a ``QueueFull``) after the submit timeout —
  open-loop producers see honest fleet-wide pressure, never a silent
  drop;
* **health + re-dispatch** — an engine that crashes (serve-loop error),
  closes, or begins a graceful shutdown is drained from rotation; its
  failed legs re-dispatch to healthy engines carrying the tokens already
  emitted (the continuation re-prefills ``prompt + generated`` — greedy
  decode is token-identical), so a retryable ``EngineShuttingDown``
  surfaces to the *fleet*, not to the user;
* **hedged stragglers** — with ``hedge_after_s`` set, ``hedge_sweep()``
  duplicates a quiet request's leg on a second engine (the duplicate
  re-prefills ``prompt + generated``, so greedy decode keeps it
  token-identical); the first finisher wins, the loser is ABORTED —
  slot + pages freed silently, its waiters never fired — and the
  duplicate's tokens only surface on promotion, never interleaved;
* **prefetch on affinity spill** — when a sticky session lands on a
  NEW engine (its affine replica was too deep), the router pushes the
  prompt's shared prefix pages there ahead of the prefill via the
  cross-engine page-share transport, converting the spill's cold miss
  into a remote hit;
* **prefill/decode disaggregation** — engines registered with
  ``role="prefill"`` hand completed prefills to ``role="decode"``
  engines via :func:`.disagg.migrate_request` (KV page migration; the
  same machinery ``remove_engine(migrate=True)`` uses for planned
  engine loss).

Liveness can additionally ride the TCPStore registry
(:class:`.registry.EngineRegistry`): handles constructed from registry
records (remote engines) report health from heartbeats instead of
in-process state.
"""
from __future__ import annotations

import itertools
import threading
import time

from ...observability import tracing as _trc
from ..metrics import ServingMetrics
from ..scheduler import (EngineClosed, EngineShuttingDown,
                         GenerationRequest, QueueFull)
from . import disagg as _disagg
from .ledger import TERMINAL_STATES, RouterDeposedError, rebuild_error

__all__ = ["FleetRouter", "FleetRequest", "FleetSaturated",
           "LocalEngineHandle"]


class FleetSaturated(QueueFull):
    """Every healthy engine's admission queue is full — fleet-wide
    backpressure. Retryable by the caller (it is a ``QueueFull``)."""


_fid = itertools.count()


class FleetRequest:
    """The caller's handle to one fleet-routed generation.

    Mirrors :class:`~..scheduler.GenerationRequest`'s caller surface
    (``result``/``done``/``ttft_s``/``inter_token_s`` plus the fields
    ``load.summarize_requests`` reads), while the engine-side legs behind
    it may be re-dispatched across engines or migrated between them —
    ``engine_ids`` records the itinerary.
    """

    def __init__(self, prompt_ids, max_new_tokens=16, eos_token_id=None,
                 temperature=0.0, top_k=None, on_token=None,
                 request_id=None, trace=None):
        # client-supplied ids are the exactly-once idempotency key
        # (ISSUE 17): the same id resubmitted reaches the same request
        # through the ledger, never a second generation
        self.request_id = str(request_id) if request_id is not None \
            else f"fleet-{next(_fid)}"
        # distributed trace context (ISSUE 20): minted at the front door
        # (or by the router when it IS the front door), journaled with
        # the ledger record, copied onto every engine leg. None when
        # tracing is off — the hot-path hooks gate on this attribute.
        self.trace = trace
        self._hedged = False       # ever hedged (tail-sampling verdict)
        self.prompt_ids = [int(t) for t in prompt_ids]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.top_k = top_k
        self.on_token = on_token
        self.generated: list = []
        self.token_times: list = []
        self.state = "waiting"
        self.error = None
        self.engine_id = None
        self.engine_ids: list = []       # every engine this request rode
        self.redispatches = 0
        self.migrations = 0
        self.queue_wait_s = 0.0
        self.evictions = 0
        self.t_submit = time.perf_counter()
        self.t_first_token = None
        self.t_done = None
        self._done = threading.Event()
        self._leg = None
        self._hedge = None         # duplicate leg racing a straggler
        # serializes token surfacing against hedge promotion: the splice
        # in _promote_hedge must not interleave with a primary leg's
        # concurrent _leg_token append. ISSUE 17 also claims the
        # in-flight migration target and the ledger cursor under it.
        self._tok_lock = threading.Lock()
        self._migrating_to = None  # dst engine of an in-flight migration
        self._ledger_cursor = 0    # tokens already journaled
        self._ledger_done = False  # terminal record written

    # ---- engine-leg plumbing (router-internal) -------------------------
    def _attach(self, leg, engine_id):
        self._leg = leg
        self.engine_id = engine_id
        if not self.engine_ids or self.engine_ids[-1] != engine_id:
            self.engine_ids.append(engine_id)
        self.state = "active"

    def _leg_token(self, leg, token, fin):
        with self._tok_lock:
            # only the PRIMARY leg surfaces tokens live — a hedge
            # duplicate's tokens accumulate engine-side and surface in
            # one splice if it wins (surfacing both would interleave two
            # token streams into one callback sequence)
            if leg is not self._leg:
                return
            now = time.perf_counter()
            if self.t_first_token is None:
                self.t_first_token = now
            self.token_times.append(now)
            self.generated.append(int(token))
            n = len(self.generated)
        if self.trace is not None:
            # per-token stream delivery: the instant this token surfaced
            # to the caller's stream in the router process
            _trc.req_event(self.trace, "stream_token", time.time(), 0.0,
                           args={"i": n, "fin": bool(fin)})
        cb = self.on_token
        if cb is not None:
            try:
                cb(self, int(token), bool(fin))
            except Exception:
                pass

    def _absorb(self, leg):
        """Fold a finished/abandoned leg's accounting into the fleet
        totals (tokens already arrived through ``_leg_token``)."""
        self.queue_wait_s += leg.queue_wait_s
        self.evictions += leg.evictions

    def _finish(self, error=None):
        if self._done.is_set():
            return
        self.state = "failed" if error is not None else "finished"
        self.error = error
        self.t_done = time.perf_counter()
        self._done.set()

    # ---- caller surface -------------------------------------------------
    def done(self):
        return self._done.is_set()

    def result(self, timeout=60.0):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not done in {timeout}s "
                f"(state={self.state}, engine={self.engine_id})")
        if self.error is not None:
            raise self.error
        return list(self.generated)

    def ttft_s(self):
        return (self.t_first_token - self.t_submit) \
            if self.t_first_token else None

    def inter_token_s(self):
        return [b - a for a, b in zip(self.token_times,
                                      self.token_times[1:])]


class LocalEngineHandle:
    """Router-side view of one in-process :class:`ServingEngine`."""

    remote = False

    def find_leg(self, rid):
        """Locate a live engine-side request by its id (router shadow
        takeover re-attach; local legs carry int GenerationRequest
        ids). None when the leg already finished or never arrived."""
        s = self.engine.scheduler
        for req in list(s.active.values()) + list(s.waiting):
            if str(req.request_id) == str(rid):
                return req
        return None

    def __init__(self, engine, engine_id, role="any"):
        self.engine = engine
        self.engine_id = str(engine_id)
        self.role = role
        self.forced_down = False
        # router-side in-flight count: incremented at dispatch,
        # decremented at leg completion/migration. Engine-reported
        # loads lag (remote heartbeats especially) — during a burst the
        # router's own unacknowledged traffic is the freshest signal.
        self.pending = 0

    def healthy(self):
        e = self.engine
        return not (self.forced_down or e._closed or e._draining
                    or e._loop_error is not None)

    def load(self):
        s = self.engine.scheduler
        return s.queue_depth() + len(s.active)

    def occupancy(self):
        return self.engine.kv.occupancy_pct()

    def submit(self, leg):
        """Non-blocking admission (the router owns retry-elsewhere)."""
        leg._handle_id = self.engine_id
        return self.engine.submit_request(leg, block=False)

    def abort(self, leg):
        """Silently cancel one leg (hedge loser). True when the leg was
        actually cancelled — its ``on_done`` will never fire."""
        return self.engine.abort_request(leg)

    def start(self):
        self.engine.start()

    def close(self):
        self.engine.close()


class FleetRouter:
    """Dispatch over engine handles with affinity, balancing, health."""

    # sticky-session map cap: beyond this the oldest entries age out
    # (LRU — refreshing a session moves it to the tail), so a stream of
    # unique prompts can't grow the dispatch tier without bound
    MAX_AFFINITY = 4096

    def __init__(self, max_redispatch=3, registry=None,
                 affinity_spill=4, hedge_after_s=None, ledger=None,
                 lease=None):
        self._handles = {}
        self._affinity = {}        # head key -> engine_id (LRU order)
        self._lock = threading.Lock()
        self.max_redispatch = int(max_redispatch)
        # affinity yields when the affine engine is this many requests
        # MORE loaded than the lightest candidate: a hot session must
        # spill to a second engine (where cross-engine prefix sharing
        # picks up the head) instead of dogpiling one replica
        self.affinity_spill = int(affinity_spill)
        # a request quiet (no token) for this long is a straggler:
        # hedge_sweep() duplicates its leg on a second engine. None
        # disables hedging (the sweep still prunes finished requests).
        self.hedge_after_s = None if hedge_after_s is None \
            else float(hedge_after_s)
        self.registry = registry
        # durable front door (ISSUE 17): the ledger journals every
        # request lifecycle through the replicated store; the lease
        # fences this router against a shadow takeover. Both optional —
        # a ledger-less router keeps the pre-17 volatile behavior.
        self._ledger = ledger
        self.lease = lease
        self._fenced = False
        self.page_size = None
        self.cfg = None            # first engine's model config (loadgen)
        self._inflight = {}        # request_id -> FleetRequest (live)
        # prefetch runs on a side thread by default so the dispatch path
        # never waits on a store round-trip; tests flip it synchronous
        self._prefetch_async = True
        # fleet-level counters (bench/tests)
        self.dispatched = 0
        self.redispatched = 0
        self.migrations = 0
        self.saturated = 0
        self.affinity_hits = 0
        self.hedges_fired = 0
        self.hedges_won = 0
        self.aborts = 0
        self.prefetch_pages = 0
        self.requests_replayed = 0   # terminal ids answered off the journal
        self.requests_attached = 0   # in-flight ids attached to live legs
        self.requests_adopted = 0    # takeover adoptions from the ledger
        # unlabeled fleet-level frontend: hedge/abort counters belong to
        # the DISPATCH tier, not to any one engine's labeled families
        self.metrics = ServingMetrics(prefix_enabled=False)

    # ------------------------------------------------------------ roster
    def add_engine(self, engine, engine_id=None, role="any", handle=None):
        """Register one engine replica. ``role``: "any" (prefill AND
        decode), "prefill" or "decode" (disaggregated fleets). Pass a
        prebuilt ``handle`` for remote engines."""
        if handle is None:
            engine_id = engine_id if engine_id is not None \
                else (engine.engine_id or f"e{len(self._handles)}")
            handle = LocalEngineHandle(engine, engine_id, role=role)
        with self._lock:
            if handle.engine_id in self._handles:
                raise ValueError(
                    f"engine id {handle.engine_id!r} already registered")
            self._handles[handle.engine_id] = handle
        eng = getattr(handle, "engine", None)
        if eng is not None:
            if self.page_size is None:
                self.page_size = eng.page_size
            if self.cfg is None:
                self.cfg = eng.cfg
        elif self.page_size is None:
            self.page_size = getattr(handle, "page_size", None)
            self.cfg = getattr(handle, "cfg", None)
        if self.registry is not None and eng is not None:
            self.registry.register(handle.engine_id, engine=eng,
                                   role=role)
        return handle

    def handles(self):
        with self._lock:
            return dict(self._handles)

    def engine(self, engine_id):
        return self._handles[engine_id].engine

    # --------------------------------------------------------- selection
    def _head_key(self, prompt, session=None):
        if session is not None:
            return ("s", session)
        ps = self.page_size or 0
        if ps and len(prompt) > ps:
            # only a FULL first page can ever be prefix-shared (the
            # cache indexes full pages; the hit cap leaves the last
            # token computed), so shorter prompts have no affinity
            return ("p", tuple(prompt[:ps]))
        return None

    def _candidates(self, head=None, stage="prefill", exclude=(),
                    pin=None):
        with self._lock:
            hs = [h for h in self._handles.values()
                  if h.engine_id not in exclude]
        if pin is not None:
            return [h for h in hs if h.engine_id == pin and h.healthy()]
        roles = ("any", "prefill") if stage == "prefill" \
            else ("any", "decode")
        hs = [h for h in hs if h.healthy() and h.role in roles]
        # the effective load blends the engine's own report with the
        # router's in-flight count: reported numbers lag by a heartbeat,
        # and during an arrival burst every stale 0 would tie-break to
        # the same engine
        loads = {h.engine_id: max(h.load(), h.pending) for h in hs}
        hs.sort(key=lambda h: (loads[h.engine_id], h.occupancy(),
                               h.engine_id))
        if head is not None and hs:
            aff = self._affinity.get(head)
            lightest = loads[hs[0].engine_id]
            for i, h in enumerate(hs):
                if h.engine_id == aff:
                    # prefer the page-holding engine — but spill once it
                    # is affinity_spill requests deeper than the
                    # lightest replica (the session's next requests
                    # prefix-hit remotely there instead of queueing here)
                    if i and loads[aff] <= lightest + self.affinity_spill:
                        hs.insert(0, hs.pop(i))
                    break
        return hs

    def _has_decode_pool(self):
        with self._lock:
            return any(h.role == "decode" for h in self._handles.values())

    # ------------------------------------------------------------ submit
    def submit(self, prompt_ids, max_new_tokens=16, eos_token_id=None,
               temperature=0.0, top_k=None, on_token=None, block=True,
               timeout=10.0, session=None, engine=None, request_id=None,
               trace=None):
        """Same surface as ``ServingEngine.submit`` (so the Poisson
        loadgen drives a fleet unchanged), plus ``session=`` (explicit
        affinity key), ``engine=`` (pin to one engine id — tests and
        the bench's cross-engine warm path) and ``request_id=`` (the
        client's exactly-once idempotency key, ISSUE 17: a terminal id
        replays the recorded result without touching an engine, an
        in-flight id attaches to the live request). ->
        :class:`FleetRequest`."""
        self._check_lease()
        if self._ledger is not None and request_id is not None:
            fr = self._resubmit(str(request_id), on_token)
            if fr is not None:
                return fr
        if trace is None:
            # the router is the front door here: mint the trace context
            # itself (None when tracing is off — one call, no allocation)
            trace = _trc.mint_context()
        fr = FleetRequest(prompt_ids, max_new_tokens=max_new_tokens,
                          eos_token_id=eos_token_id,
                          temperature=temperature, top_k=top_k,
                          on_token=on_token, request_id=request_id,
                          trace=trace)
        if self._ledger is not None:
            # journal admission BEFORE the first placement: the record
            # is the idempotency anchor a retry (or a shadow) finds
            t_led = time.time() if trace is not None else 0.0
            try:
                self._ledger.accept(fr)
            except Exception:
                pass
            if trace is not None:
                _trc.req_event(trace, "ledger_accept", t_led,
                               time.time() - t_led,
                               args={"rid": fr.request_id})
        deadline = time.perf_counter() + (float(timeout) if block else 0.0)
        first = True
        t_route = time.time() if trace is not None else 0.0
        while True:
            if self._dispatch(fr, session=session, pin=engine):
                if trace is not None:
                    dur = time.time() - t_route
                    _trc.req_event(trace, "route", t_route, dur,
                                   args={"engine": fr.engine_id})
                    self.metrics.on_phase("route", dur)
                if not fr.done():
                    with self._lock:
                        self._inflight[fr.request_id] = fr
                return fr
            self.saturated += bool(first)
            first = False
            if time.perf_counter() >= deadline:
                raise FleetSaturated(
                    "every engine's admission queue is full "
                    f"({len(self._handles)} engine(s))")
            time.sleep(0.005)

    # ------------------------------------- exactly-once ledger (ISSUE 17)
    def _check_lease(self):
        """Dispatch-path fence: a deposed router must stop dispatching.
        Between beats (ttl/3 cadence) this is one monotonic compare —
        the term re-read only happens on the beat itself."""
        if self._fenced:
            raise RouterDeposedError(
                "router fenced: a shadow holds the front-door lease")
        lease = self.lease
        if lease is None:
            return
        try:
            lease.beat()
        except RouterDeposedError:
            self.fence()
            raise

    def fence(self):
        """Stop dispatching permanently (deposed). Front-door processes
        map this to the named exit ``EXIT_DEPOSED`` (76) — the same
        yield-don't-split-brain contract as a deposed coordinator."""
        self._fenced = True

    def _resubmit(self, rid, on_token):
        """Idempotent resubmission: the same request id reaches the
        same request. -> FleetRequest, or None (novel id: caller
        dispatches fresh)."""
        with self._lock:
            live = self._inflight.get(rid)
        if live is not None:
            # attach to the live leg — the original stream keeps its
            # on_token; a second callback would double-deliver tokens
            self.requests_attached += 1
            return live
        rec = self._ledger.lookup(rid)
        if rec is None:
            return None
        if rec.get("state") in TERMINAL_STATES:
            return self._replay_terminal(rec, on_token)
        # non-terminal record with no live request: this incarnation
        # never saw it (shadow-takeover edge, or a saturated submit the
        # client retried) — adopt it off the journal now
        return self._adopt_record(rec, on_token=on_token)

    def _replay_terminal(self, rec, on_token=None):
        """Rebuild a finished request from its terminal record:
        byte-identical tokens (or the same typed error), no engine
        touched."""
        fr = FleetRequest(rec["prompt"],
                          max_new_tokens=rec.get("max_new_tokens", 16),
                          eos_token_id=rec.get("eos_token_id"),
                          temperature=rec.get("temperature", 0.0),
                          top_k=rec.get("top_k"), on_token=on_token,
                          request_id=rec["rid"],
                          trace=rec.get("trace"))
        toks = [int(t) for t in rec.get("tokens", [])]
        err = rebuild_error(rec.get("error"))
        fr.engine_id = rec.get("engine_id")
        fr.engine_ids = list(rec.get("engine_ids") or [])
        fr.queue_wait_s = float(rec.get("queue_wait_s", 0.0))
        fr.evictions = int(rec.get("evictions", 0))
        now = time.perf_counter()
        fr.generated = list(toks)
        fr.token_times = [now] * len(toks)
        if toks:
            fr.t_first_token = now
        fr._ledger_cursor = len(toks)
        fr._ledger_done = True    # the record IS the journal: no rewrite
        if on_token is not None:
            for i, t in enumerate(toks):
                try:
                    on_token(fr, int(t),
                             err is None and i == len(toks) - 1)
                except Exception:
                    pass
        if fr.trace is not None:
            _trc.req_event(fr.trace, "ledger_replay", time.time(), 0.0,
                           args={"rid": fr.request_id})
        fr._finish(err)
        self.requests_replayed += 1
        self.metrics.on_router_replay()
        return fr

    def _adopt_record(self, rec, on_token=None):
        """Reconstruct one non-terminal ledger record into a live
        request: re-attach to the engine-side leg when its engine
        survived, else re-dispatch a continuation carrying the surfaced
        tokens (greedy token-identical, the existing re-dispatch
        contract)."""
        rid = rec["rid"]
        fr = FleetRequest(rec["prompt"],
                          max_new_tokens=rec.get("max_new_tokens", 16),
                          eos_token_id=rec.get("eos_token_id"),
                          temperature=rec.get("temperature", 0.0),
                          top_k=rec.get("top_k"), on_token=on_token,
                          request_id=rid, trace=rec.get("trace"))
        toks = [int(t) for t in rec.get("tokens", [])]
        now = time.perf_counter()
        # tokens[:cursor] were already surfaced to the client by the
        # deposed router — pre-seed them so only the unstreamed tail
        # re-fires callbacks (no duplicate tokens)
        fr.generated = list(toks)
        fr.token_times = [now] * len(toks)
        if toks:
            fr.t_first_token = now
        fr.engine_ids = list(rec.get("engine_ids") or [])
        fr._ledger_cursor = len(toks)
        with self._lock:
            already = self._inflight.get(rid)
            if already is not None:
                return already    # raced another adopter: theirs wins
            self._inflight[rid] = fr
        eid = rec.get("engine_id")
        leg_rid = rec.get("leg_rid")
        h = self._handles.get(eid) if eid is not None else None
        attached = False
        if rec.get("state") in ("dispatched", "streaming") \
                and h is not None and leg_rid is not None:
            try:
                attached = self._reattach(fr, h, leg_rid,
                                          skip=len(toks))
            except Exception:
                attached = False
        if not attached:
            # its engine died with the router (or the leg never
            # landed): fresh continuation leg on a healthy engine
            deadline = time.perf_counter() + 1.0
            while not self._dispatch(fr):
                if time.perf_counter() >= deadline:
                    self._finish_fr(fr, FleetSaturated(
                        "ledger adoption found no engine with queue "
                        "space"))
                    break
                time.sleep(0.02)
        self.requests_adopted += 1
        return fr

    def _reattach(self, fr, h, leg_rid, skip=0):
        """Adopt the engine-side leg of a takeover-inherited request.
        Remote: register the wire rid with the handle — its poller's
        history replay rebuilds the token list, surfacing only tokens
        beyond ``skip`` (the persisted cursor). Local: re-point the
        live GenerationRequest's callbacks under the engine step lock
        and replay the unstreamed tail. -> bool (attached)."""
        try:
            if not h.healthy():
                return False
        except Exception:
            return False
        if getattr(h, "remote", False):
            leg = h.attach(leg_rid, fr.prompt_ids,
                           on_token=fr._leg_token,
                           on_done=self._on_leg_done, fleet=fr,
                           skip=skip)
            with self._lock:
                leg._pending_done = False
                h.pending += 1
            fr._attach(leg, h.engine_id)
            return True
        eng = getattr(h, "engine", None)
        if eng is None or not hasattr(h, "find_leg"):
            return False
        leg = h.find_leg(leg_rid)
        if leg is None:
            return False           # finished engine-side: re-dispatch
        # _step_lock -> router lock is the established order (the
        # migrate hook set it); holding it freezes emission while the
        # callbacks swing over, so no token is lost or doubled
        with eng._step_lock:
            tail = [int(t) for t in leg.generated[skip:]]
            leg.on_token = fr._leg_token
            leg.on_done = self._on_leg_done
            leg._fleet = fr
            leg._handle_id = h.engine_id
            with self._lock:
                leg._pending_done = False
                h.pending += 1
            fr._attach(leg, h.engine_id)
            for i, t in enumerate(tail):
                fr._leg_token(leg, t, False)
        return True

    def adopt_from_ledger(self):
        """Shadow takeover: reconstruct the front door from the journal
        — every non-terminal record becomes a live request again,
        re-attached to its engine's live leg (unstreamed tail replayed
        off the persisted cursor) or re-dispatched when its engine died
        too. The roster must already be added (from the
        ``EngineRegistry``); affinity rebuilds lazily from traffic.
        -> number of requests adopted."""
        led = self._ledger
        if led is None:
            return 0
        before = self.requests_adopted
        for rec in led.inflight_records():
            self._adopt_record(rec)
        return self.requests_adopted - before

    def ledger_sweep(self):
        """Batch the surfaced-token cursors into the journal: ONE store
        write per request that emitted tokens since the last sweep —
        never per token, so the token path stays store-free between
        lifecycle transitions. Rides ``hedge_sweep`` (the autoscaler
        tick) or the front-door loop."""
        led = self._ledger
        if led is None:
            return 0
        with self._lock:
            frs = list(self._inflight.values())
        wrote = 0
        for fr in frs:
            if fr.done():
                continue
            with fr._tok_lock:
                toks = [int(t) for t in fr.generated]
            if len(toks) <= fr._ledger_cursor:
                continue
            leg = fr._leg
            leg_rid = getattr(leg, "request_id", None) \
                if leg is not None else None
            try:
                led.streaming(fr, toks, leg_rid=leg_rid)
                fr._ledger_cursor = len(toks)
                wrote += 1
            except Exception:
                pass
        return wrote

    def _ledger_dispatched(self, fr, engine_id, leg):
        led = self._ledger
        if led is None or fr._ledger_done:
            return
        try:
            led.dispatched(fr, engine_id,
                           leg_rid=getattr(leg, "request_id", None))
        except Exception:
            pass

    def _finish_fr(self, fr, error=None):
        """Every terminal path funnels here: finish the caller's
        handle, journal the durable result-of-record, then untrack —
        in that order, so a retry arriving mid-finish finds either the
        live request or the terminal record, never neither."""
        fr._finish(error)
        led = self._ledger
        if led is not None and not fr._ledger_done:
            fr._ledger_done = True
            try:
                led.terminal(fr)
            except Exception:
                pass
        ctx = fr.trace
        if ctx is not None:
            _trc.req_event(ctx, "fleet_done", time.time(), 0.0,
                           args={"rid": fr.request_id,
                                 "state": fr.state,
                                 "engines": list(fr.engine_ids),
                                 "hedged": fr._hedged})
            # the router owns the request end-to-end, so ITS terminal is
            # the tail-sampling decision point: retain the trace when
            # the request was interesting (error/hedge/evict/migrate),
            # slow, or explicitly sampled
            _trc.finish_request(
                ctx, dur_s=(fr.t_done - fr.t_submit)
                if fr.t_done is not None else None,
                error=error is not None, hedged=fr._hedged,
                evicted=fr.evictions > 0, migrated=fr.migrations > 0)
        self._untrack(fr)

    def _dispatch(self, fr, session=None, pin=None, exclude=()):
        """One placement attempt over the candidate order. -> bool."""
        prompt = fr.prompt_ids + fr.generated
        remaining = fr.max_new_tokens - len(fr.generated)
        if remaining <= 0:       # redispatch raced the last token
            self._finish_fr(fr)
            return True
        head = self._head_key(prompt, session)
        disagg = self._has_decode_pool()
        for h in self._candidates(head=head, stage="prefill",
                                  exclude=exclude, pin=pin):
            leg = GenerationRequest(
                prompt, max_new_tokens=remaining,
                eos_token_id=fr.eos_token_id,
                temperature=fr.temperature, top_k=fr.top_k,
                on_token=fr._leg_token,
                on_done=self._on_leg_done, trace=fr.trace)
            leg._fleet = fr
            if disagg and h.role == "prefill":
                leg.migrate_hook = self._migrate_after_prefill
            # attach AND count BEFORE submitting: a fast engine thread
            # can finish the leg (and fire on_done, which decrements
            # pending) before this thread returns from submit — both
            # sides of the bookkeeping must already be in place
            fr._leg = leg
            with self._lock:
                leg._pending_done = False   # fresh latch per attempt
                h.pending += 1
            try:
                # a remote handle substitutes its own wire-side leg —
                # the returned object is the one that will finish
                leg = h.submit(leg) or leg
            except (QueueFull, EngineClosed):
                # raced a full queue / shutdown: next candidate
                self._dec_pending(leg, h)
                continue
            with self._lock:
                prev_aff = self._affinity.get(head) \
                    if head is not None else None
                if head is not None:
                    if prev_aff == h.engine_id:
                        self.affinity_hits += 1
                    self._affinity.pop(head, None)    # move to LRU tail
                    self._affinity[head] = h.engine_id
                    while len(self._affinity) > self.MAX_AFFINITY:
                        del self._affinity[next(iter(self._affinity))]
                self.dispatched += 1
            fr._attach(leg, h.engine_id)
            if fr.trace is not None:
                _trc.req_event(fr.trace, "dispatch", time.time(), 0.0,
                               args={"engine": h.engine_id,
                                     "redispatches": fr.redispatches})
            self._ledger_dispatched(fr, h.engine_id, leg)
            if prev_aff is not None and prev_aff != h.engine_id:
                # affinity SPILL: the session's pages live on prev_aff —
                # push the shared prefix here before the prefill runs
                self._prefetch_spill(h, prompt)
            return True
        return False

    # ----------------------------------------------------- leg lifecycle
    def _dec_pending(self, leg, handle=None):
        """Decrement the dispatching handle's in-flight count EXACTLY
        once per leg attempt. Completion, abort, and re-dispatch can all
        race to this on different threads — the per-leg latch (reset at
        each dispatch attempt) makes the loser a no-op instead of a
        double decrement that understates load forever."""
        with self._lock:
            if getattr(leg, "_pending_done", False):
                return
            leg._pending_done = True
            h = handle
            if h is None:
                hid = getattr(leg, "_handle_id", None)
                h = self._handles.get(hid) if hid is not None else None
            if h is not None and h.pending > 0:
                h.pending -= 1

    def _untrack(self, fr):
        with self._lock:
            self._inflight.pop(fr.request_id, None)

    def _on_leg_done(self, leg):
        if leg.state != "migrating":
            self._dec_pending(leg)
        fr = getattr(leg, "_fleet", None)
        if fr is None or fr.done():
            return
        if leg.state == "migrating":
            return  # moved engines, not finished
        if getattr(leg, "_hedge_base", None) is not None:
            self._hedge_done(fr, leg)
            return
        if leg is not fr._leg:
            return  # stale leg (already replaced by a promotion)
        fr._absorb(leg)
        if leg.error is None:
            with self._lock:
                hleg = fr._hedge
                fr._hedge = None
            self._finish_fr(fr)
            if hleg is not None:
                if fr.trace is not None:
                    _trc.req_event(
                        fr.trace, "hedge_lost", time.time(), 0.0,
                        args={"winner": fr.engine_id,
                              "loser": getattr(hleg, "_handle_id",
                                               None)})
                self._abort_leg(hleg)   # the duplicate lost the race
            return
        with self._lock:
            has_hedge = fr._hedge is not None
        if has_hedge:
            # the primary died but its duplicate is still running with
            # the full continuation — let the hedge carry the request
            # instead of burning a re-dispatch on a third engine
            fr._leg = None
            return
        self._redispatch_or_fail(fr, leg.error)

    def _redispatch_or_fail(self, fr, err):
        handle = self._handles.get(fr.engine_id)
        retryable = isinstance(err, (EngineShuttingDown, EngineClosed,
                                     QueueFull)) \
            or (handle is not None and not handle.healthy())
        if not retryable or fr.redispatches >= self.max_redispatch:
            self._finish_fr(fr, err)
            return
        fr.redispatches += 1
        self.redispatched += 1
        # retry-elsewhere with the tokens already emitted carried in the
        # continuation prompt; the retry window is SHORT because this
        # runs inline on whatever thread delivered the completion (an
        # engine serve thread, a remote handle's poller, a drain loop) —
        # blocking it starves every other completion behind it
        deadline = time.perf_counter() + 1.0
        while not self._dispatch(fr, exclude=(fr.engine_id,)):
            if time.perf_counter() >= deadline:
                self._finish_fr(fr, FleetSaturated(
                    "re-dispatch found no engine with queue space"))
                return
            time.sleep(0.02)

    # ------------------------------------------------------------ hedging
    def hedge_sweep(self, now=None):
        """One pass over in-flight requests: prune the finished, hedge
        the stragglers (quiet longer than ``hedge_after_s``). Returns the
        number of hedges fired. Called from the autoscaler tick; tests
        and headless routers call it directly."""
        if now is None:
            now = time.perf_counter()
        fired = 0
        with self._lock:
            frs = list(self._inflight.values())
        for fr in frs:
            if fr.done():
                self._untrack(fr)
                continue
            if self.hedge_after_s is None or fr._hedge is not None \
                    or fr._leg is None:
                continue
            last = fr.token_times[-1] if fr.token_times else fr.t_submit
            if now - last < self.hedge_after_s:
                continue
            if self._hedge(fr):
                fired += 1
        # the ledger's cursor batching rides the same tick: one store
        # write per request that streamed since the last sweep
        self.ledger_sweep()
        return fired

    def _hedge(self, fr):
        """Duplicate ``fr``'s leg on a second engine. -> bool (fired)."""
        with fr._tok_lock:
            base = len(fr.generated)
            cont = fr.prompt_ids + fr.generated
            # a disaggregation migration in flight moves the leg to
            # _migrating_to (set under this same lock): a hedge placed
            # THERE would duplicate the leg on its own engine, and one
            # keyed only on the stale pre-migration engine_id could do
            # the same a tick later — exclude both
            migrating_to = fr._migrating_to
        remaining = fr.max_new_tokens - base
        if remaining <= 0:
            return False
        exclude = tuple(e for e in (fr.engine_id, migrating_to)
                        if e is not None)
        for h in self._candidates(stage="prefill", exclude=exclude):
            hleg = GenerationRequest(
                cont, max_new_tokens=remaining,
                eos_token_id=fr.eos_token_id,
                temperature=fr.temperature, top_k=fr.top_k,
                on_token=fr._leg_token,    # dropped until promotion
                on_done=self._on_leg_done, trace=fr.trace)
            hleg._fleet = fr
            hleg._hedge_base = base
            with self._lock:
                if fr._hedge is not None or fr.done():
                    return False
                hleg._pending_done = False
                h.pending += 1
                fr._hedge = hleg
            try:
                hleg = h.submit(hleg) or hleg
            except (QueueFull, EngineClosed):
                self._dec_pending(hleg, h)
                with self._lock:
                    fr._hedge = None
                continue
            with self._lock:
                fr._hedge = hleg   # remote handles substitute wire legs
            self.hedges_fired += 1
            self.metrics.on_hedge_fired()
            fr._hedged = True      # hedged traces are always retained
            if fr.trace is not None:
                _trc.req_event(fr.trace, "hedge_fired", time.time(), 0.0,
                               args={"engine": h.engine_id,
                                     "base_tokens": base})
            return True
        return False

    def _hedge_done(self, fr, hleg):
        with self._lock:
            if hleg is not fr._hedge:
                return             # superseded hedge — nothing to do
            fr._hedge = None
            primary = fr._leg
            if hleg.error is None:
                # freeze the primary's surfacing BEFORE the splice: any
                # token it emits from here on hits the identity guard
                fr._leg = None
        if fr.done():
            return
        if hleg.error is not None:
            # the hedge lost by failing; if the primary already died
            # waiting on it, fall back to the normal re-dispatch path
            if primary is None:
                self._redispatch_or_fail(fr, hleg.error)
            return
        self._promote_hedge(fr, hleg)
        self.hedges_won += 1
        self.metrics.on_hedge_won()
        if fr.trace is not None:
            _trc.req_event(fr.trace, "hedge_won", time.time(), 0.0,
                           args={"winner": getattr(hleg, "_handle_id",
                                                   fr.engine_id),
                                 "loser": getattr(primary, "_handle_id",
                                                  None)})
        if primary is not None:
            self._abort_leg(primary)   # the original lost the race

    def _promote_hedge(self, fr, hleg):
        """The duplicate finished first: splice its tokens over the
        primary's tail (greedy decode makes them identical where they
        overlap) and finish the fleet request."""
        base = hleg._hedge_base
        with fr._tok_lock:
            surfaced = len(fr.generated) - base   # primary tokens beyond
            tail = [int(t) for t in hleg.generated[surfaced:]]
            fr.generated[base:] = [int(t) for t in hleg.generated]
            now = time.perf_counter()
            for _ in tail:
                if fr.t_first_token is None:
                    fr.t_first_token = now
                fr.token_times.append(now)
        if fr.trace is not None and tail:
            # the splice IS the delivery instant for a hedge winner's
            # tokens — they surface to the caller all at once here, not
            # through _leg_token
            t_now = time.time()
            for i in range(len(tail)):
                _trc.req_event(fr.trace, "stream_token", t_now, 0.0,
                               args={"i": base + surfaced + i + 1,
                                     "fin": i == len(tail) - 1,
                                     "spliced": True})
        cb = fr.on_token
        if cb is not None:
            for i, t in enumerate(tail):
                try:
                    cb(fr, t, i == len(tail) - 1)
                except Exception:
                    pass
        fr._attach(hleg, getattr(hleg, "_handle_id", fr.engine_id))
        fr._absorb(hleg)
        self._finish_fr(fr)

    def _abort_leg(self, leg):
        """Silently cancel a hedge loser: its slot + pages free, its
        ``on_done`` never fires — the aborter owns the pending
        decrement. MUST run outside ``self._lock``: the engine abort
        takes ``_step_lock``, and the migrate hook already establishes
        the ``_step_lock -> router lock`` order."""
        hid = getattr(leg, "_handle_id", None)
        h = self._handles.get(hid) if hid is not None else None
        if h is None or not hasattr(h, "abort"):
            return
        try:
            cancelled = bool(h.abort(leg))
        except Exception:
            cancelled = False
        if cancelled:
            self._dec_pending(leg)
            self.aborts += 1
            self.metrics.on_abort()
            ctx = getattr(leg, "trace", None)
            if ctx is not None:
                _trc.req_event(ctx, "leg_abort", time.time(), 0.0,
                               args={"engine": hid})

    def _prefetch_spill(self, handle, prompt):
        """Pull the prompt's shared prefix pages onto ``handle``'s
        engine (page-share import) so the spilled session's prefill
        prefix-hits locally instead of missing cold."""
        eng = getattr(handle, "engine", None)
        if eng is None or getattr(eng.prefix, "share", None) is None:
            return

        def run():
            try:
                n = eng.prefetch_prefix(prompt)
            except Exception:
                return
            if n:
                with self._lock:
                    self.prefetch_pages += n
        if self._prefetch_async:
            threading.Thread(target=run, daemon=True,
                             name="fleet-prefetch").start()
        else:
            run()

    def _migrate_after_prefill(self, src_engine, leg):
        """``migrate_hook`` body: the prompt completed on a prefill
        engine — move the KV pages to the least-loaded decode engine.
        False (= stay) when no decode engine can take it."""
        fr = getattr(leg, "_fleet", None)
        cands = self._candidates(stage="decode",
                                 exclude=(getattr(fr, "engine_id", None)
                                          or src_engine.engine_id,))
        cands = [c for c in cands if c.role == "decode"
                 and getattr(c, "engine", None) is not None]
        for dst in cands:
            if fr is not None:
                # publish the target BEFORE the pages move (under the
                # same lock the hedge path reads): a hedge fired during
                # the migration must not land on dst — it would race
                # the arriving leg on its own engine. Cleared only
                # AFTER _attach repoints engine_id at dst, so the
                # exclusion never gaps.
                with fr._tok_lock:
                    fr._migrating_to = dst.engine_id
            try:
                try:
                    outcome = _disagg.migrate_request(
                        src_engine, dst.engine, leg)
                except _disagg.MigrationFailed:
                    continue  # a detached leg retries the next candidate
                if outcome == "skipped":
                    return False
                self._move_pending(leg, dst)
                self.migrations += 1
                if fr is not None:
                    fr.migrations += 1
                    fr._attach(leg, dst.engine_id)
                return True
            finally:
                if fr is not None:
                    with fr._tok_lock:
                        fr._migrating_to = None
        if leg.state == "migrating":
            # every candidate refused AFTER a failed attempt detached
            # the leg from the source — it must not dangle in no
            # engine: requeue on the source (recompute locally), or
            # fail with a typed error as the last resort
            try:
                src_engine.readmit_request(leg)
            except Exception as e:
                leg.finish(e)
            return False
        return False

    def _move_pending(self, leg, dst_handle):
        """Re-home the in-flight accounting of a migrated leg. A leg the
        router never dispatched (direct engine use swept up by a drain)
        has no pending count to move — and must not gain one: nothing
        would ever decrement it."""
        if getattr(leg, "_handle_id", None) is None:
            return
        with self._lock:
            old = self._handles.get(leg._handle_id)
            if old is not None and old.pending > 0:
                old.pending -= 1
            dst_handle.pending += 1
        leg._handle_id = dst_handle.engine_id

    # ----------------------------------------------------- engine drain
    def remove_engine(self, engine_id, migrate=True):
        """Take one engine out of rotation (planned loss, upgrade,
        graceful shutdown): queued requests fail with the retryable
        ``EngineShuttingDown`` and re-dispatch through ``on_done``;
        in-flight requests migrate their pages to healthy engines when
        ``migrate=True`` (recompute fallback built in), else drain
        through the engine's own close (re-dispatch recomputes). Returns
        ``{request_id: outcome}`` for the migrated set."""
        h = self._handles.get(engine_id)
        if h is None:
            raise KeyError(f"unknown engine {engine_id!r}")
        h.forced_down = True
        with self._lock:
            # dead engine ids must not linger as affinity targets (they
            # would defeat every future affinity check for those heads)
            for k in [k for k, v in self._affinity.items()
                      if v == engine_id]:
                del self._affinity[k]
        eng = getattr(h, "engine", None)
        out = {}
        if eng is None:
            return out
        queued = eng.scheduler.begin_shutdown()
        for req in queued:
            eng.metrics.on_finish(req)
        if migrate:
            def pick(req):
                for c in self._candidates(stage="decode",
                                          exclude=(engine_id,)):
                    if getattr(c, "engine", None) is not None:
                        return c.engine
                return None

            def moved(req, dst_engine, outcome):
                fr = getattr(req, "_fleet", None)
                dst_h = next(
                    (h for h in self.handles().values()
                     if getattr(h, "engine", None) is dst_engine), None)
                if dst_h is not None:
                    self._move_pending(req, dst_h)
                self.migrations += 1
                if fr is not None:
                    fr.migrations += 1
                    fr._attach(req, dst_h.engine_id if dst_h is not None
                               else dst_engine.engine_id)

            out = _disagg.drain_active(eng, pick, on_moved=moved)
        eng.close()
        if self.registry is not None:
            try:
                self.registry.deregister(engine_id)
            except Exception:
                pass
        return out

    def mark_unhealthy(self, engine_id):
        h = self._handles.get(engine_id)
        if h is not None:
            h.forced_down = True

    def drop_engine(self, engine_id):
        """Reap an ALREADY-DEAD engine from the roster (crashed serve
        loop, lost process): no drain, no migration — its legs have
        already failed through ``on_done`` re-dispatch. The graceful
        path is ``remove_engine``."""
        with self._lock:
            h = self._handles.pop(engine_id, None)
            for k in [k for k, v in self._affinity.items()
                      if v == engine_id]:
                del self._affinity[k]
        if h is None:
            return False
        h.forced_down = True
        if self.registry is not None:
            try:
                self.registry.deregister(engine_id)
            except Exception:
                pass
        return True

    # ------------------------------------------------------------ helpers
    def start(self):
        for h in self.handles().values():
            h.start()

    def close(self):
        for h in self.handles().values():
            try:
                h.close()
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def stats(self):
        with self._lock:
            hs = dict(self._handles)
        return {
            "engines": {eid: {"healthy": h.healthy(), "role": h.role,
                              "load": h.load(), "pending": h.pending}
                        for eid, h in hs.items()},
            "dispatched": self.dispatched,
            "redispatched": self.redispatched,
            "migrations": self.migrations,
            "saturated": self.saturated,
            "affinity_hits": self.affinity_hits,
            "affinity_sessions": len(self._affinity),
            "hedges_fired": self.hedges_fired,
            "hedges_won": self.hedges_won,
            "aborts": self.aborts,
            "prefetch_pages": self.prefetch_pages,
            "inflight": len(self._inflight),
            "requests_replayed": self.requests_replayed,
            "requests_attached": self.requests_attached,
            "requests_adopted": self.requests_adopted,
            "fenced": self._fenced,
        }
