"""Prefill/decode disaggregation — KV page migration between engines.

The Gemma-on-TPU serving topology (arxiv 2605.25645): prefill is
compute-bound and bursty, decode is memory-bound and steady, so a fleet
runs **prefill-designated** and **decode-designated** engines and moves a
request's KV pages from one to the other when its prompt completes. The
same extraction → transfer → ``write_prefill`` → block-table-rebind
machinery doubles as the fleet's failover path: draining a live engine
migrates its in-flight requests instead of recomputing them.

One migration is:

1. **extract** — :meth:`ServingEngine.snapshot_kv` gathers the request's
   ``num_cached`` written tokens per layer into host arrays (a read-only
   gather; shared prefix pages keep their other readers);
2. **release** — :meth:`ServingEngine.release_request` frees the source
   slot + pages *without* finishing the request (the same
   ``GenerationRequest`` object moves — its waiters, streaming callbacks
   and timestamps ride along);
3. **adopt** — :meth:`ServingEngine.adopt_request` allocates pages on
   the target, writes the payload, and joins the decode batch directly:
   the continuation consumes ``generated[-1]`` at position
   ``num_cached``, exactly the step the source would have run next, so
   greedy decode is token-identical across the move (tested across page
   boundaries, GQA and prefix hits);
4. **fallback** — if the target pool/batch is full
   (``OutOfPages``/``OutOfSlots``), the request re-queues at the
   target's front and recomputes its ``effective_prompt()`` on
   admission — the eviction-readmission contract, still
   token-identical.
"""
from __future__ import annotations

import sys
import time

from ...observability import tracing as _trc
from ..kv_cache import OutOfPages
from ..scheduler import EngineClosed, OutOfSlots

__all__ = ["migrate_request", "MigrationFailed"]


class MigrationFailed(RuntimeError):
    """Neither the migrate nor the recompute path could place the request
    on the target engine (it is closed or saturated beyond readmission).
    The caller (router) re-dispatches to another engine."""


def migrate_request(src, dst, req):
    """Move one in-flight request from ``src`` to ``dst``.

    Returns ``"migrated"`` (pages moved), ``"recompute"`` (target had no
    room for a direct adopt; the request re-prefills from the queue) or
    ``"skipped"`` (the request reached a terminal state first). Raises
    :class:`MigrationFailed` when the target cannot take it at all. The
    request object itself moves — callers keep their handle.
    """
    ctx = getattr(req, "trace", None)
    t0 = time.time() if ctx is not None else 0.0
    with src._step_lock:
        if req.state == "migrating":
            # a PRIOR migrate attempt already detached it from the
            # source and then failed on its target — this retry goes
            # straight to placement (the pages are gone; recompute)
            payload = None
        elif req.state not in ("active", "prefilling"):
            return "skipped"
        elif req.state == "prefilling" or req.num_cached == 0:
            # nothing written yet: a recompute on the target is strictly
            # cheaper than moving zero pages
            src.release_request(req)
            payload = None
        else:
            payload = src.snapshot_kv(req)
            src.release_request(req)
        # a migration is ONE prefill->decode (or drain) move: the hook
        # must not re-fire on the target — a recompute-placed request
        # completing its re-prefill on a decode engine would otherwise
        # migrate AGAIN (ping-pong), and two decode engines migrating
        # toward each other would deadlock their serve threads (each
        # holds its own step lock while taking the other's)
        req.migrate_hook = None
    def _span(outcome, tokens):
        if ctx is None:
            return
        now = time.time()
        _trc.req_event(ctx, "kv_migrate", t0, now - t0,
                       args={"outcome": outcome, "tokens": tokens,
                             "src": getattr(src, "engine_id", None),
                             "dst": getattr(dst, "engine_id", None)})
        m = getattr(dst, "metrics", None)
        if m is not None:
            m.on_phase("migrate", now - t0)

    if payload is not None:
        ks, vs, length = payload
        try:
            dst.adopt_request(req, ks, vs, length)
            _span("migrated", int(length))
            return "migrated"
        except (OutOfPages, OutOfSlots):
            pass  # fall through to the recompute queue
        except EngineClosed as e:
            raise MigrationFailed(
                f"target engine refused adoption: {e}") from e
    try:
        dst.readmit_request(req)
        _span("recompute", 0)
        return "recompute"
    except EngineClosed as e:
        raise MigrationFailed(
            f"target engine refused readmission: {e}") from e


def drain_active(src, pick_target, on_moved=None):
    """Migrate every in-flight request off ``src`` (engine drain /
    planned loss): ``pick_target(req)`` names the destination engine per
    request (None = give up on that request). Returns
    ``{request_id: outcome}``. Used by the router's ``remove_engine``;
    requests that cannot be placed are left to the source's own
    close/shutdown path."""
    out = {}
    for req in list(src.scheduler.active.values()):
        dst = pick_target(req)
        if dst is None:
            continue
        try:
            out[req.request_id] = migrate_request(src, dst, req)
        except MigrationFailed as e:
            print(f"[fleet] migration of request {req.request_id} "
                  f"failed: {e}", file=sys.stderr, flush=True)
            if req.state == "migrating":
                # already detached from the source and NO engine took
                # it: a request in limbo must fail loudly ("tokens or
                # one typed error"), not time out — unless the source
                # can requeue it for its own drain window
                try:
                    src.readmit_request(req)
                    out[req.request_id] = "readmitted_source"
                except Exception:
                    req.finish(e)
                    out[req.request_id] = "failed"
            continue
        if on_moved is not None:
            try:
                on_moved(req, dst, out[req.request_id])
            except Exception:
                pass
    return out
