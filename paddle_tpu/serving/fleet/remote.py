"""Store-RPC serving — drive a remote ``ServingEngine`` over the TCPStore.

The multi-process half of the fleet: each engine replica runs in its own
process (its own XLA client, its own pools) and serves a tiny RPC
protocol over the control-plane store — the same transport the registry,
page-share index and elastic rendezvous already ride, so a fleet needs
exactly ONE listening port.

Protocol (keys under ``serving/<job>/eng/<eid>/``):

* ``in_seq`` counter + ``in/<seq>`` JSON — submissions (the router's
  client handle appends; the engine process tails). A record with
  ``"abort": true`` cancels the named request silently — slot + pages
  free, no completion published (the aborting client already dropped
  the leg, so a late completion would find nobody anyway);
* ``out_seq`` counter + ``out/<seq>`` JSON — completions (tokens or a
  typed, retryability-preserving error: ``QueueFull`` /
  ``EngineShuttingDown`` / ``EngineClosed`` rebuild client-side so the
  router's retry-elsewhere logic treats remote engines exactly like
  local ones);
* ``stream/tok_seq`` counter + ``stream/tok/<n>`` JSON — incremental
  token batches (ISSUE 16): every poll tick the server flushes the
  tokens emitted since the last tick as ONE record
  ``{"items": [[rid, [tokens...], fin], ...]}`` — at most one store
  write per tick regardless of decode fan-out, and the client's
  ``on_token``/TTFT reflect real emission time instead of arriving
  with the batched completion. Completions replay only the tokens the
  stream has not already surfaced, so the two channels compose without
  duplicates in either order;
* ``stop`` — graceful server exit (drain + final stats publish).

Worker entry point (used by ``bench.py --serving-fleet``)::

    python -m paddle_tpu.serving.fleet.remote --store 127.0.0.1:6200 \
        --engine-id e0 --job bench --seed 0 [--role any] [--share]

Per-engine TTFT/ITL tails still come from the engine process's own
labeled metrics JSONL (``--metrics-dir``), which is the fleet's
observability story.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

from ...distributed import keyspace
from ...observability import tracing as _trc
from ..scheduler import (EngineClosed, EngineShuttingDown,
                         GenerationRequest, QueueFull)

__all__ = ["serve_over_store", "RemoteEngineHandle", "main"]

_ERRORS = {"QueueFull": QueueFull,
           "EngineShuttingDown": EngineShuttingDown,
           "EngineClosed": EngineClosed}


def _result_record(rid, req=None, error=None):
    if error is None and req is not None and req.error is not None:
        error = req.error
    rec = {"rid": rid,
           "tokens": list(req.generated) if req is not None else [],
           "error": None}
    if error is not None:
        rec["error"] = {"type": type(error).__name__, "msg": str(error)}
    if req is not None:
        rec["queue_wait_s"] = req.queue_wait_s
        rec["evictions"] = req.evictions
        ttft = req.ttft_s()
        if ttft is not None:
            rec["ttft_s"] = ttft
    return rec


def serve_over_store(engine, store, engine_id, job="fleet",
                     registry=None, role="any", poll_s=0.04,
                     idle_timeout=None):
    """Serve one engine until the ``stop`` key appears (or
    ``idle_timeout`` seconds pass with no traffic). The engine must be
    ``start()``ed; completions are published from this thread only (one
    store client, one writer). Every store op this loop makes steals
    CPU from the engine's own core, so the polls are deliberately lean:
    one ``in_seq`` read per tick, stop keys every few ticks."""
    prefix = keyspace.fleet_engine_rpc(job, engine_id)
    stream_prefix = keyspace.fleet_engine_stream(job, engine_id)
    fleet_stop = f"{keyspace.fleet_registry(job)}/stop"
    done_lock = threading.Lock()
    done_queue = []          # results ready to publish
    tok_lock = threading.Lock()
    tok_buf = []             # (rid, token, fin) since the last flush
    inflight = {}            # rid -> engine-side request (abort target)
    # server-side idempotency (ISSUE 17 satellite): a client whose
    # submit timed out retries the SAME rid — without this cache the
    # retry record spawned a second GenerationRequest and the engine
    # generated twice. Bounded FIFO: old entries age out, and a rid old
    # enough to have aged out is also old enough to be answered by the
    # durable ledger instead.
    finished = {}            # rid -> published result record
    _FINISHED_CAP = 512

    def _remember(rid, rec):
        # caller holds done_lock
        finished[rid] = rec
        while len(finished) > _FINISHED_CAP:
            del finished[next(iter(finished))]

    def on_done(req):
        inflight.pop(req._rid, None)
        with done_lock:
            rec = _result_record(req._rid, req)
            _remember(req._rid, rec)
            done_queue.append(rec)

    def on_token(req, token, fin):
        with tok_lock:
            tok_buf.append((req._rid, int(token), bool(fin)))

    consumed = 0
    tick = 0
    last_traffic = time.monotonic()
    last_publish = 0.0
    while True:
        tick += 1
        if tick % 5 == 1 and (store.check(f"{prefix}/stop")
                              or store.check(fleet_stop)):
            break
        if idle_timeout is not None \
                and time.monotonic() - last_traffic > idle_timeout:
            break
        head = int(store.add(f"{prefix}/in_seq", 0))
        while consumed < head:
            consumed += 1
            try:
                msg = json.loads(store.get(f"{prefix}/in/{consumed}",
                                           timeout=10))
            except Exception:
                continue  # torn submission: the client will time out
            last_traffic = time.monotonic()
            rid = msg["rid"]
            if msg.get("abort"):
                req = inflight.pop(rid, None)
                if req is not None:
                    try:
                        engine.abort_request(req)
                    except Exception:
                        pass
                continue
            if rid in inflight:
                continue     # duplicate of a live request: one leg only
            with done_lock:
                replay = finished.get(rid)
                if replay is not None:
                    # retry of a finished rid: republish the recorded
                    # result instead of generating again
                    done_queue.append(replay)
            if replay is not None:
                continue
            try:
                # trace context off the wire: the SAME trace id the
                # router journaled, so this process's spans merge into
                # the one cross-process waterfall (ISSUE 20). None when
                # the submitter traced nothing — zero-overhead path.
                trace = msg.get("trace")
                req = GenerationRequest(
                    msg["prompt"],
                    max_new_tokens=int(msg.get("max_new_tokens", 16)),
                    eos_token_id=msg.get("eos_token_id"),
                    temperature=float(msg.get("temperature", 0.0)),
                    top_k=msg.get("top_k"), on_token=on_token,
                    on_done=on_done, trace=trace)
                req._rid = rid
                if trace is not None:
                    _trc.req_event(trace, "rpc_submit", time.time(),
                                   0.0, args={"rid": rid,
                                              "engine": engine_id})
                inflight[rid] = req
                engine.submit_request(req, block=False)
            except Exception as e:
                inflight.pop(rid, None)
                with done_lock:
                    rec = _result_record(rid, error=e)
                    _remember(rid, rec)
                    done_queue.append(rec)
        # per-token streaming: flush everything emitted since the last
        # tick as ONE batched record — a store write per tick, not per
        # token (and none at all on an idle tick)
        with tok_lock:
            toks, tok_buf[:] = list(tok_buf), []
        if toks:
            last_traffic = time.monotonic()
            by_rid, order, fins = {}, [], {}
            for rid, t, fin in toks:
                if rid not in by_rid:
                    by_rid[rid] = []
                    order.append(rid)
                by_rid[rid].append(t)
                fins[rid] = fin
            rec = {"items": [[r, by_rid[r], fins[r]] for r in order]}
            seq = int(store.add(f"{stream_prefix}/tok_seq", 1))
            store.set(f"{stream_prefix}/tok/{seq}", json.dumps(rec))
            tr = _trc._TR if _trc._loaded else _trc._load()
            if tr is not None:
                now = time.time()
                for r in order:
                    req = inflight.get(r)
                    ctx = getattr(req, "trace", None) \
                        if req is not None else None
                    if ctx is not None:
                        _trc.req_event(ctx, "stream_flush", now, 0.0,
                                       args={"tokens": len(by_rid[r]),
                                             "seq": seq})
        with done_lock:
            ready, done_queue[:] = list(done_queue), []
        for rec in ready:
            last_traffic = time.monotonic()
            seq = int(store.add(f"{prefix}/out_seq", 1))
            store.set(f"{prefix}/out/{seq}", json.dumps(rec))
        # load-stats refresh rides this loop THROTTLED (the registry's
        # own heartbeat thread already proves liveness at ttl/3; a
        # publish per poll tick would burn a store write every 20ms per
        # engine — measurable CPU on a small fleet host)
        now = time.monotonic()
        if registry is not None and now - last_publish > 0.25:
            last_publish = now
            try:
                registry.publish(engine_id, engine, role)
            except Exception:
                pass
        time.sleep(poll_s)


class _RemoteLeg:
    """Duck-typed stand-in for the engine-side GenerationRequest: the
    router treats it exactly like a local leg (state/error/on_done/
    accounting), completed by the handle's poller thread."""

    def __init__(self, rid, prompt, on_token=None, on_done=None,
                 skip=0):
        self.request_id = rid
        self.prompt_ids = list(prompt)
        self.generated = []
        self.state = "active"
        self.error = None
        self.queue_wait_s = 0.0
        self.evictions = 0
        self.on_token = on_token
        self.on_done = on_done
        self.migrate_hook = None
        self.trace = None        # propagated from the router leg
        # takeover re-attachment (ISSUE 17): a fresh handle's poller
        # replays the engine's stream history from seq 0 — the first
        # ``skip`` tokens were already surfaced to the client by the
        # deposed router (the ledger's persisted cursor), so they
        # rebuild ``generated`` silently; only the unstreamed tail
        # fires callbacks. Zero for normal submissions.
        self._skip = int(skip)

    def _stream(self, tokens, fin):
        """Adopt one incremental token batch from the stream channel
        (poller thread): surfaces through ``on_token`` immediately, so
        the fleet caller's TTFT/ITL reflect real emission time."""
        cb = self.on_token
        for i, t in enumerate(tokens):
            self.generated.append(int(t))
            if cb is not None and len(self.generated) > self._skip:
                try:
                    cb(self, int(t), bool(fin) and i == len(tokens) - 1)
                except Exception:
                    pass

    def _complete(self, rec):
        err = rec.get("error")
        tokens = [int(t) for t in rec.get("tokens", [])]
        # the stream channel already surfaced self.generated[:start] —
        # replay ONLY the tail the stream has not delivered yet (zero
        # when streaming kept up; everything when the server predates
        # the stream keys or the record raced ahead of the last batch).
        # A re-attached leg additionally skips its pre-takeover cursor.
        start = max(len(self.generated), self._skip)
        self.generated = tokens
        self.queue_wait_s = float(rec.get("queue_wait_s", 0.0))
        self.evictions = int(rec.get("evictions", 0))
        cb = self.on_token
        if cb is not None:
            # replay emitted tokens even on a retryable failure: the
            # router's re-dispatch carries the continuation prompt from
            # fr.generated, which only this callback populates — a
            # drained engine's 30 emitted tokens must not be recomputed
            # (final=True only on a clean finish)
            for i in range(start, len(tokens)):
                try:
                    cb(self, tokens[i],
                       err is None and i == len(tokens) - 1)
                except Exception:
                    pass
        if err is not None:
            cls = _ERRORS.get(err.get("type"), RuntimeError)
            self.error = cls(err.get("msg", "remote engine error"))
            self.state = "failed"
        else:
            self.state = "finished"
        done = self.on_done
        if done is not None:
            try:
                done(self)
            except Exception:
                pass


class RemoteEngineHandle:
    """Router-side handle to one store-served engine process.

    ``store_factory`` builds a fresh store client per internal thread
    (the native client is not shared across threads). Health/load come
    from the registry heartbeat — a dead engine process shows up as a
    stale beat, and its in-flight legs fail by client timeout, which the
    router re-dispatches."""

    remote = True
    engine = None

    def __init__(self, store_factory, engine_id, job="fleet",
                 registry=None, role="any", poll_s=0.04,
                 record_ttl=0.2, defer_poll=False):
        self.engine_id = str(engine_id)
        self.role = role
        self.job = job
        self.registry = registry
        self.forced_down = False
        self.pending = 0                # router-side in-flight count
        self._rec_cache = (0.0, None)   # (fetched_at, record)
        self._rec_ttl = float(record_ttl)
        self._prefix = keyspace.fleet_engine_rpc(job, self.engine_id)
        self._stream_prefix = keyspace.fleet_engine_stream(
            job, self.engine_id)
        self._submit_store = store_factory()
        self._poll_store = store_factory()
        self._poll_s = float(poll_s)
        self._pending = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._poll_loop,
                                        daemon=True,
                                        name=f"fleet-remote-{engine_id}")
        # ISSUE 17: a takeover must ``attach()`` every adopted rid
        # BEFORE the poller replays the stream/out history — a poller
        # racing ahead drops the early stream records (rid unknown yet)
        # and the completion's tail replay then double-fires the rest.
        # defer_poll=True holds the replay until start_polling().
        if not defer_poll:
            self._thread.start()

    def start_polling(self):
        """Start the deferred history replay (after takeover attach)."""
        if not self._thread.is_alive():
            try:
                self._thread.start()
            except RuntimeError:
                pass   # already started once

    # ---- router handle surface -----------------------------------------
    def healthy(self):
        if self.forced_down:
            return False
        rec = self._record()
        return rec is not None and rec.get("role") != "gone"

    def load(self):
        rec = self._record() or {}
        return int(rec.get("queue_depth", 0)) \
            + int(rec.get("active_slots", 0))

    def occupancy(self):
        rec = self._record() or {}
        return float(rec.get("kv_occupancy_pct", 0.0))

    def _record(self):
        if self.registry is None:
            return {"role": self.role}
        ts, rec = self._rec_cache
        now = time.monotonic()
        if now - ts < self._rec_ttl:
            return rec
        rec = self.registry.engines().get(self.engine_id)
        self._rec_cache = (now, rec)
        return rec

    def submit(self, leg):
        """Ship one router leg (a GenerationRequest OR a prebuilt
        _RemoteLeg-shaped object) to the engine process."""
        # the wire rid is STABLE per leg object: a retry after a submit
        # timeout re-enqueues the same rid, and the server's finished
        # cache / inflight check dedupes it instead of generating twice
        rid = getattr(leg, "_wire_rid", None)
        if rid is None:
            rid = f"{self.engine_id}-{id(leg)}-{time.monotonic_ns()}"
            leg._wire_rid = rid
        remote = _RemoteLeg(rid, leg.prompt_ids,
                            on_token=leg.on_token, on_done=leg.on_done)
        remote._wire_rid = rid
        remote._handle_id = self.engine_id
        fl = getattr(leg, "_fleet", None)
        remote._fleet = fl
        # re-point the fleet request at the wire-side leg that will
        # actually stream/finish — in the SAME slot the original leg
        # held (a hedge duplicate must never displace the primary)
        if getattr(leg, "_hedge_base", None) is not None:
            remote._hedge_base = leg._hedge_base
            if fl is not None and fl._hedge is leg:
                fl._hedge = remote
        elif fl is not None and fl._leg is leg:
            fl._leg = remote
        msg = {"rid": rid, "prompt": list(leg.prompt_ids),
               "max_new_tokens": leg.max_new_tokens,
               "eos_token_id": leg.eos_token_id,
               "temperature": leg.temperature, "top_k": leg.top_k}
        trace = getattr(leg, "trace", None)
        if trace is not None:
            # the trace context crosses the store-RPC wire: the engine
            # process stamps its spans under the SAME trace id
            remote.trace = trace
            msg["trace"] = trace
        with self._lock:
            self._pending[rid] = remote
        seq = int(self._submit_store.add(f"{self._prefix}/in_seq", 1))
        self._submit_store.set(f"{self._prefix}/in/{seq}",
                               json.dumps(msg))
        return remote

    def attach(self, rid, prompt, on_token=None, on_done=None,
               fleet=None, skip=0):
        """Adopt an in-flight wire leg after a router takeover (ISSUE
        17): register the DEPOSED router's wire rid with this handle so
        the poller's history replay (stream from seq 0, then the
        completion) rebuilds the token list — surfacing only tokens
        beyond ``skip``, the ledger's persisted cursor. No store write:
        the engine process never learns the router changed."""
        remote = _RemoteLeg(rid, prompt, on_token=on_token,
                            on_done=on_done, skip=skip)
        remote._handle_id = self.engine_id
        remote._fleet = fleet
        remote._wire_rid = rid
        with self._lock:
            self._pending[rid] = remote
        return remote

    def abort(self, leg):
        """Silently cancel one in-flight leg (hedge loser). Dropping the
        rid from ``_pending`` FIRST makes any late completion or stream
        record for it a no-op — the caller owns the pending decrement
        exactly when this returns True."""
        rid = leg.request_id
        with self._lock:
            if self._pending.pop(rid, None) is None:
                return False   # already completed: on_done owns it
        try:
            seq = int(self._submit_store.add(f"{self._prefix}/in_seq",
                                             1))
            self._submit_store.set(
                f"{self._prefix}/in/{seq}",
                json.dumps({"rid": rid, "abort": True}))
        except Exception:
            pass   # the engine still frees it at completion
        leg.state = "aborted"
        return True

    def start(self):
        pass  # the engine process runs its own serve loop

    def detach(self):
        """Stop this handle's poller WITHOUT stopping the engine
        process: a deposed or retiring router must leave the fleet
        running for whoever routes next (ISSUE 17)."""
        self._stop.set()

    def close(self):
        self._stop.set()
        try:
            self._submit_store.set(f"{self._prefix}/stop", b"1")
        except Exception:
            pass

    # ---- completion poller ---------------------------------------------
    def _poll_loop(self):
        consumed = 0
        tok_consumed = 0
        tick = 0
        stale = 0
        while not self._stop.is_set():
            tick += 1
            try:
                # token stream FIRST: within one tick a leg's streamed
                # tokens surface before its completion, so the
                # completion's replay tail is empty in the common case
                thead = int(self._poll_store.add(
                    f"{self._stream_prefix}/tok_seq", 0))
                while tok_consumed < thead:
                    tok_consumed += 1
                    rec = json.loads(self._poll_store.get(
                        f"{self._stream_prefix}/tok/{tok_consumed}",
                        timeout=10))
                    for rid, tokens, fin in rec.get("items", []):
                        with self._lock:
                            leg = self._pending.get(rid)
                        if leg is not None:
                            leg._stream(tokens, fin)
                head = int(self._poll_store.add(
                    f"{self._prefix}/out_seq", 0))
                while consumed < head:
                    consumed += 1
                    rec = json.loads(self._poll_store.get(
                        f"{self._prefix}/out/{consumed}", timeout=10))
                    with self._lock:
                        leg = self._pending.pop(rec.get("rid"), None)
                    if leg is not None:
                        leg._complete(rec)
            except Exception:
                pass  # store hiccup: retry next tick
            # engine-loss sweep: a killed worker process publishes
            # nothing, so its in-flight legs would wait forever — when
            # the registry heartbeat goes stale for several consecutive
            # checks, fail them with the retryable EngineClosed verdict
            # (the router's on_done re-dispatch picks them up)
            if self.registry is not None and tick % 25 == 0 \
                    and self._pending:
                stale = 0 if self.healthy() else stale + 1
                if stale >= 3:
                    stale = 0
                    with self._lock:
                        legs, self._pending = \
                            list(self._pending.values()), {}
                    err = {"type": "EngineClosed",
                           "msg": f"remote engine {self.engine_id} "
                                  "lost (heartbeat stale)"}
                    for leg in legs:
                        leg._complete({"rid": leg.request_id,
                                       "tokens": leg.generated,
                                       "error": err})
            self._stop.wait(self._poll_s)


def main(argv=None):
    """Engine-process entry: build the (seeded, fleet-identical) model,
    serve it over the store, publish labeled metrics."""
    p = argparse.ArgumentParser(prog="paddle_tpu.serving.fleet.remote")
    p.add_argument("--store", required=True, help="host:port")
    p.add_argument("--engine-id", required=True)
    p.add_argument("--job", default="fleet")
    p.add_argument("--role", default="any",
                   choices=["any", "prefill", "decode"])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--vocab", type=int, default=4096)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--kv-heads", type=int, default=None)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--page", type=int, default=8)
    p.add_argument("--pool", type=int, default=96)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--chunk", type=int, default=None)
    p.add_argument("--share", action="store_true",
                   help="cross-engine prefix-page sharing via the store")
    p.add_argument("--metrics-dir", default=None)
    p.add_argument("--rank", type=int, default=0)
    p.add_argument("--ttl", type=float, default=5.0)
    p.add_argument("--idle-timeout", type=float, default=300.0)
    p.add_argument("--trace-dir", default=None,
                   help="enable request tracing; export "
                        "trace.<engine-id>.json here (ISSUE 20)")
    p.add_argument("--trace-sample", type=float, default=None,
                   help="tail-sampling keep rate (PADDLE_TPU_TRACE_"
                        "SAMPLE) for uninteresting traces")
    p.add_argument("--trace-slow-ms", type=float, default=None,
                   help="keep traces slower than this e2e threshold")
    args = p.parse_args(argv)

    import paddle_tpu as paddle
    from paddle_tpu.distributed.tcp_store import TCPStore
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.observability import metrics as obsm
    from paddle_tpu.serving import ServingEngine
    from .page_share import PageShareClient
    from .registry import EngineRegistry

    host, _, port = args.store.rpartition(":")
    store = TCPStore(host or "127.0.0.1", int(port), is_master=False)
    reg = None
    if args.metrics_dir:
        reg = obsm.enable(out_dir=args.metrics_dir, interval_s=0,
                          rank=args.rank)
    tracing = None
    if args.trace_dir:
        # sampling knobs must be in the environment BEFORE start():
        # the buffer resolves them once, at construction
        if args.trace_sample is not None:
            os.environ["PADDLE_TPU_TRACE_SAMPLE"] = \
                str(args.trace_sample)
        if args.trace_slow_ms is not None:
            os.environ["PADDLE_TPU_TRACE_SLOW_MS"] = \
                str(args.trace_slow_ms)
        from paddle_tpu.observability import tracing
        tracing.start(path=os.path.join(
            args.trace_dir, f"trace.{args.engine_id}.json"),
            rank=args.rank)

    paddle.seed(args.seed)
    cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                    num_layers=args.layers, num_heads=args.heads,
                    num_kv_heads=args.kv_heads, max_seq_len=args.seq,
                    dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    share = None
    if args.share:
        share = PageShareClient(TCPStore(host or "127.0.0.1", int(port)),
                                args.engine_id, job=args.job)
    eng = ServingEngine(model, page_size=args.page, num_pages=args.pool,
                        max_slots=args.slots, prefill_chunk=args.chunk,
                        engine_id=args.engine_id, page_share=share,
                        registry=reg)
    eng.warm_ragged()
    eng.generate([1, 2, 3], max_new_tokens=2)  # warm the short tail too
    eng.start()

    registry = EngineRegistry(TCPStore(host or "127.0.0.1", int(port)),
                              job=args.job, ttl=args.ttl)
    registry.register(args.engine_id, engine=eng, role=args.role)
    print(f"[fleet] engine {args.engine_id} serving "
          f"(job={args.job}, role={args.role})", flush=True)
    try:
        serve_over_store(eng, store, args.engine_id, job=args.job,
                         registry=registry, role=args.role,
                         idle_timeout=args.idle_timeout)
    finally:
        try:
            eng.shutdown(drain_s=10.0)
        except Exception:
            pass
        registry.publish(args.engine_id, eng, args.role)  # final stats
        registry.close()
        if reg is not None:
            reg.flush()
        if tracing is not None:
            tracing.stop()   # export trace.<engine-id>.json
    print(f"[fleet] engine {args.engine_id} stopped", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
