# tpu-lint: hot-path
"""Elastic autoscaling — the serving fleet grows and shrinks itself.

The control loop of ISSUE 16: engines stop being a fixed roster wired
up at launch and become elastic control-plane members, exactly like
training hosts under ``elastic.ElasticManager``:

* **SLO-driven scaling** — each ``tick()`` reads the router's own
  dispatch-tier signals (per-engine queue depth blended with the
  router's unacknowledged in-flight count, plus the oldest in-flight
  request's TTFT/ITL stall age) and scales UP when the fleet is behind
  its SLO, DOWN when it has been idle — inside ``min_engines`` /
  ``max_engines`` bounds;
* **hysteresis + cooldown** — a scale decision needs the signal to hold
  for ``up_ticks``/``down_ticks`` consecutive ticks AND ``cooldown_s``
  since the last scale event, so an arrival burst's edge cannot flap
  the roster (scale-up reacts faster than scale-down on purpose: adding
  capacity late costs latency, removing it late costs only an idle
  engine);
* **warm-spare admission** — a new engine is built by the caller's
  ``spawn(engine_id)`` factory, ``warm_ragged()``-compiled and
  ``start()``-ed BEFORE it enters the router's rotation, so the first
  request it receives prefills immediately instead of paying the
  compile;
* **death → quarantine → replacement** — a crashed engine (serve-loop
  error, lost heartbeat) is struck into the fleet's
  :class:`~paddle_tpu.distributed.elastic.QuarantineList`, reaped from
  the rotation (its legs already re-dispatched through ``on_done``),
  and — when the live count fell below ``min_engines`` — replaced
  immediately, skipping hysteresis. Quarantined ids are never reused
  for replacements within the strike window;
* **membership survives failover** — the quarantine ledger and the
  autoscaler's roster epoch persist through the
  :class:`~.registry.EngineRegistry` under registry-scope keys
  (``serving/<job>/quarantine``, ``serving/<job>/autoscale``), which
  ride the FailoverStore WAL: a promoted standby store still knows who
  is struck out and how big the fleet meant to be;
* **hedging rides the tick** — ``tick()`` drives
  ``router.hedge_sweep()``, so one periodic thread serves both control
  loops (stragglers are an SLO signal *and* a mitigation target).

The loop itself runs anywhere: ``start()`` spawns a daemon thread at
``interval_s``; tests call ``tick(now=...)`` directly for determinism.
"""
from __future__ import annotations

import threading
import time

from ...distributed.elastic import QuarantineList
from ...observability import tracing as _trc

__all__ = ["EngineAutoscaler"]


class EngineAutoscaler:
    """SLO feedback loop over a :class:`~.router.FleetRouter` roster."""

    def __init__(self, router, spawn, min_engines=1, max_engines=4,
                 registry=None, quarantine=None, id_prefix="a",
                 queue_high=6.0, queue_low=0.5, ttft_slo_s=None,
                 up_ticks=2, down_ticks=6, cooldown_s=3.0,
                 interval_s=0.5, warm=True):
        self.router = router
        self.spawn = spawn                  # engine_id -> ServingEngine
        self.min_engines = int(min_engines)
        self.max_engines = int(max_engines)
        self.registry = registry
        # threshold=1: a serve-loop crash is terminal for an engine
        # process (unlike a flaky training host, there is no transient
        # NIC blip to forgive) — one strike benches it for the window
        self.quarantine = quarantine if quarantine is not None \
            else QuarantineList(threshold=1)
        if registry is not None:
            # membership survives store failover: adopt whatever ledger
            # an earlier incarnation (or the pre-failover primary)
            # persisted before making any admission decision
            registry.load_quarantine(self.quarantine)
        self.id_prefix = str(id_prefix)
        # per-engine average of max(reported load, router pending) above
        # which the fleet is behind; below queue_low it is idle
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        # oldest in-flight stall (no first token yet, or no token since)
        # that counts as an SLO breach regardless of queue depth
        self.ttft_slo_s = None if ttft_slo_s is None else float(ttft_slo_s)
        self.up_ticks = int(up_ticks)
        self.down_ticks = int(down_ticks)
        self.cooldown_s = float(cooldown_s)
        self.interval_s = float(interval_s)
        self.warm = bool(warm)
        self.events = []                    # scale-event log (bench/tests)
        self.epoch = 0                      # bumps on every roster change
        self.spawn_failures = 0
        self._hi = 0                        # consecutive over-SLO ticks
        self._lo = 0                        # consecutive idle ticks
        self._last_scale = None             # perf_counter of last event
        self._next_id = 0
        self._struck = set()                # dead ids already blamed
        self._stop = threading.Event()
        self._thread = None

    # ------------------------------------------------------------ signals
    def _roster(self):
        """-> (healthy handles, dead engine ids)."""
        live, dead = [], []
        for eid, h in self.router.handles().items():
            try:
                ok = h.healthy()
            except Exception:
                ok = False
            (live if ok else dead).append(h if ok else eid)
        return live, dead

    def _pressure(self, live):
        """Per-engine average of the router's blended load signal."""
        if not live:
            return float("inf")             # zero capacity IS pressure
        total = 0.0
        for h in live:
            try:
                total += max(h.load(), h.pending)
            except Exception:
                total += h.pending
        return total / len(live)

    def _worst_stall_s(self, now):
        """Age of the most-stalled in-flight request: time since its
        last token (TTFT counts from submit) — the router-observed
        ITL/TTFT tail without per-request histogram plumbing."""
        with self.router._lock:
            frs = list(self.router._inflight.values())
        worst = 0.0
        for fr in frs:
            if fr.done():
                continue
            last = fr.token_times[-1] if fr.token_times else fr.t_submit
            worst = max(worst, now - last)
        return worst

    # ----------------------------------------------------------- lifecycle
    def _strike(self, eid):
        """Blame one dead engine: quarantine strike + reap + persist."""
        if eid in self._struck:
            return
        self._struck.add(eid)
        self.quarantine.record_failure(eid)
        self.router.drop_engine(eid)
        if self.registry is not None:
            try:
                self.registry.save_quarantine(self.quarantine)
            except Exception:
                pass

    def _pick_engine_id(self):
        """Next roster id, skipping live engines AND quarantined ids —
        a struck-out engine must not be re-admitted inside its window."""
        handles = self.router.handles()
        while True:
            eid = f"{self.id_prefix}{self._next_id}"
            self._next_id += 1
            if eid in handles or eid in self._struck \
                    or self.quarantine.is_quarantined(eid):
                continue
            return eid

    def _record_event(self, direction, eid, n_after, now):
        self.epoch += 1
        self._last_scale = now
        ev = {"t": time.time(), "dir": direction, "engine": eid,
              "n_engines": n_after, "epoch": self.epoch}
        self.events.append(ev)
        self.router.metrics.on_scale_event(direction, n_after)
        # fleet-lane trace mark: scale events land in the SAME merged
        # timeline as the request waterfalls, so "p99 spiked here"
        # lines up with "the roster shrank here" (no-op when off)
        _trc.add_complete(f"scale_{direction}", ev["t"], 0.0,
                          cat="fleet", args={"engine": eid,
                                             "n_engines": n_after})
        if self.registry is not None:
            try:
                self.registry.save_autoscale(
                    {"epoch": self.epoch, "n_engines": n_after,
                     "events": self.events[-16:]})
            except Exception:
                pass

    def scale_up(self, now=None):
        """Admit one warm spare. -> engine_id or None (at max / spawn
        failed / no id available)."""
        now = time.perf_counter() if now is None else now
        live, _ = self._roster()
        if len(live) >= self.max_engines:
            return None
        eid = self._pick_engine_id()
        try:
            engine = self.spawn(eid)
            if self.warm:
                # warm-spare admission: compile BEFORE rotation, so the
                # new engine's first real request never pays the jit
                try:
                    engine.warm_ragged()
                except Exception:
                    pass
            engine.start()
        except Exception:
            self.spawn_failures += 1
            return None
        self.router.add_engine(engine, engine_id=eid)
        self._record_event("up", eid, len(live) + 1, now)
        return eid

    def scale_down(self, now=None):
        """Drain the least-loaded engine out of rotation (its in-flight
        requests migrate). -> engine_id or None."""
        now = time.perf_counter() if now is None else now
        live, _ = self._roster()
        if len(live) <= self.min_engines:
            return None
        victim = min(live, key=lambda h: (max(h.load(), h.pending),
                                          h.engine_id))
        try:
            self.router.remove_engine(victim.engine_id, migrate=True)
        except Exception:
            return None
        self.router.drop_engine(victim.engine_id)
        self._record_event("down", victim.engine_id, len(live) - 1, now)
        return victim.engine_id

    # ---------------------------------------------------------------- tick
    def tick(self, now=None):
        """One control-loop pass. Returns the scale action taken
        ("up"/"down"/None). Deterministic under an injected ``now``."""
        now = time.perf_counter() if now is None else now
        self.router.hedge_sweep(now=now)
        live, dead = self._roster()
        for eid in dead:
            self._strike(eid)
        if dead:
            live, _ = self._roster()
        # death replacement skips hysteresis: running BELOW min_engines
        # is an availability hole, not a load trend to be smoothed
        if len(live) < self.min_engines:
            return "up" if self.scale_up(now=now) else None
        in_cooldown = self._last_scale is not None \
            and now - self._last_scale < self.cooldown_s
        pressure = self._pressure(live)
        stalled = self.ttft_slo_s is not None \
            and self._worst_stall_s(now) > self.ttft_slo_s
        if pressure > self.queue_high or stalled:
            self._hi += 1
            self._lo = 0
            if self._hi >= self.up_ticks and not in_cooldown:
                self._hi = 0
                return "up" if self.scale_up(now=now) else None
        elif pressure < self.queue_low:
            self._lo += 1
            self._hi = 0
            if self._lo >= self.down_ticks and not in_cooldown:
                self._lo = 0
                return "down" if self.scale_down(now=now) else None
        else:
            self._hi = 0
            self._lo = 0
        return None

    # ------------------------------------------------------------- thread
    def start(self):
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="fleet-autoscale")
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                pass  # one bad tick must not kill the control loop

    def close(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
