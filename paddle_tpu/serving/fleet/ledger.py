# tpu-lint: hot-path
"""Durable request ledger + router lease — the fleet's exactly-once spine.

ISSUE 17: every bit of front-door state used to live in one
:class:`~.router.FleetRouter` process — pending legs, affinity, hedge
bookkeeping, streamed-token cursors — so a router death orphaned every
in-flight request on every engine. This module journals each request's
lifecycle into the control-plane store under registry-scope keys
(:func:`~paddle_tpu.distributed.keyspace.fleet_ledger`), which ride the
FailoverStore WAL exactly like fleet membership does: a promoted standby
store still holds the journal, and a shadow router reconstructs the
front door from it.

**Record lifecycle** (one JSON record per request id, last-write-wins —
the leased router is the single writer)::

    accepted ──▶ dispatched(engine, leg) ──▶ streaming(cursor, tokens)
                                                  └──▶ done | failed

**Exactly-once contract** (client-supplied request ids are the
idempotency key, end to end — the same id dedupes in this ledger AND in
the engine-side store-RPC server):

* resubmitting a **terminal** id replays the recorded result —
  byte-identical tokens or the same typed error — without touching any
  engine;
* resubmitting an **in-flight** id attaches the caller to the live leg
  (same ``FleetRequest``), never double-generating;
* after a router failover the shadow adopts every non-terminal record:
  it re-attaches to engines' live legs through the store-RPC streams,
  replaying only each request's unstreamed tail off the persisted
  ``cursor`` (the deposed router already surfaced ``tokens[:cursor]``
  to the client), and re-dispatches legs whose engine died with the
  router.

**Dispatch-path cost is deliberate**: ``lookup`` + ``accept`` +
``dispatched`` are one store round-trip each on the submit path — that
is the durability the exactly-once contract is made of, so the writes
carry reasoned tpu-lint suppressions instead of being hidden off-path.
Token-cursor updates are NOT per-token: the router's sweep batches them
(one write per changed request per sweep tick).

:class:`RouterLease` is the serving twin of the coordinator lease in
``launch/main.py``: the term counter is the fence — a shadow adopting
the front door bumps it, and every later renewal by the deposed router
raises :class:`RouterDeposedError` (named exit ``EXIT_DEPOSED``/76,
same as a deposed coordinator).
"""
from __future__ import annotations

import json
import os
import threading
import time

from ...distributed import keyspace
from ..scheduler import EngineClosed, EngineShuttingDown, QueueFull

__all__ = ["RequestLedger", "RouterLease", "RouterDeposedError",
           "TERMINAL_STATES"]

TERMINAL_STATES = ("done", "failed")

# typed errors cross the ledger the same way they cross the store-RPC
# wire: retryability-preserving reconstruction on replay
_ERRORS = {"QueueFull": QueueFull,
           "EngineShuttingDown": EngineShuttingDown,
           "EngineClosed": EngineClosed}


class RouterDeposedError(RuntimeError):
    """This router's lease term was superseded: a shadow adopted the
    front door while this instance was presumed dead. The holder must
    stop dispatching (exit ``EXIT_DEPOSED``) instead of split-braining
    the fleet — its ledger writes would race the adopter's."""


def rebuild_error(err):
    """Recorded ``{"type", "msg"}`` -> the typed exception instance."""
    if err is None:
        return None
    cls = _ERRORS.get(err.get("type"), RuntimeError)
    return cls(err.get("msg", "recorded request error"))


class RequestLedger:
    """Journal request lifecycles under ``serving/<job>/ledger/...``.

    One store client, many callers (router dispatch threads, engine
    completion callbacks, the sweep) — ops serialize behind one lock,
    the same rule :class:`~.registry.EngineRegistry` follows.
    """

    def __init__(self, store, job="fleet"):
        self.store = store
        self.job = str(job)
        self._prefix = keyspace.fleet_ledger(self.job)
        self._store_lock = threading.Lock()
        self._idx_cache = {}     # join-log idx -> rid (immutable)

    def _k(self, *parts):
        return "/".join((self._prefix,) + parts)

    # ----------------------------------------------------------- records
    def _write(self, rid, rec):
        with self._store_lock:
            # the lock only serializes this one store client; no
            # router/engine lock is ever taken inside it
            self.store.set(self._k("req", str(rid)), json.dumps(rec))

    def lookup(self, rid):
        """Latest record for one request id (None = never accepted)."""
        key = self._k("req", str(rid))
        try:
            with self._store_lock:
                if not self.store.check(key):
                    return None
                raw = self.store.get(key, timeout=10)
            return json.loads(raw)
        except Exception:
            return None

    @staticmethod
    def _base_record(fr):
        rec = {"rid": str(fr.request_id),
               "prompt": [int(t) for t in fr.prompt_ids],
               "max_new_tokens": int(fr.max_new_tokens),
               "eos_token_id": fr.eos_token_id,
               "temperature": fr.temperature, "top_k": fr.top_k,
               "engine_id": fr.engine_id,
               "engine_ids": list(fr.engine_ids)}
        # trace context rides the journal so a shadow that adopts or
        # replays this request keeps stamping the SAME trace id — the
        # waterfall survives router failover (ISSUE 20)
        trace = getattr(fr, "trace", None)
        if trace is not None:
            rec["trace"] = trace
        return rec

    def accept(self, fr):
        """Journal admission (state ``accepted``) and append the rid to
        the join-log — the enumeration a shadow reconstructs from (the
        store has no key listing; same idiom as the engine registry).
        Call once per NEW rid: the submit path's ``lookup`` already
        proved novelty, so no existence re-check burns a round-trip."""
        rec = self._base_record(fr)
        rec.update(state="accepted", cursor=0, tokens=[], error=None)
        self._write(fr.request_id, rec)
        with self._store_lock:
            # join-log append: the durable enumeration record — a
            # dispatch-path round-trip by design
            idx = int(self.store.add(self._k("seq"), 1))
            self.store.set(self._k("idx", str(idx)),
                           str(fr.request_id))

    def dispatched(self, fr, engine_id, leg_rid=None):
        """Journal a placement: which engine, which engine-side leg id
        (the store-RPC wire rid for remote legs — the handle a shadow
        re-attaches to). Re-dispatches and hedge promotions re-journal
        with the new engine; ``cursor``/``tokens`` carry forward."""
        rec = self._base_record(fr)
        with fr._tok_lock:
            toks = [int(t) for t in fr.generated]
        rec.update(state="dispatched", engine_id=engine_id,
                   leg_rid=leg_rid, cursor=len(toks), tokens=toks,
                   error=None)
        self._write(fr.request_id, rec)

    def streaming(self, fr, tokens, leg_rid=None):
        """Journal the surfaced-token cursor (batched by the router's
        sweep — never per token). ``tokens`` is the full surfaced list:
        a shadow pre-seeds the client's view from it, so re-attachment
        replays only the unstreamed tail."""
        rec = self._base_record(fr)
        rec.update(state="streaming", leg_rid=leg_rid,
                   cursor=len(tokens),
                   tokens=[int(t) for t in tokens], error=None)
        self._write(fr.request_id, rec)

    def terminal(self, fr):
        """Journal the terminal state: full token list on success, the
        typed error on failure — the replayable result of record."""
        rec = self._base_record(fr)
        with fr._tok_lock:
            toks = [int(t) for t in fr.generated]
        err = fr.error
        rec.update(state="failed" if err is not None else "done",
                   cursor=len(toks), tokens=toks,
                   error=None if err is None else
                   {"type": type(err).__name__, "msg": str(err)},
                   queue_wait_s=fr.queue_wait_s,
                   evictions=fr.evictions)
        self._write(fr.request_id, rec)

    # --------------------------------------------------------- discovery
    def rids(self):
        """Every request id ever accepted, in acceptance order."""
        try:
            with self._store_lock:
                n = int(self.store.add(self._k("seq"), 0))
        except Exception:
            return []
        out = []
        for i in range(1, n + 1):
            rid = self._idx_cache.get(i)
            if rid is None:
                key = self._k("idx", str(i))
                try:
                    with self._store_lock:
                        if not self.store.check(key):
                            continue
                        rid = self.store.get(key, timeout=10).decode()
                except Exception:
                    continue
                self._idx_cache[i] = rid
            if rid not in out:
                out.append(rid)
        return out

    def inflight_records(self):
        """Every non-terminal record, acceptance order — the set a
        shadow router adopts at takeover."""
        out = []
        for rid in self.rids():
            rec = self.lookup(rid)
            if rec is not None and rec.get("state") not in TERMINAL_STATES:
                out.append(rec)
        return out


class RouterLease:
    """Primary/shadow lease for the serving front door.

    The same protocol as the coordinator lease in ``launch/main.py``:
    ``acquire()`` bumps the term counter (the fence) and publishes the
    lease JSON; ``beat()`` renews at ttl/3 and raises
    :class:`RouterDeposedError` the moment the term moved under us;
    ``adopt()`` is the shadow's takeover bump. ``stale_age()`` measures
    lease staleness on the WATCHER's monotonic clock since the last
    observed stamp change — never by differencing two hosts' wall
    clocks (NTP skew would depose a healthy primary on sight).
    """

    def __init__(self, store, job="fleet", ttl=3.0, router_id=None):
        self.store = store
        self.job = str(job)
        self.ttl = float(ttl)
        self.router_id = str(router_id) if router_id is not None \
            else f"router-{os.getpid()}"
        self.term = 0
        self._prefix = keyspace.fleet_router(self.job)
        self._next = 0.0
        self._lock = threading.Lock()
        # shadow-side staleness state (monotonic since last stamp change)
        self._last_ts = None
        self._fresh_at = None

    def _k(self, leaf):
        return f"{self._prefix}/{leaf}"

    def current_term(self):
        return int(self.store.add(self._k("term"), 0))

    def acquire(self):
        """Take the next term and publish the first lease (primary)."""
        # store round-trip outside the lock: the add is atomic in the
        # store, the lock only guards the local term/throttle fields
        new_term = int(self.store.add(self._k("term"), 1))
        with self._lock:
            self.term = new_term
        self.publish()
        return self.term

    # the shadow's takeover is the same bump — the names document intent
    adopt = acquire

    def publish(self):
        """Renew the lease NOW, with the deposed-term fence."""
        with self._lock:
            term = self.term
            self._next = time.monotonic() + self.ttl / 3.0
        cur = self.current_term()
        if cur != term:
            raise RouterDeposedError(
                f"router lease term moved {term} -> {cur}: a shadow "
                "adopted the front door while this router was presumed "
                "dead")
        self.store.set(self._k("lease"), json.dumps(
            {"term": term, "ts": time.time(), "pid": os.getpid(),
             "router_id": self.router_id}))

    def beat(self):
        """Throttled renewal (ttl/3 cadence): cheap no-op between
        beats, so the dispatch path can call it per submit."""
        if time.monotonic() < self._next:
            return
        self.publish()

    def read(self):
        """-> published lease dict, or None (no primary yet)."""
        key = self._k("lease")
        try:
            if not self.store.check(key):
                return None
            return json.loads(self.store.get(key, timeout=10))
        except Exception:
            return None

    def stale_age(self):
        """Seconds since the lease stamp last CHANGED, on this
        process's monotonic clock (None until a lease is seen)."""
        lease = self.read()
        if lease is None:
            return None
        ts = lease.get("ts")
        now = time.monotonic()
        if ts != self._last_ts or self._fresh_at is None:
            self._last_ts, self._fresh_at = ts, now
        return now - self._fresh_at
