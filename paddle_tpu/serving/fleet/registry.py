"""Engine registry — fleet membership/liveness over the TCPStore.

The serving twin of ``elastic.ElasticManager``'s host registry: every
engine replica registers under ``serving/<job>/...`` on the control-plane
store (a plain :class:`TCPStore` or a replicated
:class:`FailoverStore` — registry-scope keys ride the PR-10 WAL, so a
promoted standby already knows the fleet roster) and heartbeats one
JSON record per ``ttl/3`` carrying its load gauges (queue depth, active
slots, KV occupancy, prefix remote hits). The router/bench discover
engines through the join log (the store has no key enumeration — same
idiom as ``elastic.py``) and treat a stale heartbeat as engine loss.

ISSUE 16 adds the quarantine ledger: the autoscaler strikes flaky
engines into an ``elastic.QuarantineList`` and persists its
``to_dict()`` JSON under ``serving/<job>/quarantine`` — registry scope,
so the ledger rides the FailoverStore WAL and a struck-out engine stays
excluded across a store failover exactly like a flaky training node.
"""
from __future__ import annotations

import json
import os
import threading
import time
from ...distributed import keyspace

__all__ = ["EngineRegistry"]


class EngineRegistry:
    """Register/heartbeat/discover serving engines on one store."""

    def __init__(self, store, job="fleet", ttl=5.0):
        self.store = store
        self.job = str(job)
        self.ttl = float(ttl)
        self._prefix = keyspace.fleet_registry(self.job)
        self._beats = {}         # engine_id -> (stop event, thread)
        self._join_cache = {}    # join-log idx -> engine_id (immutable)
        # ONE store client, many callers (the heartbeat thread + every
        # router thread reading liveness): the native client is not
        # thread-safe, so all ops serialize behind this lock — the same
        # rule that gives RemoteEngineHandle separate clients per thread
        self._store_lock = threading.Lock()

    def _k(self, *parts):
        return "/".join((self._prefix,) + parts)

    def _set(self, key, value):
        with self._store_lock:
            return self.store.set(key, value)

    def _get(self, key, timeout=None):
        with self._store_lock:
            return self.store.get(key, timeout=timeout)

    def _add(self, key, n):
        with self._store_lock:
            return self.store.add(key, n)

    def _check(self, key):
        with self._store_lock:
            return self.store.check(key)

    # ------------------------------------------------------ registration
    def _stats_record(self, engine, role, extra=None):
        rec = {"ts": time.time(), "role": role,
               "pid": os.getpid()}
        if engine is not None:
            try:
                s = engine.scheduler
                rec["queue_depth"] = s.queue_depth()
                rec["active_slots"] = len(s.active)
                rec["kv_occupancy_pct"] = round(
                    engine.kv.occupancy_pct(), 2)
                rec["decode_tokens"] = engine._decode_tokens
                share = getattr(engine.prefix, "share", None)
                if share is not None:
                    rec["prefix_remote_hits"] = share.remote_hits
                    rec["prefix_remote_hit_tokens"] = \
                        share.remote_hit_tokens
                    rec["prefix_published_pages"] = share.published
            except Exception:
                pass
        if extra:
            rec.update(extra)
        return rec

    def register(self, engine_id, engine=None, role="any", extra=None,
                 heartbeat=True):
        """Announce one engine and (by default) start its heartbeat
        thread. Records ride the join log so discovery needs no key
        enumeration."""
        eid = str(engine_id)
        self.publish(eid, engine, role, extra)
        idx = self._add(self._k("join_seq"), 1)
        self._set(self._k("join", str(idx)), eid)
        if heartbeat:
            stop = threading.Event()

            def beat():
                while not stop.wait(self.ttl / 3):
                    try:
                        self.publish(eid, engine, role, extra)
                    except Exception:
                        return  # store gone: the fleet sees a stale beat
            t = threading.Thread(target=beat, daemon=True,
                                 name=f"fleet-beat-{eid}")
            t.start()
            self._beats[eid] = (stop, t)
        return eid

    def publish(self, engine_id, engine=None, role="any", extra=None):
        """One heartbeat/stats record (also callable directly for a
        final flush before exit)."""
        self._set(self._k("eng", str(engine_id)),
                  json.dumps(self._stats_record(engine, role, extra)))

    def deregister(self, engine_id):
        eid = str(engine_id)
        beat = self._beats.pop(eid, None)
        if beat is not None:
            beat[0].set()
        try:
            rec = {"ts": 0, "role": "gone"}
            self._set(self._k("eng", eid), json.dumps(rec))
        except Exception:
            pass

    def close(self):
        for eid in list(self._beats):
            self.deregister(eid)

    # ------------------------------------------------------- quarantine
    def save_quarantine(self, quarantine, now=None):
        """Persist the fleet's quarantine ledger (registry scope: the
        JSON rides the WAL to the standby store)."""
        self._set(keyspace.fleet_quarantine(self.job),
                  json.dumps(quarantine.to_dict(now)))

    def load_quarantine(self, quarantine, now=None):
        """Restore ``quarantine`` from the persisted ledger (no-op when
        none was ever saved). Ages re-anchor against ``now`` so a strike
        window survives the wall-clock gap of a failover. -> bool
        (whether a ledger existed)."""
        key = keyspace.fleet_quarantine(self.job)
        try:
            if not self._check(key):
                return False
            state = json.loads(self._get(key, timeout=5))
        except Exception:
            return False
        quarantine.restore(state, now)
        return True

    def save_autoscale(self, state):
        """Persist the autoscaler's roster epoch + scale-event tail
        (registry scope — a promoted standby store still knows the
        fleet's intended size)."""
        self._set(f"{keyspace.fleet_autoscale(self.job)}/state",
                  json.dumps(state))

    def load_autoscale(self):
        """-> persisted autoscaler state dict, or None."""
        key = f"{keyspace.fleet_autoscale(self.job)}/state"
        try:
            if not self._check(key):
                return None
            return json.loads(self._get(key, timeout=5))
        except Exception:
            return None

    # --------------------------------------------------------- discovery
    def joined(self):
        """Every engine id that ever registered, in join order."""
        try:
            n = int(self._add(self._k("join_seq"), 0))
        except Exception:
            return []
        out = []
        for i in range(1, n + 1):
            eid = self._join_cache.get(i)
            if eid is None:
                key = self._k("join", str(i))
                if not self._check(key):
                    continue
                eid = self._get(key).decode()
                self._join_cache[i] = eid
            if eid not in out:
                out.append(eid)
        return out

    def record(self, engine_id):
        """Latest heartbeat record for one engine (None if absent)."""
        key = self._k("eng", str(engine_id))
        try:
            if not self._check(key):
                return None
            return json.loads(self._get(key, timeout=5))
        except Exception:
            return None

    def engines(self, live_only=True):
        """-> {engine_id: record}; ``live_only`` filters on heartbeat
        freshness (within ttl) — the router's liveness verdict."""
        now = time.time()
        out = {}
        for eid in self.joined():
            rec = self.record(eid)
            if rec is None:
                continue
            if live_only and now - float(rec.get("ts", 0)) > self.ttl:
                continue
            out[eid] = rec
        return out
