"""paddle_tpu.serving.fleet — N engines, one serving system (ISSUE 14).

The millions-of-users tier over the PR 1-5/10 control plane: a
:class:`FleetRouter` front-end (session affinity, queue-depth-aware
balancing, backpressure propagation, engine-loss re-dispatch), fleet-wide
prefix-cache sharing through the TCPStore
(:class:`~.page_share.SharedPrefixCache` — system prompts prefill once
per FLEET), prefill/decode disaggregation with KV page migration
(:func:`~.disagg.migrate_request` — the Gemma-on-TPU serving topology,
arxiv 2605.25645), store-backed engine registration/liveness
(:class:`~.registry.EngineRegistry`) and a store-RPC transport for
multi-process fleets (:mod:`~.remote`).

ISSUE 16 makes the roster ELASTIC: :class:`~.autoscale.EngineAutoscaler`
grows/shrinks the fleet against router-observed SLO signals (warm-spare
admission, quarantine strikes for crashed engines, membership persisted
through store failover), the router hedges stragglers onto a second
engine (first finisher wins, loser aborted slot-and-pages-free), and the
store-RPC transport streams tokens incrementally instead of batching
them at completion.

ISSUE 17 makes the front door DURABLE: a :class:`~.ledger.RequestLedger`
journals every request lifecycle through the replicated store (client
request ids are exactly-once keys — a retried terminal id replays the
recorded result, an in-flight id attaches to the live leg), a
:class:`~.ledger.RouterLease` term-fences primary/shadow routers, and
:mod:`~.frontdoor` packages the pair as processes: a shadow adopts the
ledger on lease expiry, re-attaching to engines' live legs off the
persisted token cursors.

    from paddle_tpu.serving.fleet import FleetRouter
    router = FleetRouter()
    router.add_engine(engine_a, "e0")
    router.add_engine(engine_b, "e1")
    router.start()
    req = router.submit(prompt_ids, max_new_tokens=64)
    tokens = req.result(timeout=60)
"""
from .router import (  # noqa: F401
    FleetRequest, FleetRouter, FleetSaturated, LocalEngineHandle,
)
from .page_share import PageShareClient, SharedPrefixCache  # noqa: F401
from .disagg import MigrationFailed, migrate_request  # noqa: F401
from .registry import EngineRegistry  # noqa: F401
from .remote import RemoteEngineHandle, serve_over_store  # noqa: F401
from .autoscale import EngineAutoscaler  # noqa: F401
from .ledger import (  # noqa: F401
    RequestLedger, RouterDeposedError, RouterLease,
)
from .frontdoor import RouterClient, serve_router  # noqa: F401
