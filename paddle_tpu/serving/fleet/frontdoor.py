"""The durable front door — a replicated serving router over the store.

ISSUE 17's process half: :class:`~.router.FleetRouter` gained the
exactly-once machinery (ledger journaling, lease fencing, takeover
adoption); this module packages it as a primary/shadow PROCESS pair the
same way ``launch/main.py`` packages the coordinator:

* :class:`RouterClient` — the client side of the front-door wire
  protocol. Submissions ride an ``in_seq`` counter + ``in/<n>`` records
  under :func:`~paddle_tpu.distributed.keyspace.fleet_router` (the same
  counter-log idiom as the store-RPC engine protocol); results come
  back through the LEDGER, not a private reply key — the journal is the
  single source of truth, so a client survives a router swap without
  noticing: it polls ``req/<rid>``, surfaces the cursor's new tokens,
  and resubmits the SAME rid if the record goes quiet (idempotent by
  the exactly-once contract — a duplicate submission attaches, replays,
  or dedupes; it never double-generates).
* :func:`serve_router` — the routing loop: tail the submission log,
  dispatch through the router (ledger-journaled), beat the lease, run
  the hedge/ledger sweep. Carries the ``route`` chaos site:
  ``router_die`` SIGKILLs the process mid-dispatch (the shadow adopts),
  ``router_stall`` freezes the loop while the process lives (the lease
  goes stale, the shadow adopts, and the stalled primary's next beat
  hits the term fence).
* :func:`main` — CLI. ``--role primary`` acquires the lease and serves;
  ``--role shadow`` watches lease staleness on ITS OWN monotonic clock
  (never wall-clock differencing), then adopts: term bump (fences the
  deposed primary), fresh engine handles (their pollers replay the
  store-RPC history from seq 0), ledger adoption (re-attach live legs
  off the persisted cursors, re-dispatch orphans), and only then starts
  routing. A deposed router exits ``EXIT_DEPOSED`` (76) — the same
  yield-don't-split-brain contract as a deposed coordinator.

Worker entry point (used by ``bench.py --serving-fleet`` chaos leg)::

    python -m paddle_tpu.serving.fleet.frontdoor --store 127.0.0.1:6200 \
        --job bench --role primary [--engines e0,e1] [--ttl 1.0]
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
from collections import deque

from ...distributed import fault as _fault
from ...distributed import keyspace
from ...observability import tracing as _trc
from .ledger import (RequestLedger, RouterDeposedError, RouterLease,
                     TERMINAL_STATES, rebuild_error)
from .router import FleetRouter, FleetSaturated

__all__ = ["RouterClient", "serve_router", "main"]


class RouterClient:
    """Submit requests to whichever router holds the lease, and read
    results straight off the durable ledger.

    The client never learns which process routed it: submissions append
    to the shared wire log, results come from the journal. ``rid`` is
    the client's exactly-once key — pick it once per logical request
    and retry freely."""

    def __init__(self, store, job="fleet", poll_s=0.03,
                 resubmit_after=2.0):
        self.store = store
        self.job = str(job)
        self.poll_s = float(poll_s)
        # resubmit the rid after this long with NO record change: long
        # enough to ride out a takeover, short enough that a request
        # lost with a dead router's in-memory retry queue still lands
        self.resubmit_after = float(resubmit_after)
        self._prefix = keyspace.fleet_router(self.job)
        self._ledger_prefix = keyspace.fleet_ledger(self.job)
        self._lock = threading.Lock()
        self._sent = {}          # rid -> wire msg (for resubmission)

    def submit(self, rid, prompt_ids, max_new_tokens=16,
               eos_token_id=None, temperature=0.0, top_k=None,
               engine=None):
        """Enqueue one request under the client-chosen ``rid``.
        Calling this twice with the same rid is safe by design.
        ``engine=`` pins the request to one engine id (tests and warm
        benches); the trace context is minted HERE — the true front of
        the waterfall — and rides the wire msg so router/engine spans
        land under the same trace id (ISSUE 20)."""
        trace = _trc.mint_context()   # None when tracing is off
        t0 = time.time() if trace is not None else 0.0
        msg = {"rid": str(rid), "prompt": [int(t) for t in prompt_ids],
               "max_new_tokens": int(max_new_tokens),
               "eos_token_id": eos_token_id,
               "temperature": temperature, "top_k": top_k}
        if engine is not None:
            msg["engine"] = str(engine)
        if trace is not None:
            msg["trace"] = trace
        with self._lock:
            self._sent[str(rid)] = msg
        self._enqueue(msg)
        if trace is not None:
            _trc.req_event(trace, "client_submit", t0,
                           time.time() - t0,
                           args={"rid": str(rid),
                                 "prompt_tokens": len(msg["prompt"])})
        return str(rid)

    def _enqueue(self, msg):
        seq = int(self.store.add(f"{self._prefix}/in_seq", 1))
        self.store.set(f"{self._prefix}/in/{seq}", json.dumps(msg))

    def result(self, rid, timeout=60.0, on_token=None):
        """Block until ``rid`` reaches a terminal record; surface each
        cursor advance through ``on_token(token, fin)`` as it lands.
        Returns the full token list, or raises the recorded typed
        error. Resubmits the same rid whenever the record goes quiet —
        across a router failover this is what re-engages the new
        primary for a request the old one never journaled."""
        rid = str(rid)
        key = f"{self._ledger_prefix}/req/{rid}"
        deadline = time.monotonic() + float(timeout)
        surfaced = 0
        last_change = time.monotonic()
        last_raw = None
        while True:
            raw = None
            try:
                if self.store.check(key):
                    raw = self.store.get(key, timeout=10)
            except Exception:
                raw = None
            if raw is not None and raw != last_raw:
                last_raw = raw
                last_change = time.monotonic()
                rec = json.loads(raw)
                toks = rec.get("tokens") or []
                term = rec.get("state") in TERMINAL_STATES
                err = rec.get("error")
                if on_token is not None:
                    for i in range(surfaced, len(toks)):
                        try:
                            on_token(int(toks[i]),
                                     term and err is None
                                     and i == len(toks) - 1)
                        except Exception:
                            pass
                surfaced = max(surfaced, len(toks))
                if term:
                    e = rebuild_error(err)
                    if e is not None:
                        raise e
                    return [int(t) for t in toks]
            now = time.monotonic()
            if now >= deadline:
                raise TimeoutError(
                    f"request {rid!r} not terminal after {timeout}s")
            if now - last_change > self.resubmit_after:
                last_change = now
                with self._lock:
                    msg = self._sent.get(rid)
                if msg is not None:
                    try:
                        self._enqueue(msg)
                    except Exception:
                        pass
            time.sleep(self.poll_s)

    def generate(self, rid, prompt_ids, timeout=60.0, on_token=None,
                 **kw):
        """``submit`` + ``result`` in one call."""
        self.submit(rid, prompt_ids, **kw)
        return self.result(rid, timeout=timeout, on_token=on_token)


def serve_router(router, store, job="fleet", poll_s=0.03,
                 idle_timeout=None):
    """Route until the ``stop`` key appears (or ``idle_timeout`` passes
    with no traffic). Raises :class:`RouterDeposedError` the moment the
    lease term moves — the caller maps it to ``EXIT_DEPOSED``.

    The ``route`` chaos site fires once per DISPATCHED request (not per
    poll tick), so ``router_die@route:N`` deterministically kills the
    Nth routed request mid-burst."""
    prefix = keyspace.fleet_router(job)
    fleet_stop = f"{keyspace.fleet_registry(job)}/stop"
    consumed = 0
    retry = deque()              # saturated submissions await capacity
    tick = 0
    last_traffic = time.monotonic()
    last_sweep = 0.0
    while True:
        tick += 1
        if tick % 5 == 1 and (store.check(f"{prefix}/stop")
                              or store.check(fleet_stop)):
            return
        if idle_timeout is not None \
                and time.monotonic() - last_traffic > idle_timeout:
            return
        # the lease beat is the fence: a deposed router finds out here
        # (or inside submit's own _check_lease) and must stop routing
        if router.lease is not None:
            try:
                router.lease.beat()
            except RouterDeposedError:
                router.fence()
                raise
        head = int(store.add(f"{prefix}/in_seq", 0))
        while consumed < head:
            consumed += 1
            try:
                msg = json.loads(store.get(f"{prefix}/in/{consumed}",
                                           timeout=10))
            except Exception:
                continue  # torn submission: the client resubmits
            last_traffic = time.monotonic()
            retry.append(msg)
        for _ in range(len(retry)):
            msg = retry.popleft()
            k = _fault.maybe_inject("route")
            if k == "router_die":
                print(f"ROUTER_DIE {time.time():.6f}", flush=True)
                print("[fleet] injected router_die: SIGKILL self (the "
                      "shadow router adopts the ledger)",
                      file=sys.stderr, flush=True)
                sys.stdout.flush()
                sys.stderr.flush()
                os.kill(os.getpid(), signal.SIGKILL)
            try:
                router.submit(msg["prompt"],
                              max_new_tokens=int(
                                  msg.get("max_new_tokens", 16)),
                              eos_token_id=msg.get("eos_token_id"),
                              temperature=float(
                                  msg.get("temperature", 0.0)),
                              top_k=msg.get("top_k"), block=False,
                              request_id=msg.get("rid"),
                              engine=msg.get("engine"),
                              trace=msg.get("trace"))
            except FleetSaturated:
                retry.append(msg)   # every queue full: retry next tick
            except RouterDeposedError:
                raise
            except Exception:
                continue            # malformed submission: drop it
        now = time.monotonic()
        if now - last_sweep > 0.25:
            last_sweep = now
            try:
                # hedges + the ledger's batched cursor writes
                router.hedge_sweep()
            except Exception:
                pass
        time.sleep(poll_s)


def _build_handles(router, store_factory, registry, job, engine_ids):
    """Fresh RemoteEngineHandles for the given (or discovered) engine
    ids. Built at SERVE time on purpose: a fresh handle's poller
    replays the store-RPC stream/out history from seq 0, which is what
    re-attachment after a takeover feeds on.

    Handles are built with ``defer_poll=True``: the caller starts the
    pollers (``start_polling``) only AFTER ledger adoption has attached
    every inherited rid — a poller racing the attach would consume the
    early history records while their rid is still unknown and drop
    those tokens."""
    from .remote import RemoteEngineHandle
    recs = registry.engines(live_only=True)
    ids = engine_ids or sorted(recs)
    for eid in ids:
        role = (recs.get(eid) or {}).get("role", "any")
        router.add_engine(None, handle=RemoteEngineHandle(
            store_factory, eid, job=job, registry=registry, role=role,
            defer_poll=True))
    return ids


def main(argv=None):
    """Front-door process entry (primary or shadow)."""
    p = argparse.ArgumentParser(
        prog="paddle_tpu.serving.fleet.frontdoor")
    p.add_argument("--store", required=True, help="host:port")
    p.add_argument("--job", default="fleet")
    p.add_argument("--role", default="primary",
                   choices=["primary", "shadow"])
    p.add_argument("--engines", default="",
                   help="comma-separated engine ids "
                        "(default: discover live engines)")
    p.add_argument("--ttl", type=float, default=2.0,
                   help="router lease ttl (beat at ttl/3)")
    p.add_argument("--grace", type=float, default=None,
                   help="shadow adopts after the lease is stale this "
                        "long (default 3*ttl)")
    p.add_argument("--hedge-after", type=float, default=None)
    p.add_argument("--poll", type=float, default=0.03)
    p.add_argument("--idle-timeout", type=float, default=300.0)
    args = p.parse_args(argv)

    from ...distributed.tcp_store import TCPStore
    from .registry import EngineRegistry

    host, _, port = args.store.rpartition(":")
    host, port = host or "127.0.0.1", int(port)

    def store_factory():
        return TCPStore(host, port, is_master=False)

    store = store_factory()
    registry = EngineRegistry(store_factory(), job=args.job)
    ledger = RequestLedger(store_factory(), job=args.job)
    lease = RouterLease(store_factory(), job=args.job, ttl=args.ttl,
                        router_id=f"{args.role}-{os.getpid()}")
    router = FleetRouter(hedge_after_s=args.hedge_after, ledger=ledger,
                         lease=lease)
    engine_ids = [e for e in args.engines.split(",") if e]
    prefix = keyspace.fleet_router(args.job)
    fleet_stop = f"{keyspace.fleet_registry(args.job)}/stop"

    if args.role == "shadow":
        grace = args.grace if args.grace is not None else 3 * args.ttl
        print(f"[fleet] shadow router watching (job={args.job}, "
              f"grace={grace:.2f}s)", flush=True)
        while True:
            if store.check(f"{prefix}/stop") or store.check(fleet_stop):
                print("[fleet] shadow router stopped (never adopted)",
                      flush=True)
                return 0
            age = lease.stale_age()
            if age is not None and age > grace:
                break
            time.sleep(max(args.ttl / 3.0, 0.05))
        t0 = time.monotonic()
        term = lease.adopt()
        _build_handles(router, store_factory, registry, args.job,
                       engine_ids)
        adopted = router.adopt_from_ledger()
        # pollers start only now: every adopted rid is registered, so
        # the history replay surfaces each request's full tail exactly
        # once (see _build_handles)
        for h in router.handles().values():
            h.start_polling()
        adopt_s = time.monotonic() - t0
        router.metrics.on_router_failover(adopt_s)
        print(f"ROUTER_ADOPTED term={term} adopt_s={adopt_s:.3f} "
              f"adopted={adopted} replayed={router.requests_replayed} "
              f"wall={time.time():.6f}", flush=True)
    else:
        term = lease.acquire()
        _build_handles(router, store_factory, registry, args.job,
                       engine_ids)
        for h in router.handles().values():
            h.start_polling()   # nothing to adopt: start immediately
        print(f"ROUTER_PRIMARY term={term} wall={time.time():.6f}",
              flush=True)

    try:
        serve_router(router, store, job=args.job, poll_s=args.poll,
                     idle_timeout=args.idle_timeout)
    except RouterDeposedError as e:
        print(f"ROUTER_DEPOSED term={lease.term} wall={time.time():.6f}",
              flush=True)
        print(f"[fleet] router deposed: {e} "
              f"({_fault.describe_exit(_fault.EXIT_DEPOSED)})",
              file=sys.stderr, flush=True)
        return _fault.EXIT_DEPOSED
    finally:
        # detach, never close: closing a RemoteEngineHandle stops its
        # ENGINE, and this router exiting (deposed or stopped) must not
        # take the fleet down with it
        for h in router.handles().values():
            try:
                h.detach()
            except Exception:
                pass
    print(f"[fleet] router stopped (term={lease.term})", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
