"""paddle_tpu.serving — continuous-batching inference over paged KV.

The serving tier (SURVEY layer 11; ROADMAP items 2+3): ONE ragged paged
attention launch per scheduler round (mixed decode rows + prefill chunks
over a paged KV cache — no bucket-compile matrix; ``ragged=False`` keeps
the bucketed fixed-slot fallback), iteration-level scheduling between
rounds, streaming token callbacks, A/B-gated attention backends, and
Poisson open-loop load tooling for the bench.

    from paddle_tpu.serving import ServingEngine
    eng = ServingEngine(model, page_size=16, num_pages=128, max_slots=8)
    eng.start()
    req = eng.submit(prompt_ids, max_new_tokens=64,
                     on_token=lambda r, tok, fin: stream(tok))
    tokens = req.result(timeout=60)
"""
from .kv_cache import BlockAllocator, OutOfPages, PagedKVCache, pages_for  # noqa: F401
from .prefix_cache import PrefixCache  # noqa: F401
from .scheduler import (  # noqa: F401
    ContinuousBatchingScheduler, EngineClosed, EngineShuttingDown,
    GenerationRequest, OutOfSlots, QueueFull,
)
from .decode import (  # noqa: F401
    ab_compare, paged_decode_attention, paged_prefill_attention,
    resolve_backend, sharded_paged_attention, sharded_paged_prefill,
)
from .ragged_attention import (  # noqa: F401
    ab_compare_ragged, pad_total_tokens, ragged_paged_attention,
    sharded_ragged_attention,
)
from .engine import ServingEngine  # noqa: F401
from .metrics import ServingMetrics  # noqa: F401
from .load import (  # noqa: F401
    make_mixed_length_prompts, make_session_prompts,
    make_shared_prefix_prompts, run_poisson_load, summarize_requests,
)
# the fleet tier (router / page sharing / disaggregation) lives in the
# .fleet subpackage — imported lazily by ServingEngine(page_share=) and
# explicitly by fleet users, so single-engine serving pays nothing

