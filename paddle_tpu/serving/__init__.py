"""paddle_tpu.serving — continuous-batching inference over paged KV.

The serving tier (SURVEY layer 11; ROADMAP item 3): a fixed-slot decode
batch over a paged KV cache, iteration-level scheduling between decode
steps, streaming token callbacks, A/B-gated paged-attention backends, and
Poisson open-loop load tooling for the bench.

    from paddle_tpu.serving import ServingEngine
    eng = ServingEngine(model, page_size=16, num_pages=128, max_slots=8)
    eng.start()
    req = eng.submit(prompt_ids, max_new_tokens=64,
                     on_token=lambda r, tok, fin: stream(tok))
    tokens = req.result(timeout=60)
"""
from .kv_cache import BlockAllocator, OutOfPages, PagedKVCache, pages_for  # noqa: F401
from .prefix_cache import PrefixCache  # noqa: F401
from .scheduler import (  # noqa: F401
    ContinuousBatchingScheduler, EngineClosed, EngineShuttingDown,
    GenerationRequest, QueueFull,
)
from .decode import (  # noqa: F401
    ab_compare, paged_decode_attention, paged_prefill_attention,
    resolve_backend, sharded_paged_attention, sharded_paged_prefill,
)
from .engine import ServingEngine  # noqa: F401
from .metrics import ServingMetrics  # noqa: F401
from .load import (  # noqa: F401
    make_shared_prefix_prompts, run_poisson_load, summarize_requests,
)
