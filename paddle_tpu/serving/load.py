"""Poisson open-loop load generator — the serving yardstick harness.

Open-loop means arrivals follow a seeded Poisson process regardless of
how fast the engine drains them (closed-loop generators hide tail latency
by self-throttling; the Gemma-on-TPU serving study, arxiv 2605.25645, is
the external comparison this mirrors). Drives a running
:class:`~.engine.ServingEngine`, then reduces per-request timestamps into
the tokens/s + TTFT + inter-token tail numbers ``bench.py --serving``
records next to the training rows.
"""
from __future__ import annotations

import time

import numpy as np

__all__ = ["run_poisson_load", "summarize_requests",
           "make_shared_prefix_prompts", "make_mixed_length_prompts",
           "make_session_prompts"]


def _pct(values, q):
    return float(np.percentile(np.asarray(values, np.float64), q)) \
        if values else None


def summarize_requests(requests, wall_s, by_engine=False):
    """Reduce finished requests -> the bench row dict (times in ms).

    ``by_engine=True`` adds per-engine breakdown rows (requests that
    carry an ``engine_id`` — fleet-routed :class:`~.fleet.router.
    FleetRequest`\\ s do) so a fleet run shows WHERE the load landed:
    router balancing is only verifiable when no engine idles while
    another queues."""
    ok = [r for r in requests if r.error is None and r.t_done is not None]
    # never-finished requests (result() deadline hit, engine wedged) are
    # FAILURES — without this they vanish from both columns and a hung
    # run reads as healthy
    failed = [r for r in requests if r.error is not None
              or r.t_done is None]
    tokens = sum(len(r.generated) for r in ok)
    ttft = [r.ttft_s() * 1e3 for r in ok if r.ttft_s() is not None]
    itl = [dt * 1e3 for r in ok for dt in r.inter_token_s()]
    e2e = [(r.t_done - r.t_submit) * 1e3 for r in ok]
    # CUMULATIVE queue wait (pre-eviction segments included — an evicted
    # request's early waiting must not vanish from the tail attribution)
    qwait = [r.queue_wait_s * 1e3 for r in ok]
    out = {
        "requests_ok": len(ok),
        "requests_failed": len(failed),
        "tokens": tokens,
        "wall_s": round(wall_s, 3),
        "tokens_per_sec": round(tokens / wall_s, 2) if wall_s > 0 else 0.0,
        "qps_completed": round(len(ok) / wall_s, 2) if wall_s > 0 else 0.0,
        "ttft_ms_p50": _pct(ttft, 50),
        "ttft_ms_p99": _pct(ttft, 99),
        "itl_ms_p50": _pct(itl, 50),
        "itl_ms_p99": _pct(itl, 99),
        "e2e_ms_p50": _pct(e2e, 50),
        "e2e_ms_p99": _pct(e2e, 99),
        "queue_wait_ms_p50": _pct(qwait, 50),
        "queue_wait_ms_p99": _pct(qwait, 99),
        "evictions": sum(r.evictions for r in requests),
        "requests_evicted": sum(1 for r in requests if r.evictions > 0),
    }
    for k, v in list(out.items()):
        if isinstance(v, float) and v is not None and k.endswith(
                ("p50", "p99")):
            out[k] = round(v, 2)
    if by_engine:
        groups = {}
        for r in requests:
            eid = getattr(r, "engine_id", None)
            groups.setdefault(eid if eid is not None else "?",
                              []).append(r)
        rows = {}
        for eid, reqs in sorted(groups.items()):
            g_ok = [r for r in reqs if r.error is None
                    and r.t_done is not None]
            g_ttft = [r.ttft_s() * 1e3 for r in g_ok
                      if r.ttft_s() is not None]
            g_itl = [dt * 1e3 for r in g_ok for dt in r.inter_token_s()]
            rows[eid] = {
                "requests_ok": len(g_ok),
                "requests_failed": len(reqs) - len(g_ok),
                "tokens": sum(len(r.generated) for r in g_ok),
                "ttft_ms_p99": _pct(g_ttft, 99),
                "itl_ms_p99": _pct(g_itl, 99),
                "redispatches": sum(getattr(r, "redispatches", 0)
                                    for r in reqs),
                "migrations": sum(getattr(r, "migrations", 0)
                                  for r in reqs),
            }
        out["by_engine"] = rows
    return out


def make_shared_prefix_prompts(n_requests, prompt_len, vocab,
                               shared_prefix, seed=0):
    """The ``shared_prefix`` workload: ONE common system-prompt head of
    ``shared_prefix`` tokens (drawn once from the seed) followed by a
    per-request random tail of length in ``prompt_len`` — the realistic
    mix that drives a prefix cache (every production deployment fronts
    requests with the same system prompt). Deterministic per seed, so a
    prefix-cache engine and its cold twin see identical prompts."""
    rng = np.random.RandomState(seed)
    head = rng.randint(1, vocab, size=int(shared_prefix)).tolist()
    lo, hi = prompt_len
    return [head + rng.randint(1, vocab,
                               size=rng.randint(lo, hi + 1)).tolist()
            for _ in range(n_requests)]


def make_mixed_length_prompts(n_requests, prompt_len, vocab,
                              decode_heavy=0.5, max_new_tokens=(4, 24),
                              seed=0):
    """The ragged stress workload (ISSUE 13): prompt lengths drawn
    **log-uniform** over ``prompt_len=(lo, hi)`` — the long-tailed mix
    where a bucketed engine pads worst (most prompts are short, the
    bucket grid is sized for the long tail) — with a
    ``decode_heavy``-probability knob: a decode-heavy request keeps its
    prompt at the short end (capped at the geometric midpoint) and
    generates ``max_new_tokens[1]`` tokens; a prefill-heavy request
    keeps its log-uniform length and generates only ``max_new_tokens[0]``.
    Deterministic per seed, so the ragged engine and its bucketed twin
    see identical load. -> ``(prompts, max_new_tokens_per_request)``."""
    rng = np.random.RandomState(seed)
    lo, hi = int(prompt_len[0]), int(prompt_len[1])
    if not 1 <= lo <= hi:
        raise ValueError(f"prompt_len {prompt_len!r} must be 1 <= lo <= hi")
    mid = int(np.sqrt(lo * hi))
    n_lo, n_hi = int(max_new_tokens[0]), int(max_new_tokens[1])
    prompts, news = [], []
    for _ in range(int(n_requests)):
        ln = int(round(np.exp(rng.uniform(np.log(lo), np.log(hi)))))
        ln = min(max(ln, lo), hi)
        if rng.rand() < decode_heavy:
            ln, new = min(ln, max(mid, lo)), n_hi
        else:
            new = n_lo
        prompts.append(rng.randint(1, vocab, size=ln).tolist())
        news.append(new)
    return prompts, news


def make_session_prompts(n_sessions, requests_per_session, head_len,
                         tail_len, vocab, seed=0, interleave=True):
    """The FLEET workload (ISSUE 14): ``n_sessions`` sessions, each with
    its own ``head_len``-token head shared by that session's
    ``requests_per_session`` requests (per-request random tails of
    length in ``tail_len``), arrivals interleaved round-robin across
    sessions — affinity has to hold mid-stream with other sessions'
    requests landing in between, and a session spilling to a second
    engine exercises cross-engine prefix sharing on the SAME seeded
    workload. Deterministic per seed. -> ``(prompts, session_ids)``."""
    rng = np.random.RandomState(seed)
    lo, hi = tail_len
    heads = [rng.randint(1, vocab, size=int(head_len)).tolist()
             for _ in range(int(n_sessions))]
    per = [[heads[s] + rng.randint(
        1, vocab, size=rng.randint(lo, hi + 1)).tolist()
        for _ in range(int(requests_per_session))]
        for s in range(int(n_sessions))]
    if interleave:
        prompts = [per[s][r] for r in range(int(requests_per_session))
                   for s in range(int(n_sessions))]
        sids = [s for _ in range(int(requests_per_session))
                for s in range(int(n_sessions))]
    else:
        prompts = [p for sess in per for p in sess]
        sids = [s for s in range(int(n_sessions))
                for _ in range(int(requests_per_session))]
    return prompts, sids


def run_poisson_load(engine, n_requests=32, qps=10.0, prompt_len=(8, 24),
                     max_new_tokens=12, eos_token_id=None, seed=0,
                     timeout=300.0, shared_prefix=None, prompts=None,
                     by_engine=False):
    """Submit ``n_requests`` at Poisson arrivals of rate ``qps`` (prompts
    are uniform-random token ids of uniform-random length in
    ``prompt_len``), wait for completion, -> summary dict. The engine
    must be ``start()``ed (open loop: submission never waits on decode).
    Backpressure turns into measured queue wait, not dropped load — the
    submit timeout is sized to the whole run.

    ``shared_prefix=N`` switches to the shared-system-prompt workload:
    every prompt is one common ``N``-token head plus the random tail
    (:func:`make_shared_prefix_prompts`), so the engine's prefix cache —
    when enabled — sees a realistic hit mix; ``prompt_len`` then sizes
    the per-request tail.

    ``prompts=`` overrides generation entirely (a pre-built workload like
    :func:`make_mixed_length_prompts`); ``max_new_tokens`` may then be a
    per-request sequence of the same length."""
    rng = np.random.RandomState(seed)
    vocab = engine.cfg.vocab_size
    lo, hi = prompt_len
    if prompts is not None:
        n_requests = len(prompts)
    gaps = rng.exponential(1.0 / qps, size=n_requests)
    if prompts is None and shared_prefix:
        prompts = make_shared_prefix_prompts(
            n_requests, prompt_len, vocab, shared_prefix, seed=seed)
    per_req_new = max_new_tokens if hasattr(max_new_tokens, "__len__") \
        else [max_new_tokens] * n_requests
    if len(per_req_new) != n_requests:
        raise ValueError(
            f"max_new_tokens sequence has {len(per_req_new)} entries for "
            f"{n_requests} requests")
    requests = []
    t_start = time.perf_counter()
    for i in range(n_requests):
        target = t_start + float(gaps[:i + 1].sum())
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        prompt = prompts[i] if prompts is not None else \
            rng.randint(1, vocab, size=rng.randint(lo, hi + 1)).tolist()
        req = engine.submit(list(prompt),
                            max_new_tokens=int(per_req_new[i]),
                            eos_token_id=eos_token_id, timeout=timeout)
        requests.append(req)
    deadline = time.perf_counter() + timeout
    for req in requests:
        left = max(0.1, deadline - time.perf_counter())
        try:
            req.result(timeout=left)
        except Exception:
            pass  # summarized as failed below
    wall_s = time.perf_counter() - t_start
    out = summarize_requests(requests, wall_s, by_engine=by_engine)
    out["qps_offered"] = float(qps)
    out["n_requests"] = int(n_requests)
    return out
