"""Paged-attention decode step — XLA reference path, Pallas variant, A/B gate.

One decode step attends ONE query token per sequence against that
sequence's pages of the shared KV pool (Ragged Paged Attention,
arxiv 2604.15464). Two interchangeable backends:

* ``xla`` — :func:`paged_attention_reference` (ops/pallas/paged_attention):
  a pure-jnp gather formulation XLA compiles on any device. Always correct;
  the baseline every kernel must beat.
* ``pallas`` — the scalar-prefetch Pallas kernel (same module): the page
  table rides scalar prefetch so the DMA streams exactly the pages a
  sequence owns. TPU-only (interpret mode is not a measurement).

The **A/B gate** enforces the standing kernel rule (ROADMAP item 1): the
Pallas path is used only where its measured time beats the XLA reference
at the serving shape — :func:`ab_compare` times both and
:func:`resolve_backend` turns ``auto`` into a decision, recorded by
``bench.py --serving`` as ``serving_paged_attn_{xla,pallas}_ms``.
``PADDLE_TPU_SERVING_ATTN=xla|pallas|auto`` overrides.

Multi-chip serving shards along **KV heads** over the fleet mesh's
``model`` axis (SNIPPETS.md [2] ``sharded_paged_attention``):
:func:`sharded_paged_attention` wraps either backend in ``shard_map`` with
the head dim partitioned; block tables and context lens replicate.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.pallas import _common as _gate
from ..ops.pallas._common import on_tpu
from ..ops.pallas.paged_attention import (
    paged_attention as _pallas_paged_attention,
    paged_attention_reference as _xla_paged_attention,
    paged_prefill_reference as _xla_paged_prefill,
)

__all__ = ["paged_decode_attention", "paged_prefill_attention",
           "sharded_paged_attention", "sharded_paged_prefill",
           "resolve_backend", "ab_compare", "on_tpu"]

BACKENDS = ("xla", "pallas", "auto")


def paged_decode_attention(q, k_pool, v_pool, block_tables, context_lens,
                           backend="xla", scale=None):
    """One decode step. ``q`` [B, H, Dh]; pools [P, page, H, Dh];
    ``block_tables`` [B, max_pages] int32; ``context_lens`` [B] int32.
    Returns [B, H, Dh]."""
    if backend == "pallas":
        return _pallas_paged_attention(q, k_pool, v_pool, block_tables,
                                       context_lens, scale=scale)
    return _xla_paged_attention(q, k_pool, v_pool, block_tables,
                                context_lens, scale=scale)


def paged_prefill_attention(q, k_pool, v_pool, block_tables, q_start,
                            q_lens, scale=None):
    """Partial-prefix attention for one **chunked-prefill** step: ``q``
    [B, S, H, Dh] chunk tokens starting at absolute position
    ``q_start[b]`` per row, attending causally over the row's pages
    (which already hold the prefix AND this chunk — write-then-attend,
    same order as decode). XLA gather formulation only: chunk prefill is
    a batched matmul-shaped workload XLA handles well, so there is no
    Pallas leg to gate."""
    return _xla_paged_prefill(q, k_pool, v_pool, block_tables, q_start,
                              q_lens, scale=scale)


def sharded_paged_prefill(mesh, axis_name="model", scale=None):
    """Chunked-prefill attention sharded along KV heads over
    ``mesh[axis_name]`` — same partitioning as the decode step (query
    heads ride with their KV-head group; tables/starts/lens replicate).
    Falls back to the unsharded fn when the axis degree is 1."""
    degree = int(mesh.shape.get(axis_name, 1))

    def _impl(q, kp, vp, bt, start, lens):
        return paged_prefill_attention(q, kp, vp, bt, start, lens,
                                       scale=scale)

    if degree <= 1:
        return _impl
    in_specs = (
        P(None, None, axis_name, None),   # q [B, S, H, Dh]
        P(None, None, axis_name, None),   # k_pool [P, page, KVH, Dh]
        P(None, None, axis_name, None),   # v_pool
        P(),                              # block_tables (replicated)
        P(),                              # q_start
        P(),                              # q_lens
    )
    out_specs = P(None, None, axis_name, None)
    # tpu-lint: ok[RC001] built once per engine at a fixed shape and invoked inside the engine's jitted round (nested jit inlines) — the round program is counted at its _note_program install site
    return jax.jit(jax.shard_map(_impl, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))


def sharded_paged_attention(mesh, axis_name="model", backend="xla",
                            scale=None):
    """Decode attention sharded along KV heads over ``mesh[axis_name]``
    (snippet [2] shape). Each shard attends its own head slice against its
    head slice of every page; tables/lens replicate — no collective in the
    step, the out_spec stitches heads back. Falls back to the unsharded
    fn when the axis degree is 1."""
    degree = int(mesh.shape.get(axis_name, 1))

    def _impl(q, kp, vp, bt, lens):
        return paged_decode_attention(q, kp, vp, bt, lens,
                                      backend=backend, scale=scale)

    if degree <= 1:
        return _impl
    in_specs = (
        P(None, axis_name, None),         # q [B, H, Dh]
        P(None, None, axis_name, None),   # k_pool [P, page, H, Dh]
        P(None, None, axis_name, None),   # v_pool
        P(),                              # block_tables (replicated)
        P(),                              # context_lens (replicated)
    )
    out_specs = P(None, axis_name, None)
    # tpu-lint: ok[RC001] built once per engine at a fixed shape and invoked inside the engine's jitted round (nested jit inlines) — the round program is counted at its _note_program install site
    return jax.jit(jax.shard_map(_impl, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))


def resolve_backend(requested=None):
    """Normalize the backend choice: explicit arg wins, then the
    ``PADDLE_TPU_SERVING_ATTN`` env knob, then the global
    ``PADDLE_TPU_KERNELS`` gate knob, default ``auto``."""
    b = requested or os.environ.get("PADDLE_TPU_SERVING_ATTN") \
        or os.environ.get(_gate.KERNELS_ENV) or "auto"
    b = str(b).lower()
    if b not in BACKENDS:
        raise ValueError(
            f"unknown serving attention backend {b!r}; pick from "
            f"{BACKENDS}")
    return b


def ab_compare(q, k_pool, v_pool, block_tables, context_lens, scale=None,
               repeats=20):
    """Time the jitted XLA reference vs the Pallas kernel at this exact
    serving shape and pick a winner — now the generalized demotion gate
    (``ops/pallas/_common.ab_gate``) with the verdict recorded under the
    ``paged_attention`` kernel, so bench's kernels leg and the serving
    engine share one verdict cache. Off-TPU the Pallas leg is skipped
    (interpret mode measures the emulator, not the chip) and XLA wins by
    default. -> ``{"backend", "xla_ms", "pallas_ms", "reason"}``."""
    args = (q, k_pool, v_pool, jnp.asarray(block_tables, jnp.int32),
            jnp.asarray(context_lens, jnp.int32))
    # recorded under the leading-operand (q) sig, matching what the
    # incubate paged_attention auto path queries
    return _gate.ab_gate(
        "paged_attention",
        lambda *a: _xla_paged_attention(*a, scale=scale),
        lambda *a: _pallas_paged_attention(*a, scale=scale),
        args, repeats=repeats, sig=_gate.shape_sig(q))
