"""Serving metrics — QPS, TTFT, inter-token latency, KV-pool occupancy.

Everything lands in the PR-5 observability registry
(``PADDLE_TPU_METRICS=1``; see ``observability/metrics.py``) so serving
runs share the JSONL snapshot/report plumbing with training. Names:

* ``serving_requests_total{status=ok|failed|evicted}`` — counters
  (``evicted`` counts preemptions, not terminal states)
* ``serving_tokens_total`` — generated tokens
* ``serving_ttft_ms`` / ``serving_inter_token_ms`` / ``serving_e2e_ms`` /
  ``serving_queue_wait_ms`` — latency histograms
* ``serving_qps`` — finished requests/s over a sliding window
* ``serving_tokens_per_sec`` — decode throughput over the same window
* ``serving_active_slots`` / ``serving_queue_depth`` /
  ``serving_kv_occupancy_pct`` — gauges sampled every engine step

Every hook is a no-op when the registry is off (one ``None`` check), so
an un-instrumented engine pays nothing — same contract as the flight
recorder and telemetry callbacks.
"""
from __future__ import annotations

import time
from collections import deque

from ..observability import metrics as _metrics

__all__ = ["ServingMetrics"]


class ServingMetrics:
    """Per-engine metrics frontend over the process registry."""

    def __init__(self, registry=None, window_s=30.0):
        self._reg = registry if registry is not None \
            else _metrics.get_registry()
        self.window_s = float(window_s)
        self._finish_times: deque = deque()
        self._token_times: deque = deque()

    @property
    def enabled(self):
        return self._reg is not None

    def _trim(self, dq, now):
        cutoff = now - self.window_s
        while dq and dq[0] < cutoff:
            dq.popleft()

    def on_admit(self, req):
        reg = self._reg
        if reg is None or req.t_admit is None:
            return
        # since the last (re-)enqueue: a re-admitted evicted request must
        # not count its prior active service time as queueing
        reg.histogram("serving_queue_wait_ms").observe(
            (req.t_admit - req.t_enqueue) * 1e3)

    def on_first_token(self, req):
        reg = self._reg
        if reg is None:
            return
        ttft = req.ttft_s()
        if ttft is not None:
            reg.histogram("serving_ttft_ms").observe(ttft * 1e3)

    def on_token(self, req, dt_s=None):
        reg = self._reg
        if reg is None:
            return
        reg.counter("serving_tokens_total").inc()
        if dt_s is not None:
            reg.histogram("serving_inter_token_ms").observe(dt_s * 1e3)
        now = time.perf_counter()
        self._token_times.append(now)
        self._trim(self._token_times, now)
        span = now - self._token_times[0]
        if len(self._token_times) > 1 and span > 0:
            reg.gauge("serving_tokens_per_sec").set(
                (len(self._token_times) - 1) / span)

    def on_evict(self, req):
        reg = self._reg
        if reg is None:
            return
        reg.counter("serving_evictions_total").inc()
        reg.counter("serving_requests_total", status="evicted").inc()

    def on_finish(self, req):
        reg = self._reg
        if reg is None:
            return
        status = "failed" if req.error is not None else "ok"
        reg.counter("serving_requests_total", status=status).inc()
        if req.t_done is not None:
            reg.histogram("serving_e2e_ms").observe(
                (req.t_done - req.t_submit) * 1e3)
        now = time.perf_counter()
        self._finish_times.append(now)
        self._trim(self._finish_times, now)
        span = now - self._finish_times[0]
        if len(self._finish_times) > 1 and span > 0:
            reg.gauge("serving_qps").set(
                (len(self._finish_times) - 1) / span)

    def sample_state(self, active_slots, queue_depth, occupancy_pct):
        reg = self._reg
        if reg is None:
            return
        reg.gauge("serving_active_slots").set(active_slots)
        reg.gauge("serving_queue_depth").set(queue_depth)
        reg.gauge("serving_kv_occupancy_pct").set(occupancy_pct)
