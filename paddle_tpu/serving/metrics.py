"""Serving metrics — QPS, TTFT, inter-token latency, KV-pool occupancy.

Everything lands in the PR-5 observability registry
(``PADDLE_TPU_METRICS=1``; see ``observability/metrics.py``) so serving
runs share the JSONL snapshot/report plumbing with training. Names:

* ``serving_requests_total{status=ok|failed|evicted}`` — counters
  (``evicted`` counts preemptions, not terminal states)
* ``serving_tokens_total`` — generated tokens
* ``serving_ttft_ms`` / ``serving_inter_token_ms`` / ``serving_e2e_ms`` /
  ``serving_queue_wait_ms`` — latency histograms
* ``serving_qps`` — finished requests/s over a sliding window
* ``serving_tokens_per_sec`` — decode throughput over the same window
* ``serving_active_slots`` / ``serving_queue_depth`` /
  ``serving_kv_occupancy_pct`` — gauges sampled every engine step
* ``serving_prefix_{hits,misses,hit_tokens}_total`` — prefix-cache
  admission counters; ``serving_prefix_shared_pages`` /
  ``serving_prefix_cached_pages`` — live-shared and reclaimable-cached
  page gauges
* ``serving_prefill_chunk_tokens_total`` — chunk-tokens processed by the
  budgeted chunked-prefill interleave
* ``serving_phase_ms{phase=queue_wait|prefill|decode|route|migrate}`` —
  per-lifecycle-phase latency histograms (ISSUE 20): the SAME phase
  boundaries the distributed request trace stamps, so the aggregate
  tails and the per-request waterfalls are two views of one measurement
* ``serving_compiles_total`` — counter: every shape-specialized callable
  the engine installs (ragged token pad, prefill/chunk bucket pair,
  decode step); ``serving_distinct_programs`` — gauge: how many are live
  (the ISSUE-13 bucket-matrix elimination as a measured number)

``serving_queue_wait_ms`` observes each request's **cumulative** queue
wait once, at its terminal state (re-admissions carry their pre-eviction
wait forward; prefix hit/miss counters fire on the first admission only).

**Fleet identity** (ISSUE 14): construct with ``engine="e0"`` and every
row above carries an ``engine`` label (``serving_ttft_ms{engine=e0}``),
so N engines sharing one registry/JSONL stream stay attributable —
``observability/report.py`` aggregates the labeled families into
per-engine tail rows. ``engine=None`` (the default) keeps the legacy
unlabeled names. Fleet-only rows: ``serving_prefix_remote_hits`` /
``serving_prefix_remote_hit_tokens`` gauges (cross-engine prefix
imports) and ``serving_migrations_{in,out}_total`` counters (page
migration legs of the disaggregated fleet).

**Elastic fleet rows** (ISSUE 16, emitted by the router/autoscaler
through an unlabeled frontend): ``serving_hedges_{fired,won}_total``
(speculative straggler duplication), ``serving_aborts_total`` (silently
cancelled hedge losers), ``serving_prefetch_pages_total`` (prefix pages
pushed ahead of traffic on affinity spill), and
``serving_scale_events_total{direction=up|down}`` +
``serving_fleet_engines`` (autoscaler lifecycle).

Every hook is a no-op when the registry is off (one ``None`` check), so
an un-instrumented engine pays nothing — same contract as the flight
recorder and telemetry callbacks.
"""
from __future__ import annotations

import time
from collections import deque

from ..observability import metrics as _metrics

__all__ = ["ServingMetrics"]


class ServingMetrics:
    """Per-engine metrics frontend over the process registry."""

    def __init__(self, registry=None, window_s=30.0, prefix_enabled=True,
                 engine=None):
        self._reg = registry if registry is not None \
            else _metrics.get_registry()
        self.window_s = float(window_s)
        # fleet identity: every row carries engine=<id> so two engines in
        # one job (one process or one JSONL dir) never collide in one
        # family; None keeps the legacy unlabeled names
        self._labels = {"engine": str(engine)} if engine is not None \
            else {}
        # engines without a prefix cache must not export the prefix
        # metric family at all (every request would read as a miss — a
        # nonexistent cache reporting 0% hit rate poisons hot/cold
        # comparisons)
        self.prefix_enabled = bool(prefix_enabled)
        self._finish_times: deque = deque()
        self._token_times: deque = deque()

    @property
    def enabled(self):
        return self._reg is not None

    def _trim(self, dq, now):
        cutoff = now - self.window_s
        while dq and dq[0] < cutoff:
            dq.popleft()

    # engine-labeled children (the engine label rides every row this
    # frontend emits; extra labels like status compose with it)
    def _counter(self, name, **extra):
        return self._reg.counter(name, **self._labels, **extra)

    def _gauge(self, name):
        return self._reg.gauge(name, **self._labels)

    def _hist(self, name):
        return self._reg.histogram(name, **self._labels)

    def on_phase(self, phase, dur_s):
        """One lifecycle-phase latency sample for the
        ``serving_phase_ms{phase=...}`` family (ISSUE 20) — fed at the
        same boundaries the request trace stamps."""
        reg = self._reg
        if reg is None or dur_s is None:
            return
        reg.histogram("serving_phase_ms", **self._labels,
                      phase=str(phase)).observe(max(0.0, dur_s) * 1e3)

    def on_admit(self, req):
        reg = self._reg
        if reg is None or req.t_admit is None:
            return
        t_enq = getattr(req, "t_enqueue", None)
        if t_enq is not None:
            self.on_phase("queue_wait", req.t_admit - t_enq)
        # request-level prefix hit/miss: counted on the FIRST admission
        # only — an evicted request re-hitting its own cached head on
        # readmission must not inflate the hit rate (the recompute it
        # saves is already visible in the eviction rows)
        if self.prefix_enabled and req.evictions == 0:
            if req.prefix_hit_tokens > 0:
                self._counter("serving_prefix_hits_total").inc()
                self._counter("serving_prefix_hit_tokens_total").inc(
                    req.prefix_hit_tokens)
            else:
                self._counter("serving_prefix_misses_total").inc()

    def on_first_token(self, req):
        reg = self._reg
        if reg is None:
            return
        ttft = req.ttft_s()
        if ttft is not None:
            self._hist("serving_ttft_ms").observe(ttft * 1e3)
        if req.t_admit is not None and req.t_first_token is not None:
            self.on_phase("prefill", req.t_first_token - req.t_admit)

    def on_token(self, req, dt_s=None):
        reg = self._reg
        if reg is None:
            return
        self._counter("serving_tokens_total").inc()
        if dt_s is not None:
            self._hist("serving_inter_token_ms").observe(dt_s * 1e3)
        now = time.perf_counter()
        self._token_times.append(now)
        self._trim(self._token_times, now)
        span = now - self._token_times[0]
        if len(self._token_times) > 1 and span > 0:
            self._gauge("serving_tokens_per_sec").set(
                (len(self._token_times) - 1) / span)

    def on_evict(self, req):
        reg = self._reg
        if reg is None:
            return
        self._counter("serving_evictions_total").inc()
        self._counter("serving_requests_total", status="evicted").inc()

    def on_adopt(self, req):
        """A migrated request joined this engine with its KV pre-written
        (fleet page migration, the decode half)."""
        reg = self._reg
        if reg is None:
            return
        self._counter("serving_migrations_in_total").inc()

    def on_migrate_out(self, req):
        """A request left this engine for a decode-designated one (the
        prefill half of the disaggregated fleet)."""
        reg = self._reg
        if reg is None:
            return
        self._counter("serving_migrations_out_total").inc()

    def on_finish(self, req):
        reg = self._reg
        if reg is None:
            return
        status = "failed" if req.error is not None else "ok"
        self._counter("serving_requests_total", status=status).inc()
        # CUMULATIVE queue wait, observed ONCE per request at its
        # terminal state: the total covers every waiting segment across
        # eviction/readmission (the pre-eviction time used to vanish when
        # t_enqueue was reset), and observing only here keeps the
        # histogram sum exact — per-admission samples of a running total
        # would double-count the earlier segments
        self._hist("serving_queue_wait_ms").observe(
            req.queue_wait_s * 1e3)
        if req.t_done is not None:
            self._hist("serving_e2e_ms").observe(
                (req.t_done - req.t_submit) * 1e3)
            if req.t_first_token is not None:
                self.on_phase("decode", req.t_done - req.t_first_token)
        now = time.perf_counter()
        self._finish_times.append(now)
        self._trim(self._finish_times, now)
        span = now - self._finish_times[0]
        if len(self._finish_times) > 1 and span > 0:
            self._gauge("serving_qps").set(
                (len(self._finish_times) - 1) / span)

    def sample_state(self, active_slots, queue_depth, occupancy_pct,
                     shared_pages=None, cached_pages=None,
                     remote_hits=None, remote_hit_tokens=None):
        reg = self._reg
        if reg is None:
            return
        self._gauge("serving_active_slots").set(active_slots)
        self._gauge("serving_queue_depth").set(queue_depth)
        self._gauge("serving_kv_occupancy_pct").set(occupancy_pct)
        if shared_pages is not None:
            self._gauge("serving_prefix_shared_pages").set(shared_pages)
        if cached_pages is not None:
            self._gauge("serving_prefix_cached_pages").set(cached_pages)
        if remote_hits is not None:
            self._gauge("serving_prefix_remote_hits").set(remote_hits)
        if remote_hit_tokens is not None:
            self._gauge("serving_prefix_remote_hit_tokens").set(
                remote_hit_tokens)

    # ---- fleet lifecycle (ISSUE 16) ------------------------------------
    def on_hedge_fired(self):
        """The router duplicated a straggler leg on a second engine."""
        reg = self._reg
        if reg is None:
            return
        self._counter("serving_hedges_fired_total").inc()

    def on_hedge_won(self):
        """A hedge duplicate finished first (the original was aborted)."""
        reg = self._reg
        if reg is None:
            return
        self._counter("serving_hedges_won_total").inc()

    def on_prefetch_pages(self, n_pages):
        """Prefix pages pushed/imported ahead of traffic (router
        prefetch-on-affinity-spill)."""
        reg = self._reg
        if reg is None:
            return
        self._counter("serving_prefetch_pages_total").inc(n_pages)

    def on_abort(self):
        """A leg was silently cancelled (hedge loser): slot + pages
        freed, waiters never fired."""
        reg = self._reg
        if reg is None:
            return
        self._counter("serving_aborts_total").inc()

    def on_router_replay(self):
        """An exactly-once replay: a resubmitted terminal request id was
        answered from the ledger's recorded result (ISSUE 17) — no
        engine touched."""
        reg = self._reg
        if reg is None:
            return
        self._counter("serving_router_requests_replayed_total").inc()

    def on_router_failover(self, seconds):
        """A shadow router adopted the front door; ``seconds`` is the
        takeover wall time (lease-stale detection through ledger
        adoption)."""
        reg = self._reg
        if reg is None:
            return
        self._gauge("serving_router_failover_s").set(float(seconds))

    def on_scale_event(self, direction, n_engines):
        """The autoscaler changed the fleet size (``direction`` is
        "up" or "down"); the gauge tracks the resulting roster size."""
        reg = self._reg
        if reg is None:
            return
        self._counter("serving_scale_events_total",
                      direction=str(direction)).inc()
        self._gauge("serving_fleet_engines").set(n_engines)

    def on_prefill_chunk(self, n_tokens):
        reg = self._reg
        if reg is None:
            return
        self._counter("serving_prefill_chunk_tokens_total").inc(n_tokens)

    def on_compile(self, distinct_programs):
        """The engine installed a NEW shape-specialized callable (ragged
        token pad, prefill/chunk bucket pair, or the decode step) — the
        compile-count observability of ISSUE 13: the ragged rebuild's
        bucket-matrix elimination must be a measured number, and a
        regression (a knob reintroducing a bucket grid) must show up in
        the snapshot JSON."""
        reg = self._reg
        if reg is None:
            return
        self._counter("serving_compiles_total").inc()
        self._gauge("serving_distinct_programs").set(distinct_programs)
