"""Continuous-batching serving engine: one ragged launch per round.

The millions-of-users tier (ROADMAP item 3; SURVEY layer 11). A
:class:`ServingEngine` wraps a GPT-family ``models.gpt.GPTForCausalLM``
and runs it as a concurrent serving loop.

* **ragged serving (default; ISSUE 13)** — every scheduler round is ONE
  launch of one jitted program (Ragged Paged Attention, arxiv
  2604.15464): single-token decode rows, budgeted prefill chunks and
  prefix-hit prompt tails flatten into a ``[total_tokens]`` token stream
  with per-row metadata (``row_starts``/``row_lens``/``kv_lens``/block
  tables); K/V scatter into pages and causal ragged attention happen in
  the same program. Only ``total_tokens`` is padded (power-of-two
  schedule) — the (batch, seq) prefill bucket matrix, the per-(batch,
  chunk) chunk-step compiles, and the fixed-slot decode program collapse
  into a handful of shape-specializations of ONE callable, counted by
  ``serving_compiles_total`` / ``serving_distinct_programs``.
  ``PADDLE_TPU_SERVING_RAGGED=0`` (or ``ragged=False``) falls back to
  the bucketed paths below, which the bucket knobs now exist for.

The bucketed fallback keeps the pre-ISSUE-13 shape:

* **prefill** — newly admitted requests run the dense causal forward at
  bucketed shapes (batch buckets AND sequence buckets share
  ``inference.pick_bucket`` with :class:`~paddle_tpu.inference.
  BatchingPredictor`, whose pad-to-bucket idea this generalizes),
  compiled ONCE per (batch, seq) bucket pair with ``jax.jit`` (the
  bucket sets bound the compile cache; eager per-op dispatch no longer
  sits on TTFT), their K/V is written into pages of the shared pool,
  and the first token streams out (TTFT ends here).
* **decode** — ONE fixed-shape step over all ``max_slots`` slots: embed
  the last token of every row at its own absolute position, scatter its
  K/V into the pool, paged attention over each row's block table, greedy
  argmax on device (host-side temperature/top-k sampling per request when
  asked). Compiled once with ``jax.jit`` — params, block tables and pools
  are arguments, pools are donated on TPU, so steady-state decode is one
  XLA program launch per token regardless of admission churn.
* **chunked prefill** (ISSUE 9) — ``prefill_chunk=C`` splits prompts
  into C-token chunks advanced at most ``prefill_token_budget`` tokens
  per scheduler round, interleaved with decode: each chunk scatters its
  K/V into the request's pages and runs partial-prefix attention
  (:func:`~.decode.paged_prefill_attention`) over itself + the already-
  written prefix, so a long prompt arriving mid-stream never stalls
  in-flight decodes (ITL p99 is bounded by the budget).
* **prefix caching** (ISSUE 9, on by default) — full prompt pages are
  indexed in a page-granular trie (:class:`~.prefix_cache.PrefixCache`);
  an admission hit takes the shared head by refcounted reference
  (skipping its prefill compute AND page writes — only the tail runs
  the chunk step), shared pages are copy-on-write read-only, and
  reclamation drains only refcount-0 cached pages, LRU-first.
* **scheduling** — between steps the
  :class:`~.scheduler.ContinuousBatchingScheduler` finishes / evicts /
  admits, so a request arriving mid-stream joins the next step without
  stalling in-flight rows (the no-decode-gap acceptance test).

The paged-attention backend is A/B gated (``serving/decode.py``): Pallas
only where it measurably beats the XLA reference at the serving shape;
``PADDLE_TPU_SERVING_ATTN`` overrides. Pass ``mesh=`` to shard the decode
along KV heads over the fleet mesh's ``model`` axis for multi-chip
serving.

Metrics flow through the PR-5 registry via :class:`~.metrics.
ServingMetrics`; ``bench.py --serving`` drives a Poisson open-loop load
(``serving/load.py``) and records tokens/s + tail latency.
"""
from __future__ import annotations

import contextlib
import os
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import no_grad
from ..core.tensor import Tensor
from ..inference import pick_bucket
from ..observability import tracing as _trc
from . import decode as _decode
from .ragged_attention import (ab_compare_ragged as _ab_compare_ragged,
                               pad_total_tokens as _pad_total_tokens,
                               ragged_paged_attention
                               as _ragged_attention,
                               sharded_ragged_attention
                               as _sharded_ragged_attention)
from .kv_cache import PagedKVCache, pages_for
from .metrics import ServingMetrics
from .prefix_cache import PrefixCache
from .scheduler import (ContinuousBatchingScheduler, EngineClosed,
                        EngineShuttingDown, GenerationRequest)

__all__ = ["ServingEngine"]


@contextlib.contextmanager
def _swap_params(params, arrays):
    """Temporarily back the model's Parameters with (traced) arrays so the
    decode step jits with weights as real arguments — no giant closure
    constants, donation-friendly."""
    olds = [p._data for p in params]
    for p, a in zip(params, arrays):
        p._data = a
    try:
        yield
    finally:
        for p, o in zip(params, olds):
            p._data = o


def _select_token(logits_row, req):
    """Host-side sampling for one request: greedy at temperature 0, else
    temperature + optional top-k from the request's own seeded RNG (the
    decode batch stays deterministic per request, not per step)."""
    if req.temperature <= 0.0:
        return int(np.argmax(logits_row))
    z = logits_row.astype(np.float64) / max(req.temperature, 1e-6)
    if req.top_k is not None:
        kth = np.partition(z, -int(req.top_k))[-int(req.top_k)]
        z = np.where(z < kth, -np.inf, z)
    z = z - z.max()
    p = np.exp(z)
    p /= p.sum()
    return int(req.rng().choice(len(p), p=p))


class ServingEngine:
    """Continuous-batching inference over a paged KV cache.

    Synchronous use (tests, batch jobs)::

        eng = ServingEngine(model, page_size=16, num_pages=64, max_slots=4)
        tokens = eng.generate([1, 2, 3], max_new_tokens=8)

    Concurrent serving (streaming callbacks + backpressure)::

        with ServingEngine(model, ...) as eng:
            eng.start()
            req = eng.submit(prompt, on_token=lambda r, t, fin: push(t))
            req.result(timeout=30)
    """

    def __init__(self, model, page_size=16, num_pages=64, max_slots=4,
                 max_queue=256, prefill_seq_buckets=None,
                 prefill_batch_buckets=None, attn_backend=None, mesh=None,
                 mesh_axis="model", jit=True, registry=None,
                 prefill_chunk=None, prefill_token_budget=None,
                 prefix_cache=True, ragged=None, engine_id=None,
                 page_share=None):
        cfg = model.config
        self.model = model
        self.model.eval()
        self.cfg = cfg
        # fleet identity: labels this engine's metric rows (two engines in
        # one job used to collide in one registry family) and names it in
        # the router/registry; None keeps the legacy unlabeled rows
        self.engine_id = engine_id
        self.page_size = int(page_size)
        self.max_slots = int(max_slots)
        self.max_pages = pages_for(cfg.max_seq_len, self.page_size)
        H = cfg.num_heads
        KVH = getattr(cfg, "num_kv_heads", None) or H
        Dh = cfg.hidden_size // H
        dt = model.gpt.wte.weight._data.dtype
        # GQA pools carry only the KV heads — [pages, page, KVH, Dh] is an
        # H/KVH memory cut that directly raises max concurrent requests
        self.kv = PagedKVCache(cfg.num_layers, int(num_pages),
                               self.page_size, KVH, Dh, dtype=dt)
        self.num_kv_heads = KVH
        # prefix cache: content-addressed page sharing across requests
        # with a common prompt head (hits skip prefill compute AND page
        # writes; pages are refcounted with page-granular copy-on-write).
        # With a fleet PageShareClient attached the trie becomes fleet-
        # wide: a local miss consults the store-published index and
        # imports the hot pages (system prompts prefill once per FLEET)
        if not prefix_cache:
            self.prefix = None
        elif page_share is not None:
            from .fleet.page_share import SharedPrefixCache
            self.prefix = SharedPrefixCache(self.kv, self.page_size,
                                            page_share)
        else:
            self.prefix = PrefixCache(self.kv.allocator, self.page_size)
        self.scheduler = ContinuousBatchingScheduler(
            self.kv.allocator, self.max_slots, self.page_size,
            cfg.max_seq_len, max_queue=max_queue,
            prefix_cache=self.prefix)
        self.metrics = ServingMetrics(registry=registry,
                                      prefix_enabled=self.prefix
                                      is not None, engine=engine_id)
        # chunked prefill: split prompts into prefill_chunk-token chunks
        # and interleave at most prefill_token_budget chunk-tokens per
        # scheduler round with the decode step — a long prompt arriving
        # mid-stream no longer stalls in-flight decodes (ITL p99 becomes
        # bounded by the budget, not the longest prompt)
        self.prefill_chunk = int(prefill_chunk) if prefill_chunk else None
        if self.prefill_chunk is not None and self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        if prefill_token_budget and self.prefill_chunk is None:
            raise ValueError(
                "prefill_token_budget only bounds CHUNKED prefill — pass "
                "prefill_chunk= as well (without it, prompts prefill "
                "whole and the budget would be silently ignored)")
        self._prefill_budget = int(prefill_token_budget) \
            if prefill_token_budget else (self.prefill_chunk or 0)
        self._prefilling: list = []     # FIFO of mid-prefill requests
        # seq buckets cap padding waste at ~2x; batch buckets keep the
        # prefill compile cache small (one shape per bucket pair)
        if prefill_seq_buckets is None:
            prefill_seq_buckets, b = [], 16
            while b < cfg.max_seq_len:
                prefill_seq_buckets.append(b)
                b *= 2
            prefill_seq_buckets.append(cfg.max_seq_len)
        self.prefill_seq_buckets = sorted(set(prefill_seq_buckets))
        self.prefill_batch_buckets = sorted(set(
            prefill_batch_buckets or [1, 2, 4, self.max_slots]))
        # chunk-step shapes: partial tail chunks bucket to powers of two
        # below the chunk size (or the prefill seq buckets when chunking
        # is off and only prefix-hit tails ride this path)
        if self.prefill_chunk:
            cb, b = {self.prefill_chunk}, 8
            while b < self.prefill_chunk:
                cb.add(b)
                b *= 2
            self._chunk_buckets = sorted(cb)
        else:
            self._chunk_buckets = list(self.prefill_seq_buckets)
        # ragged serving (ISSUE 13): the whole scheduler round is ONE
        # launch of one jitted program; the bucketed paths (and their
        # bucket knobs above) stay as the explicit fallback
        if ragged is None:
            ragged = os.environ.get("PADDLE_TPU_SERVING_RAGGED",
                                    "1") not in ("0", "false", "off")
        self.ragged = bool(ragged)
        # ---- paged-attention backend (A/B gated; standing kernel rule)
        requested = _decode.resolve_backend(attn_backend)
        self.attn_ab = None
        if requested == "auto":
            self.attn_ab = self._run_ab_gate_ragged() if self.ragged \
                else self._run_ab_gate()
            self.attn_backend = self.attn_ab["backend"]
        else:
            self.attn_backend = requested
        if mesh is not None and int(mesh.shape.get(mesh_axis, 1)) > 1:
            deg = int(mesh.shape[mesh_axis])
            if H % deg or KVH % deg:
                raise ValueError(
                    f"heads ({H} query / {KVH} KV) not divisible by mesh "
                    f"axis {mesh_axis}={deg} — GQA sharding splits both,"
                    " keeping each query-head group with its KV head")
        if mesh is not None:
            self._attn_impl = _decode.sharded_paged_attention(
                mesh, axis_name=mesh_axis, backend=self.attn_backend)
            self._prefill_attn_impl = _decode.sharded_paged_prefill(
                mesh, axis_name=mesh_axis)
            self._ragged_attn_impl = _sharded_ragged_attention(
                mesh, axis_name=mesh_axis, backend=self.attn_backend)
        else:
            backend = self.attn_backend
            self._attn_impl = lambda q, kp, vp, bt, lens: \
                _decode.paged_decode_attention(q, kp, vp, bt, lens,
                                               backend=backend)
            self._prefill_attn_impl = _decode.paged_prefill_attention
            self._ragged_attn_impl = lambda q, kp, vp, rs, rl, kl, bt: \
                _ragged_attention(q, kp, vp, rs, rl, kl, bt,
                                  backend=backend)
        self._params = list(model.parameters())
        self._param_arrays = [p._data for p in self._params]
        self._jit = bool(jit)
        self._step_fn = self._build_step()
        # prefill compiles once per (batch bucket, seq bucket) pair — ONE
        # jitted callable (jax's cache specializes per bucket shape), with
        # the pairs it has served tracked in _prefill_fns so the
        # bounded-compile contract is observable (tested); steady-state
        # prefill dispatch is one compiled-program launch instead of the
        # eager per-op tunnel that used to sit on TTFT (ROADMAP item 3)
        self._prefill_fn = self._build_prefill()
        self._prefill_fns = {}
        # the chunk step doubles as the prefix-hit tail prefill (both are
        # partial-prefix attention over already-written pages); one jitted
        # callable, shape-specialized per (batch, chunk) bucket pair
        self._chunk_fn = self._build_chunk_prefill()
        self._chunk_fns = {}
        # the ragged round: ONE callable; jax.jit shape-specializes it
        # per padded total_tokens only (pad_total_tokens schedule). The
        # pads it has served live in _ragged_shapes; every installed
        # shape-specialized program — ragged pad, prefill/chunk bucket
        # pair, the fixed-slot decode step — lands in _programs, feeding
        # serving_compiles_total / serving_distinct_programs (the
        # bucket-matrix elimination as a measured number)
        self._ragged_fn = self._build_ragged_step()
        self._ragged_shapes: set = set()
        self._programs: set = set()
        self._steps = 0
        self._decode_tokens = 0
        self._chunk_tokens = 0
        self.capture_logits = None   # tests: a list collects per-step
        # [S, V] decode logits (forces a host fetch; leave None in prod)
        self._peak_occupancy = 0.0
        self._thread = None
        self._stop_evt = threading.Event()
        self._wake = threading.Event()
        self._closed = False
        self._draining = False
        self._loop_error = None  # terminal serve-loop crash (unhealthy)
        self._shutdown_lock = threading.Lock()
        # serializes actual scheduler rounds: the engine contract is one
        # driving thread, but a SIGTERM drain (watcher thread) can land
        # while a foreground generate()/run_until_idle() is mid-step —
        # without this, two steppers pop the same slot / double-alloc
        # pages. Re-entrant so the serve loop's own step nests freely.
        self._step_lock = threading.RLock()

    # ------------------------------------------------------------ A/B gate
    def _run_ab_gate(self):
        """Measure XLA vs Pallas at this engine's decode shape; 'auto'
        resolves to the winner (Pallas never wins off-TPU)."""
        H, Dh = self.cfg.num_heads, self.cfg.hidden_size // self.cfg.num_heads
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (self.max_slots, H, Dh),
                              self.kv.dtype)
        bt = np.zeros((self.max_slots, self.max_pages), np.int32)
        lens = np.full((self.max_slots,),
                       min(self.page_size, self.cfg.max_seq_len), np.int32)
        return _decode.ab_compare(q, self.kv.k[0], self.kv.v[0], bt, lens)

    def _run_ab_gate_ragged(self):
        """Measure XLA vs Pallas at this engine's ragged launch shape
        (a full round: every slot a decode row, padded to the schedule);
        'auto' resolves to the winner (Pallas never wins off-TPU)."""
        H = self.cfg.num_heads
        Dh = self.cfg.hidden_size // H
        R = self.max_slots
        T = _pad_total_tokens(R + self._prefill_budget)
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (T, H, Dh), self.kv.dtype)
        rs = np.arange(R, dtype=np.int32)
        rl = np.ones(R, np.int32)
        kl = np.full(R, min(self.page_size, self.cfg.max_seq_len),
                     np.int32)
        bt = np.zeros((R, self.max_pages), np.int32)
        return _ab_compare_ragged(q, self.kv.k[0], self.kv.v[0],
                                         rs, rl, kl, bt)

    def _note_program(self, key):
        """Record the installation of a new shape-specialized callable
        (ragged pad, prefill/chunk bucket pair, decode step) — the
        bounded-compile contract as a measured number."""
        if key in self._programs:
            return
        self._programs.add(key)
        self.metrics.on_compile(len(self._programs))

    # ----------------------------------------------------------- decode fn
    def _build_step(self):
        model, params = self.model, self._params
        L = self.cfg.num_layers
        attn_impl = self._attn_impl

        def step(arrays, tokens, positions, bt, k_pools, v_pools):
            with no_grad(), _swap_params(params, arrays):
                caches = [{"paged": True,
                           "k_pool": Tensor(k_pools[i]),
                           "v_pool": Tensor(v_pools[i]),
                           "block_tables": Tensor(bt),
                           "positions": Tensor(positions),
                           "attn_impl": attn_impl}
                          for i in range(L)]
                logits = model(Tensor(tokens[:, None]), caches=caches,
                               pos_offset=Tensor(positions))
                last = logits._data[:, -1]
                nxt = jnp.argmax(last, axis=-1).astype(jnp.int32)
                return (nxt, last,
                        [c["k_pool"]._data for c in caches],
                        [c["v_pool"]._data for c in caches])

        if not self._jit:
            return step
        # donation saves the pool double-buffer on TPU; CPU/older
        # backends warn and ignore it, so only ask where it works
        if _decode.on_tpu():
            return jax.jit(step, donate_argnums=(4, 5))
        return jax.jit(step)

    # -------------------------------------------------------- ragged round
    def _build_ragged_step(self):
        """ONE program for the whole scheduler round: embed the flat
        token stream at per-token positions, scatter every row's K/V into
        its pages, run ragged paged attention, and hand back one
        next-token + logit row per batch row (the row's LAST valid
        token's logits — a decode row's next token, a completing prefill
        row's first token). Params are real arguments (no giant closure
        constants), pools are donated on TPU; jax.jit specializes per
        padded total_tokens ONLY."""
        model, params = self.model, self._params
        L = self.cfg.num_layers
        attn_impl = self._ragged_attn_impl
        from ..ops.pallas.ragged_attention import ragged_row_index

        def rstep(arrays, tokens, row_starts, row_lens, kv_lens, bt,
                  k_pools, v_pools):
            with no_grad(), _swap_params(params, arrays):
                T = tokens.shape[0]
                _, pos, valid = ragged_row_index(row_starts, row_lens,
                                                 kv_lens, T)
                positions = jnp.where(valid, pos, 0).astype(jnp.int32)
                caches = [{"ragged": True,
                           "k_pool": Tensor(k_pools[i]),
                           "v_pool": Tensor(v_pools[i]),
                           "block_tables": Tensor(bt),
                           "row_starts": Tensor(row_starts),
                           "row_lens": Tensor(row_lens),
                           "kv_lens": Tensor(kv_lens),
                           "attn_impl": attn_impl}
                          for i in range(L)]
                logits = model(Tensor(tokens[None, :]), caches=caches,
                               pos_offset=Tensor(positions[None, :]))
                # each row's last valid token carries the round's output
                # logit; unused rows clip to garbage the host ignores
                last = jnp.clip(row_starts + row_lens - 1, 0, T - 1)
                row_logits = logits._data[0, last]
                nxt = jnp.argmax(row_logits, axis=-1).astype(jnp.int32)
                return (nxt, row_logits,
                        [c["k_pool"]._data for c in caches],
                        [c["v_pool"]._data for c in caches])

        if not self._jit:
            return rstep
        if _decode.on_tpu():
            return jax.jit(rstep, donate_argnums=(6, 7))
        return jax.jit(rstep)

    def warm_ragged(self, max_tokens=None):
        """Pre-compile the ragged program at every token pad up to
        ``max_tokens``. A pad first seen mid-run costs one XLA compile
        inside a serving round — an ITL spike the schedule makes rare
        but warmup makes impossible. The default covers the engine's
        true worst-case round: every slot decoding plus one prefill
        budget of chunk tokens when chunking is on, or every slot
        carrying a whole max-length prompt when it is off (unchunked
        engines serving known-short prompts should pass a tighter
        ``max_tokens`` rather than compile the full ladder). The warm
        launches carry zero valid rows: every token is padding, so the
        writes land on the reserved scrap page and no request state is
        touched. Serialized against concurrent rounds — the launches
        consume (and on TPU donate) the live pools. -> the list of pads
        compiled."""
        if not self.ragged:
            return []
        if max_tokens is None:
            if self.prefill_chunk is not None:
                # a round carries max(1, budget // chunk) prefill rows of
                # up to chunk tokens EACH — with budget < chunk that one
                # row still takes a whole chunk, so the worst case is the
                # row count times the chunk, not the budget itself
                rows = max(1, self._prefill_budget // self.prefill_chunk)
                max_tokens = self.max_slots + rows * self.prefill_chunk
            else:
                max_tokens = self.max_slots * self.cfg.max_seq_len
        max_tokens = min(int(max_tokens),
                         self.max_slots * self.cfg.max_seq_len)
        pads, t = [], 1
        while True:
            p = _pad_total_tokens(t)
            pads.append(p)
            if p >= max_tokens:
                break
            t = p + 1
        R = self.max_slots
        with self._step_lock:
            for p in pads:
                if p in self._ragged_shapes:
                    continue
                self._ragged_shapes.add(p)
                self._note_program(("ragged", p))
                _, _, self.kv.k, self.kv.v = self._ragged_fn(
                    self._param_arrays, jnp.zeros(p, jnp.int32),
                    jnp.full(R, p, jnp.int32), jnp.zeros(R, jnp.int32),
                    jnp.zeros(R, jnp.int32),
                    jnp.zeros((R, self.max_pages), jnp.int32),
                    list(self.kv.k), list(self.kv.v))
        return pads

    def _step_ragged(self):
        """One ragged scheduler round: admit, grow/evict, then assemble
        decode rows + prefill chunks (budget-bounded FIFO, chunk-boundary
        semantics identical to the bucketed chunk step) into ONE flat
        launch. -> decode tokens emitted."""
        # the ONE tracing gate of the round (standing contract: off =
        # one check, no allocation, no call)
        tr = _trc._TR if _trc._loaded else _trc._load()
        t0 = time.time() if tr is not None else 0.0
        admitted = self.scheduler.schedule()
        for req in admitted:
            self.metrics.on_admit(req)
            req.state = "prefilling"
            self._prefilling.append(req)
        _, evicted = self.scheduler.ensure_decode_capacity()
        for req in evicted:
            self.metrics.on_evict(req)
        self._prefilling = [r for r in self._prefilling
                            if r.state == "prefilling"]
        decode_rows = sorted(
            (r for r in self.scheduler.active.values()
             if r.state == "active"), key=lambda r: r.slot)
        # prefill rows: FIFO, at most budget // chunk rows per round each
        # contributing one chunk (same budget spreading as the bucketed
        # chunk step — ITL stays bounded by the budget); unchunked mode
        # takes every pending row's whole remaining tail
        if self.prefill_chunk is not None:
            n_rows = max(1, self._prefill_budget // self.prefill_chunk)
            prefill_rows = self._prefilling[:n_rows]
        else:
            prefill_rows = list(self._prefilling)
        plan = [(req, 1, req.generated[-1:]) for req in decode_rows]
        prompts = {}
        for req in prefill_rows:
            p = req.effective_prompt()
            prompts[req.request_id] = p
            take = len(p) - req.num_cached
            if self.prefill_chunk is not None:
                take = min(take, self.prefill_chunk)
            plan.append((req, take,
                         p[req.num_cached:req.num_cached + take]))
        if not plan:
            return 0
        R, maxp = self.max_slots, self.max_pages
        total = sum(take for _, take, _ in plan)
        T = _pad_total_tokens(total)
        tokens = np.zeros(T, np.int32)
        row_starts = np.full(R, T, np.int32)   # unused rows: sentinel T
        row_lens = np.zeros(R, np.int32)
        kv_lens = np.zeros(R, np.int32)
        bt = np.zeros((R, maxp), np.int32)
        cursor = 0
        for i, (req, take, seg) in enumerate(plan):
            tokens[cursor:cursor + take] = seg
            row_starts[i] = cursor
            row_lens[i] = take
            kv_lens[i] = req.num_cached + take
            bt[i, :len(req.pages)] = req.pages
            cursor += take
        if T not in self._ragged_shapes:
            self._ragged_shapes.add(T)
            self._note_program(("ragged", T))
        nxt, row_logits, self.kv.k, self.kv.v = self._ragged_fn(
            self._param_arrays, jnp.asarray(tokens),
            jnp.asarray(row_starts), jnp.asarray(row_lens),
            jnp.asarray(kv_lens), jnp.asarray(bt),
            list(self.kv.k), list(self.kv.v))
        completing = [req for req, take, _ in plan[len(decode_rows):]
                      if req.num_cached + take
                      >= len(prompts[req.request_id])]
        any_sampling = any(r.temperature > 0.0
                           for r in decode_rows + completing)
        # tpu-lint: ok[HS002] designed sync: ONE batched token fetch per ragged round feeds host-side scheduling/sampling
        nxt = np.asarray(nxt)
        # tpu-lint: ok[HS002] designed sync: the logit rows ride the same per-round host sampling fetch
        logits_np = np.asarray(row_logits) \
            if (any_sampling or self.capture_logits is not None) else None
        if self.capture_logits is not None and decode_rows:
            cap = np.zeros((self.max_slots,) + logits_np.shape[1:],
                           logits_np.dtype)
            for i, req in enumerate(decode_rows):
                cap[req.slot] = logits_np[i]
            self.capture_logits.append(
                (dict((r.slot, r.request_id) for r in decode_rows), cap))
        # decode rows: account through the scheduler like the fixed-slot
        # step did (num_cached advance, emit, finish)
        by_slot = {}
        for i, req in enumerate(decode_rows):
            if req.temperature > 0.0:
                by_slot[req.slot] = _select_token(logits_np[i], req)
            else:
                by_slot[req.slot] = int(nxt[i])
        finished = self.scheduler.complete_step(by_slot)
        for req in decode_rows:
            tt = req.token_times
            self.metrics.on_token(
                req, tt[-1] - tt[-2] if len(tt) >= 2 else None)
        for req in finished:
            self.metrics.on_finish(req)
        # prefill rows: advance the cursor; a row whose prompt completed
        # emits its first token this round (TTFT ends here) and decodes
        # as a decode row from the NEXT round on
        spent = 0
        for j, (req, take, _) in enumerate(plan[len(decode_rows):]):
            i = len(decode_rows) + j
            prompt = prompts[req.request_id]
            req.num_cached += take
            spent += take
            if req.num_cached < len(prompt):
                continue
            tok = _select_token(logits_np[i], req) \
                if req.temperature > 0.0 else int(nxt[i])
            self._finish_prompt(req, prompt, tok)
        if spent:
            self._chunk_tokens += spent
            self.metrics.on_prefill_chunk(spent)
        if tr is not None:
            now = time.time()
            # engine-lane round span: batched, ONE per round, row counts
            # in args (the waterfall's decode cadence)
            tr.add("decode_round", t0, now - t0, cat="serving",
                   args={"decode_rows": len(by_slot),
                         "prefill_rows": len(plan) - len(decode_rows),
                         "prefill_tokens": spent})
            for req, take, _ in plan[len(decode_rows):]:
                if req.trace is not None:
                    _trc.req_event(req.trace, "prefill_chunk", t0,
                                   now - t0,
                                   args={"tokens": take,
                                         "cached": req.num_cached})
        self._decode_tokens += len(by_slot)
        return len(by_slot)

    # ------------------------------------------------------------- prefill
    def _finish_prompt(self, req, prompt, tok):
        """Prompt-completion protocol — ONE copy for the dense, chunked
        and ragged prefill paths: emit the first generated token (TTFT
        ends here), flip the row to decoding, index the PRE-emit
        prompt's pages for prefix sharing, and finish if the budget is
        already met. ``prompt`` MUST be the pre-emit prompt:
        ``effective_prompt()`` after emit includes the generated token,
        whose KV is only written by the NEXT decode step — indexing it
        would let a (prompt+1)-page-multiple request publish a page with
        an unwritten slot (garbage KV for any future hit if this request
        finishes or evicts before that step runs)."""
        first = not req.generated
        req.emit(tok)
        if first:
            self.metrics.on_first_token(req)
            if req.trace is not None:
                _trc.req_event(req.trace, "first_token", time.time(), 0.0,
                               args={"ttft_ms": (req.t_first_token -
                                                 req.t_submit) * 1e3})
        self.metrics.on_token(req)
        req.state = "active"
        if req in self._prefilling:
            self._prefilling.remove(req)
        if self.prefix is not None:
            self.prefix.insert(prompt, req.pages)
        if req.hit_stop():
            self.scheduler.finish(req)
            self.metrics.on_finish(req)
            return
        hook = req.migrate_hook
        if hook is not None:
            # prefill/decode disaggregation (fleet): the prompt is done
            # but the budget has more to go — hand the request (and its
            # KV pages) to a decode-designated engine. The hook owns the
            # release/adopt; True means the request left this engine. A
            # failed hook degrades gracefully: the row keeps decoding
            # here, never stranding the caller.
            try:
                if hook(self, req):
                    self.metrics.on_migrate_out(req)
            except Exception as e:
                print(f"[serving] migrate hook failed for request "
                      f"{req.request_id}: {type(e).__name__}: {e} — "
                      "decoding locally", file=sys.stderr, flush=True)

    def _prefill_admitted(self, admitted):
        """Route newly-admitted requests to a prefill path:

        * chunked mode — everything queues on ``_prefilling`` and advances
          ``prefill_token_budget`` tokens per scheduler round, interleaved
          with decode.
        * unchunked + prefix hit — the non-shared tail runs the partial-
          prefix chunk step once, whole-tail (shared head skipped).
        * unchunked + miss — the legacy dense bucketed prefill.
        """
        dense = []
        for req in admitted:
            self.metrics.on_admit(req)
            if (self.prefill_chunk is not None or req.num_cached > 0
                    or len(req.effective_prompt())
                    > self.prefill_seq_buckets[-1]):
                # the third arm is the pick_bucket clamp-down fix (ISSUE
                # 13 satellite): a prompt longer than the largest
                # configured seq bucket used to clamp DOWN and blow up
                # mid-launch — route it through the partial-prefix chunk
                # step instead, which splits it across launches
                req.state = "prefilling"
                self._prefilling.append(req)
            else:
                dense.append(req)
        groups = {}
        for req in dense:
            sb = pick_bucket(len(req.effective_prompt()),
                             self.prefill_seq_buckets)
            groups.setdefault(sb, []).append(req)
        step_rows = min(self.max_slots, self.prefill_batch_buckets[-1])
        for sb, reqs in sorted(groups.items()):
            i = 0
            while i < len(reqs):
                chunk = reqs[i:i + step_rows]
                i += step_rows
                self._prefill_batch(chunk, sb)
        if self.prefill_chunk is None:
            # prefix-hit tails finish within the admission round (only
            # chunked mode spreads prefill across rounds)
            while self._prefilling:
                self._run_chunk_batch()

    def _build_prefill(self):
        """The compiled prefill: the dense causal forward with params as
        real arguments (same no-giant-closure treatment as the decode
        step), returning logits + per-layer K/V for the pool writes.
        jax.jit specializes one program per (batch, seq) bucket shape."""
        model, params = self.model, self._params
        L = self.cfg.num_layers

        def prefill(arrays, ids):
            with no_grad(), _swap_params(params, arrays):
                caches = [{"k": None, "v": None} for _ in range(L)]
                logits = model(Tensor(ids), caches=caches)
                return (logits._data,
                        [c["k"]._data for c in caches],
                        [c["v"]._data for c in caches])

        return jax.jit(prefill) if self._jit else prefill

    def _build_chunk_prefill(self):
        """The compiled chunk step: write one chunk of tokens per row into
        the row's pages, then partial-prefix attention over the pages
        (chunk tokens + everything previously written). Same params-as-
        arguments treatment as the decode step; pools are donated on TPU.
        jax.jit specializes per (batch bucket, chunk bucket) shape."""
        model, params = self.model, self._params
        L = self.cfg.num_layers
        prefill_impl = self._prefill_attn_impl
        attn_impl = self._attn_impl

        def chunk_step(arrays, tokens, positions, lens, bt, k_pools,
                       v_pools):
            with no_grad(), _swap_params(params, arrays):
                caches = [{"paged": True,
                           "k_pool": Tensor(k_pools[i]),
                           "v_pool": Tensor(v_pools[i]),
                           "block_tables": Tensor(bt),
                           "positions": Tensor(positions),
                           "chunk_lens": Tensor(lens),
                           "attn_impl": attn_impl,
                           "prefill_impl": prefill_impl}
                          for i in range(L)]
                logits = model(Tensor(tokens), caches=caches,
                               pos_offset=Tensor(positions))
                return (logits._data,
                        [c["k_pool"]._data for c in caches],
                        [c["v_pool"]._data for c in caches])

        if not self._jit:
            return chunk_step
        if _decode.on_tpu():
            return jax.jit(chunk_step, donate_argnums=(5, 6))
        return jax.jit(chunk_step)

    def _run_chunk_batch(self):
        """Advance pending prefills by ONE batched chunk launch: up to
        ``budget // chunk`` requests (FIFO) each contribute their next
        chunk. Requests whose prompt completes emit their first token and
        join the decode batch the same round."""
        self._prefilling = [r for r in self._prefilling
                            if r.state == "prefilling"]
        pending = self._prefilling
        if not pending:
            return 0
        tr = _trc._TR if _trc._loaded else _trc._load()
        t0 = time.time() if tr is not None else 0.0
        cap = self.prefill_chunk
        # never take more rows than the largest batch bucket can carry
        # (pick_bucket clamps DOWN to its largest entry; a batch wider
        # than that would index past the padded launch)
        max_rows = min(self.max_slots, self.prefill_batch_buckets[-1])
        if cap is not None:
            rows = max(1, self._prefill_budget // cap)
            batch = pending[:min(rows, max_rows)]
        else:
            batch = pending[:max_rows]
        longest = max(len(r.effective_prompt()) - r.num_cached
                      for r in batch)
        want = min(cap, longest) if cap is not None else longest
        sb = pick_bucket(want, self._chunk_buckets)
        # batch was pre-clamped to the largest batch bucket above;
        # strict turns any future violation into a loud error instead of
        # a silent clamp-down that truncates the round
        nb = pick_bucket(len(batch), self.prefill_batch_buckets,
                         strict=True)
        tokens = np.zeros((nb, sb), np.int32)
        positions = np.zeros(nb, np.int32)
        lens = np.zeros(nb, np.int32)
        bt = np.zeros((nb, self.max_pages), np.int32)
        prompts = []
        for i, req in enumerate(batch):
            p = req.effective_prompt()
            prompts.append(p)
            take = len(p) - req.num_cached
            if cap is not None:
                take = min(take, cap)
            take = min(take, sb)
            seg = p[req.num_cached:req.num_cached + take]
            tokens[i, :take] = seg
            positions[i] = req.num_cached
            lens[i] = take
            bt[i, :len(req.pages)] = req.pages
        self._chunk_fns.setdefault((nb, sb), self._chunk_fn)
        self._note_program(("chunk", nb, sb))
        logits_arr, self.kv.k, self.kv.v = self._chunk_fn(
            self._param_arrays, jnp.asarray(tokens),
            jnp.asarray(positions), jnp.asarray(lens), jnp.asarray(bt),
            list(self.kv.k), list(self.kv.v))
        spent = 0
        for i, req in enumerate(batch):
            take = int(lens[i])
            req.num_cached += take
            spent += take
            if req.num_cached < len(prompts[i]):
                continue
            # prompt complete: last chunk's final logit row is the first
            # generated token, and the prompt's full pages become
            # shareable for future prefix-cache hits
            # tpu-lint: ok[HS002] designed sync: host-side sampling consumes this logit row once per completed prompt
            row = np.asarray(logits_arr[i, take - 1])
            self._finish_prompt(req, prompts[i], _select_token(row, req))
        self._chunk_tokens += spent
        self.metrics.on_prefill_chunk(spent)
        if tr is not None:
            now = time.time()
            for i, req in enumerate(batch):
                if req.trace is not None:
                    _trc.req_event(req.trace, "prefill_chunk", t0,
                                   now - t0,
                                   args={"tokens": int(lens[i]),
                                         "cached": req.num_cached})
        return spent

    def _prefill_batch(self, reqs, seq_bucket):
        """Dense causal forward at [batch_bucket, seq_bucket]; right
        padding is causal-safe (position i never attends j > i), so each
        row's first `len` K/V rows are exact. Jitted per bucket pair —
        prompts of different lengths share the bucket's one program."""
        n = len(reqs)
        tr = _trc._TR if _trc._loaded else _trc._load()
        t0 = time.time() if tr is not None else 0.0
        # strict: the caller split the round by the largest batch bucket,
        # so a clamp-down here could only mean indexing past the pad
        nb = pick_bucket(n, self.prefill_batch_buckets, strict=True)
        ids = np.zeros((nb, seq_bucket), np.int64)
        lens, prompts = [], []
        for i, req in enumerate(reqs):
            p = req.effective_prompt()
            prompts.append(p)
            ids[i, :len(p)] = p
            lens.append(len(p))
        self._prefill_fns.setdefault((nb, seq_bucket), self._prefill_fn)
        self._note_program(("prefill", nb, seq_bucket))
        logits_arr, ks, vs = self._prefill_fn(self._param_arrays,
                                              jnp.asarray(ids))
        for i, req in enumerate(reqs):
            ln = lens[i]
            for layer in range(self.cfg.num_layers):
                self.kv.write_prefill(layer, ks[layer][i],
                                      vs[layer][i], req.pages, ln)
            req.num_cached = ln
            if tr is not None and req.trace is not None:
                _trc.req_event(req.trace, "prefill_chunk", t0,
                               time.time() - t0,
                               args={"tokens": ln, "dense": True})
            # tpu-lint: ok[HS002] designed sync: host-side sampling consumes this logit row once per prefilled request
            row = np.asarray(logits_arr[i, ln - 1])
            self._finish_prompt(req, prompts[i], _select_token(row, req))

    # ---------------------------------------------------------- decode step
    def _decode_once(self, active):
        self._note_program(("decode",))
        tr = _trc._TR if _trc._loaded else _trc._load()
        t0 = time.time() if tr is not None else 0.0
        S, maxp = self.max_slots, self.max_pages
        tokens = np.zeros(S, np.int32)
        positions = np.zeros(S, np.int32)
        bt = np.zeros((S, maxp), np.int32)
        any_sampling = False
        for slot, req in active.items():
            tokens[slot] = req.generated[-1]
            positions[slot] = req.num_cached
            bt[slot, :len(req.pages)] = req.pages
            any_sampling |= req.temperature > 0.0
        nxt, last, self.kv.k, self.kv.v = self._step_fn(
            self._param_arrays, jnp.asarray(tokens),
            jnp.asarray(positions), jnp.asarray(bt),
            list(self.kv.k), list(self.kv.v))
        # tpu-lint: ok[HS002] designed sync: ONE batched token fetch per decode round feeds host-side sampling
        nxt = np.asarray(nxt)
        # tpu-lint: ok[HS002] designed sync: the logits rows ride the same per-round host sampling fetch
        logits_np = np.asarray(last) \
            if (any_sampling or self.capture_logits is not None) else None
        if self.capture_logits is not None:
            self.capture_logits.append(
                (dict((s, r.request_id) for s, r in active.items()),
                 logits_np))
        by_slot = {}
        for slot, req in active.items():
            if req.temperature > 0.0:
                by_slot[slot] = _select_token(logits_np[slot], req)
            else:
                by_slot[slot] = int(nxt[slot])
        finished = self.scheduler.complete_step(by_slot)
        for slot, req in active.items():
            tt = req.token_times
            self.metrics.on_token(
                req, tt[-1] - tt[-2] if len(tt) >= 2 else None)
        for req in finished:
            self.metrics.on_finish(req)
        if tr is not None:
            tr.add("decode_round", t0, time.time() - t0, cat="serving",
                   args={"decode_rows": len(by_slot)})
        self._decode_tokens += len(by_slot)
        return len(by_slot)

    # ------------------------------------------------------------ stepping
    def _step_bucketed(self):
        """The bucketed fallback round (pre-ISSUE-13 shape): dense/chunk
        prefill launches, then ONE fixed-slot decode step."""
        admitted = self.scheduler.schedule()
        if admitted:
            self._prefill_admitted(admitted)
        if self.prefill_chunk is not None and self._prefilling:
            # budgeted interleave: one bounded chunk launch per round
            self._run_chunk_batch()
        _, evicted = self.scheduler.ensure_decode_capacity()
        for req in evicted:
            self.metrics.on_evict(req)
        active = {slot: r for slot, r in self.scheduler.active.items()
                  if r.state == "active"}
        return self._decode_once(active) if active else 0

    def step(self):
        """One scheduler round -> decode tokens emitted (0 when idle).
        Ragged (default): admission, budgeted prefill chunks and every
        active row's decode token ride ONE flat launch of one program.
        Bucketed fallback: dense/chunked prefill launches then the
        fixed-slot decode step. Either way a newcomer prefilling never
        stalls in-flight rows — the gap between two decode steps is
        bounded by the chunk budget, not by the longest prompt in the
        queue."""
        if self._loop_error is not None:
            raise EngineClosed(
                f"engine unhealthy: serve loop crashed with "
                f"{type(self._loop_error).__name__}: {self._loop_error}"
            ) from self._loop_error
        if self._closed:
            raise EngineClosed("engine is closed")
        with self._step_lock:
            emitted = self._step_ragged() if self.ragged \
                else self._step_bucketed()
            occ = self.kv.occupancy_pct()
            self._peak_occupancy = max(self._peak_occupancy, occ)
            alloc = self.kv.allocator
            share = getattr(self.prefix, "share", None)
            self.metrics.sample_state(
                len(self.scheduler.active), self.scheduler.queue_depth(),
                occ,
                shared_pages=alloc.shared_pages() if self.prefix else None,
                cached_pages=alloc.cached_pages if self.prefix else None,
                remote_hits=share.remote_hits if share else None,
                remote_hit_tokens=share.remote_hit_tokens
                if share else None)
            self._steps += 1
            return emitted

    def run_until_idle(self, max_steps=100000):
        steps = 0
        while self.scheduler.has_work():
            self.step()
            steps += 1
            if steps >= max_steps:
                raise RuntimeError(
                    f"run_until_idle exceeded {max_steps} steps")
        return steps

    # ------------------------------------------------------------- serving
    def submit(self, prompt_ids, max_new_tokens=16, eos_token_id=None,
               temperature=0.0, top_k=None, on_token=None, block=True,
               timeout=10.0, on_done=None):
        """Queue one request (backpressure: blocks up to ``timeout`` for
        queue space, then raises :class:`~.scheduler.QueueFull`)."""
        req = GenerationRequest(prompt_ids, max_new_tokens=max_new_tokens,
                                eos_token_id=eos_token_id,
                                temperature=temperature, top_k=top_k,
                                on_token=on_token, on_done=on_done)
        return self.submit_request(req, block=block, timeout=timeout)

    def _check_accepting(self):
        if self._draining:
            raise EngineShuttingDown("engine is shutting down")
        if self._loop_error is not None:
            raise EngineClosed(
                f"engine unhealthy: serve loop crashed with "
                f"{type(self._loop_error).__name__}: {self._loop_error}"
            ) from self._loop_error
        if self._closed:
            raise EngineClosed("engine is closed")

    def submit_request(self, req, block=True, timeout=10.0):
        """Queue an already-built :class:`~.scheduler.GenerationRequest`
        (the fleet router builds its own legs so it can wire ``on_done``
        re-dispatch before the engine ever sees them)."""
        self._check_accepting()
        self.scheduler.submit(req, block=block, timeout=timeout)
        self._wake.set()
        return req

    # --------------------------------------------------- fleet migration
    def snapshot_kv(self, req):
        """Host copy of one request's written KV (``req.num_cached``
        tokens): ``(k_layers, v_layers, length)`` with each layer a
        ``[length, KVH, Dh]`` numpy array. Read-only on the pools (shared
        prefix pages included), serialized against rounds — the page
        migration payload of the disaggregated fleet."""
        with self._step_lock:
            length = int(req.num_cached)
            # tpu-lint: ok[HS002] page migration IS the designed host roundtrip: one gather per layer moves this request's KV off-device
            ks = [np.asarray(self.kv.gather(l, req.pages, length, "k"))
                  for l in range(self.cfg.num_layers)]
            # tpu-lint: ok[HS002] second half of the same migration payload (V pools ride the same deliberate roundtrip)
            vs = [np.asarray(self.kv.gather(l, req.pages, length, "v"))
                  for l in range(self.cfg.num_layers)]
        return ks, vs, length

    def release_request(self, req):
        """Detach a migrating request from this engine: free its slot and
        pages (a deref — shared prefix pages keep their other readers)
        WITHOUT finishing it. The caller adopts it elsewhere."""
        with self._step_lock:
            self.scheduler.release_for_migration(req)
            if req in self._prefilling:
                self._prefilling.remove(req)

    def adopt_request(self, req, k_layers, v_layers, length):
        """Admit a migrated request with its KV pages pre-populated: the
        block-table rebind half of fleet page migration. Allocates pages
        for ``length`` tokens, writes the payload into this engine's
        pools, and joins the decode batch directly — the continuation
        consumes ``req.generated[-1]`` at position ``length``, exactly
        the step the source engine would have run next. Raises
        :class:`~.kv_cache.OutOfPages` / :class:`~.scheduler.OutOfSlots`
        when this pool/batch cannot take it (caller falls back to
        :meth:`readmit_request`)."""
        from .kv_cache import pages_for as _pages_for
        with self._step_lock:
            self._check_accepting()
            pages = self.kv.allocator.alloc(
                max(1, _pages_for(length, self.page_size)))
            try:
                for layer in range(self.cfg.num_layers):
                    self.kv.write_prefill(layer, k_layers[layer],
                                          v_layers[layer], pages, length)
                req.pages = pages
                req.num_cached = int(length)
                self.scheduler.admit_prepared(req)
            except Exception:
                self.kv.allocator.free(pages)
                req.pages = []
                raise
            self.metrics.on_adopt(req)
        self._wake.set()
        return req

    def readmit_request(self, req):
        """Recompute fallback for a migrated request: re-queue it at the
        front — admission re-prefills ``effective_prompt()`` (greedy
        continuation is token-identical, same contract as eviction)."""
        self._check_accepting()
        self.scheduler.readmit(req)
        self._wake.set()
        return req

    def abort_request(self, req):
        """Cancel one leg without firing its waiters (ISSUE 16 hedging:
        the router duplicated this request on another engine and the
        duplicate won — the loser's slot + pages free immediately, its
        ``on_done`` never fires, and the winning leg owns the caller's
        done event). Serialized against rounds so a mid-step slot/page
        assignment can never be torn. Returns False when the leg already
        reached a terminal state first (it finished fair and square —
        its completion is the one the router keeps)."""
        with self._step_lock:
            if not self.scheduler.abort_request(req):
                return False
            if req in self._prefilling:
                self._prefilling.remove(req)
        return True

    def prefetch_prefix(self, tokens):
        """Warm this engine's prefix cache with a prompt head published
        elsewhere in the fleet (router prefetch-on-affinity-spill): walk
        the shared trie, import the remote pages into the LOCAL pool,
        then drop the lookup references so the pages park indexed +
        reclaimable — the session's next request here prefix-hits
        locally instead of paying the import on its admission path.
        -> number of pages imported (0 without a share client)."""
        share = getattr(self.prefix, "share", None)
        if share is None or self._closed or self._draining:
            return 0
        with self._step_lock:
            t0 = share.remote_hit_tokens
            # tpu-lint: ok[LK002] the store fetch is bounded by the share client's fetch timeout and the lock is required: lookup mutates allocator refcounts and imports pages into the pools, exactly like the admission-path lookup step() runs under this same lock
            pages, _n = self.prefix.lookup(tokens)
            if pages:
                # lookup took one reader ref per page for an admission
                # that is not happening — release them; the trie keeps
                # the pages indexed (reclaimable, hit-ready)
                self.kv.allocator.free(pages)
            imported = (share.remote_hit_tokens - t0) // self.page_size
        if imported:
            self.metrics.on_prefetch_pages(imported)
        return imported

    def generate(self, prompt_ids, timeout=120.0, **kw):
        """Synchronous helper: submit + drive (foreground when no serve
        thread is running) + wait. -> generated token list."""
        req = self.submit(prompt_ids, **kw)
        if self._thread is None:
            self.run_until_idle()
        return req.result(timeout=timeout)

    def start(self):
        """Background serve loop (idempotent)."""
        if self._thread is not None:
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(target=self._serve_loop,
                                        name="paddle-tpu-serving",
                                        daemon=True)
        self._thread.start()

    def _serve_loop(self):
        from ..distributed.fault import maybe_inject as _inject
        # serving chaos (ISSUE 16): PADDLE_TPU_FAULT_ENGINE narrows the
        # serve_loop site to ONE engine id so a multi-engine process
        # kills a chosen replica deterministically (the trigger counter
        # is process-global; without the filter, whichever serve thread
        # hit the site Nth would die)
        target = os.environ.get("PADDLE_TPU_FAULT_ENGINE")
        honored = target in (None, "") or target == str(self.engine_id)
        while not self._stop_evt.is_set():
            try:
                if honored and _inject("serve_loop") == "engine_die":
                    raise RuntimeError(
                        "injected fault: engine_die@serve_loop")
                if self.scheduler.has_work():
                    self.step()
                else:
                    self._wake.wait(0.02)
                    self._wake.clear()
            except Exception as e:
                # a broken step is terminal, not a silent hang: fail every
                # queued + in-flight waiter with the ACTUAL error and mark
                # the engine unhealthy so later submit()s fail fast naming
                # it (graceful degradation — callers can route elsewhere)
                self._loop_error = e
                self._closed = True
                self.scheduler.close(error=e)
                print(f"[serving] serve loop crashed; engine unhealthy: "
                      f"{type(e).__name__}: {e}", file=sys.stderr,
                      flush=True)
                break

    def stop(self, timeout=10.0):
        self._stop_evt.set()
        self._wake.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout)

    def close(self):
        """Stop the loop and fail everything still queued or in flight —
        same contract as ``BatchingPredictor.close``."""
        if self._closed:
            return
        self._closed = True
        self.stop()
        self.scheduler.close()

    # -------------------------------------------------- graceful shutdown
    def shutdown(self, drain_s=30.0):
        """SIGTERM-grade graceful shutdown (ISSUE 10 satellite), the
        serving twin of the training tier's exit-75 preemption save:

        1. stop admitting — later ``submit``\\ s and every QUEUED request
           fail with the named :class:`~.scheduler.EngineShuttingDown`
           status (they never started; safe to retry elsewhere), not the
           indiscriminate bare close;
        2. drain in-flight decodes up to ``drain_s`` seconds — requests
           mid-generation finish normally;
        3. fail whatever missed the deadline, then flush the serving
           metrics JSONL so the final counters land on disk before exit.

        Idempotent; returns a summary dict. ``close()`` afterwards is a
        no-op. Serialized: a concurrent second call (the SIGTERM watcher
        racing a user-initiated shutdown) blocks until the in-progress
        drain finishes, then sees ``_closed`` and returns the empty
        summary — two threads must never both drive ``step()``."""
        with self._shutdown_lock:
            return self._shutdown_locked(drain_s)

    def _shutdown_locked(self, drain_s):
        if self._closed:
            return {"drained_tokens": 0, "failed_queued": 0,
                    "failed_inflight": 0}
        self._draining = True
        self.stop()  # join the serve loop; we drive the drain inline
        queued = self.scheduler.begin_shutdown()
        for req in queued:
            # rejected-at-queue is a terminal state too: the flushed
            # counters must show these requests, not a clean drain
            self.metrics.on_finish(req)
        deadline = time.monotonic() + max(0.0, float(drain_s))
        drained = 0
        # drain on has_work, not just active: KV pressure can EVICT an
        # in-flight request back onto the waiting queue mid-drain, and it
        # deserves its remaining budget (schedule() re-admits it — the
        # shutdown gate closed submit(), not the internal readmit path)
        while self.scheduler.has_work() and time.monotonic() < deadline:
            drained += self.step()
        missed = [r for r in self.scheduler.active.values()
                  if r.state in ("active", "prefilling")]
        # evicted mid-drain and never re-admitted: close out the pending
        # queue-wait segment (same honesty rule as begin_shutdown) before
        # close() stamps them failed
        now = time.perf_counter()
        for req in self.scheduler.waiting:
            req.queue_wait_s += now - req.t_enqueue
        missed += list(self.scheduler.waiting)
        self._closed = True
        self.scheduler.close(error=EngineShuttingDown(
            f"engine shut down before this request finished "
            f"(drain deadline {drain_s:.0f}s)"))
        for req in missed:
            self.metrics.on_finish(req)
        reg = self.metrics._reg
        if reg is not None:
            try:
                reg.flush()
            except Exception:
                pass
        out = {"drained_tokens": drained, "failed_queued": len(queued),
               "failed_inflight": len(missed)}
        print(f"[serving] graceful shutdown: {out}", flush=True)
        return out

    def install_sigterm(self, drain_s=None):
        """Wire SIGTERM to the training-tier convention: graceful drain
        (:meth:`shutdown`), then exit ``EXIT_PREEMPT`` (75) so the same
        launcher/orchestrator policy that resumes preempted trainers
        treats a drained server as resumable, not failed. ``drain_s``
        defaults to ``PADDLE_TPU_SERVING_DRAIN_S`` (30). Returns True if
        the handler was installed (main thread only).

        The handler itself only sets the preemption flag (the fault
        module's safe flag-only mode); the drain runs on a dedicated
        watcher thread. Running ``shutdown()`` inside the signal frame
        would self-deadlock if SIGTERM lands while the interrupted main
        thread holds the scheduler's (non-reentrant) admission lock —
        the exact hazard ``install_preemption_handler``'s docstring
        names for mid-collective saves."""
        from ..distributed import fault as _fault
        if drain_s is None:
            drain_s = float(os.environ.get(
                "PADDLE_TPU_SERVING_DRAIN_S", "30"))
        if not _fault.install_preemption_handler():
            return False

        def _watch():
            while not self._closed:
                if _fault.preempted():
                    # the exit must happen even if the drain raises (a
                    # racing close(), a decode error): a dead watcher
                    # thread would swallow the SIGTERM entirely and the
                    # orchestrator's grace window would end in SIGKILL
                    # with no metrics flush and no exit-75 classification
                    try:
                        self.shutdown(drain_s=drain_s)
                    finally:
                        sys.stdout.flush()
                        sys.stderr.flush()
                        os._exit(_fault.EXIT_PREEMPT)
                time.sleep(0.1)

        threading.Thread(target=_watch, daemon=True,
                         name="serving-sigterm-drain").start()
        return True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # --------------------------------------------------------------- stats
    def stats(self):
        out = {
            "engine_id": self.engine_id,
            "steps": self._steps,
            "decode_tokens": self._decode_tokens,
            "evictions": self.scheduler.total_evictions,
            "kv_occupancy_pct": round(self.kv.occupancy_pct(), 2),
            "kv_occupancy_peak_pct": round(self._peak_occupancy, 2),
            "active": len(self.scheduler.active),
            "queued": self.scheduler.queue_depth(),
            "attn_backend": self.attn_backend,
            "attn_ab": self.attn_ab,
            "num_kv_heads": self.num_kv_heads,
            "prefill_chunk": self.prefill_chunk,
            "prefill_chunk_tokens": self._chunk_tokens,
            "ragged": self.ragged,
            "distinct_programs": len(self._programs),
            "ragged_token_pads": sorted(self._ragged_shapes),
        }
        if self.prefix is not None:
            out.update({
                "prefix_hits": self.prefix.hits,
                "prefix_misses": self.prefix.misses,
                "prefix_hit_rate": round(self.prefix.hit_rate(), 4),
                "prefix_hit_tokens": self.prefix.hit_tokens,
                "prefix_cached_pages": self.kv.allocator.cached_pages,
                "prefix_shared_pages": self.kv.allocator.shared_pages(),
                "prefix_reclaimed_pages": self.prefix.reclaimed_pages,
            })
            share = getattr(self.prefix, "share", None)
            if share is not None:
                out.update({
                    "prefix_remote_hits": share.remote_hits,
                    "prefix_remote_hit_tokens": share.remote_hit_tokens,
                    "prefix_published_pages": share.published,
                })
        return out
