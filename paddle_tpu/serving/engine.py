"""Continuous-batching serving engine: prefill/decode split over paged KV.

The millions-of-users tier (ROADMAP item 3; SURVEY layer 11). A
:class:`ServingEngine` wraps a GPT-family ``models.gpt.GPTForCausalLM``
and runs it as a concurrent serving loop:

* **prefill** — newly admitted requests run the dense causal forward at
  bucketed shapes (batch buckets AND sequence buckets share
  ``inference.pick_bucket`` with :class:`~paddle_tpu.inference.
  BatchingPredictor`, whose pad-to-bucket idea this generalizes),
  compiled ONCE per (batch, seq) bucket pair with ``jax.jit`` (the
  bucket sets bound the compile cache; eager per-op dispatch no longer
  sits on TTFT), their K/V is written into pages of the shared pool,
  and the first token streams out (TTFT ends here).
* **decode** — ONE fixed-shape step over all ``max_slots`` slots: embed
  the last token of every row at its own absolute position, scatter its
  K/V into the pool, paged attention over each row's block table, greedy
  argmax on device (host-side temperature/top-k sampling per request when
  asked). Compiled once with ``jax.jit`` — params, block tables and pools
  are arguments, pools are donated on TPU, so steady-state decode is one
  XLA program launch per token regardless of admission churn.
* **scheduling** — between steps the
  :class:`~.scheduler.ContinuousBatchingScheduler` finishes / evicts /
  admits, so a request arriving mid-stream joins the next step without
  stalling in-flight rows (the no-decode-gap acceptance test).

The paged-attention backend is A/B gated (``serving/decode.py``): Pallas
only where it measurably beats the XLA reference at the serving shape;
``PADDLE_TPU_SERVING_ATTN`` overrides. Pass ``mesh=`` to shard the decode
along KV heads over the fleet mesh's ``model`` axis for multi-chip
serving.

Metrics flow through the PR-5 registry via :class:`~.metrics.
ServingMetrics`; ``bench.py --serving`` drives a Poisson open-loop load
(``serving/load.py``) and records tokens/s + tail latency.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import no_grad
from ..core.tensor import Tensor
from ..inference import pick_bucket
from . import decode as _decode
from .kv_cache import PagedKVCache, pages_for
from .metrics import ServingMetrics
from .scheduler import (ContinuousBatchingScheduler, EngineClosed,
                        GenerationRequest)

__all__ = ["ServingEngine"]


@contextlib.contextmanager
def _swap_params(params, arrays):
    """Temporarily back the model's Parameters with (traced) arrays so the
    decode step jits with weights as real arguments — no giant closure
    constants, donation-friendly."""
    olds = [p._data for p in params]
    for p, a in zip(params, arrays):
        p._data = a
    try:
        yield
    finally:
        for p, o in zip(params, olds):
            p._data = o


def _select_token(logits_row, req):
    """Host-side sampling for one request: greedy at temperature 0, else
    temperature + optional top-k from the request's own seeded RNG (the
    decode batch stays deterministic per request, not per step)."""
    if req.temperature <= 0.0:
        return int(np.argmax(logits_row))
    z = logits_row.astype(np.float64) / max(req.temperature, 1e-6)
    if req.top_k is not None:
        kth = np.partition(z, -int(req.top_k))[-int(req.top_k)]
        z = np.where(z < kth, -np.inf, z)
    z = z - z.max()
    p = np.exp(z)
    p /= p.sum()
    return int(req.rng().choice(len(p), p=p))


class ServingEngine:
    """Continuous-batching inference over a paged KV cache.

    Synchronous use (tests, batch jobs)::

        eng = ServingEngine(model, page_size=16, num_pages=64, max_slots=4)
        tokens = eng.generate([1, 2, 3], max_new_tokens=8)

    Concurrent serving (streaming callbacks + backpressure)::

        with ServingEngine(model, ...) as eng:
            eng.start()
            req = eng.submit(prompt, on_token=lambda r, t, fin: push(t))
            req.result(timeout=30)
    """

    def __init__(self, model, page_size=16, num_pages=64, max_slots=4,
                 max_queue=256, prefill_seq_buckets=None,
                 prefill_batch_buckets=None, attn_backend=None, mesh=None,
                 mesh_axis="model", jit=True, registry=None):
        cfg = model.config
        self.model = model
        self.model.eval()
        self.cfg = cfg
        self.page_size = int(page_size)
        self.max_slots = int(max_slots)
        self.max_pages = pages_for(cfg.max_seq_len, self.page_size)
        H = cfg.num_heads
        Dh = cfg.hidden_size // H
        dt = model.gpt.wte.weight._data.dtype
        self.kv = PagedKVCache(cfg.num_layers, int(num_pages),
                               self.page_size, H, Dh, dtype=dt)
        self.scheduler = ContinuousBatchingScheduler(
            self.kv.allocator, self.max_slots, self.page_size,
            cfg.max_seq_len, max_queue=max_queue)
        self.metrics = ServingMetrics(registry=registry)
        # seq buckets cap padding waste at ~2x; batch buckets keep the
        # prefill compile cache small (one shape per bucket pair)
        if prefill_seq_buckets is None:
            prefill_seq_buckets, b = [], 16
            while b < cfg.max_seq_len:
                prefill_seq_buckets.append(b)
                b *= 2
            prefill_seq_buckets.append(cfg.max_seq_len)
        self.prefill_seq_buckets = sorted(set(prefill_seq_buckets))
        self.prefill_batch_buckets = sorted(set(
            prefill_batch_buckets or [1, 2, 4, self.max_slots]))
        # ---- paged-attention backend (A/B gated; standing kernel rule)
        requested = _decode.resolve_backend(attn_backend)
        self.attn_ab = None
        if requested == "auto":
            self.attn_ab = self._run_ab_gate()
            self.attn_backend = self.attn_ab["backend"]
        else:
            self.attn_backend = requested
        if mesh is not None and int(mesh.shape.get(mesh_axis, 1)) > 1 \
                and H % int(mesh.shape[mesh_axis]) != 0:
            raise ValueError(
                f"{H} heads not divisible by mesh axis "
                f"{mesh_axis}={mesh.shape[mesh_axis]}")
        if mesh is not None:
            self._attn_impl = _decode.sharded_paged_attention(
                mesh, axis_name=mesh_axis, backend=self.attn_backend)
        else:
            backend = self.attn_backend
            self._attn_impl = lambda q, kp, vp, bt, lens: \
                _decode.paged_decode_attention(q, kp, vp, bt, lens,
                                               backend=backend)
        self._params = list(model.parameters())
        self._param_arrays = [p._data for p in self._params]
        self._jit = bool(jit)
        self._step_fn = self._build_step()
        # prefill compiles once per (batch bucket, seq bucket) pair — ONE
        # jitted callable (jax's cache specializes per bucket shape), with
        # the pairs it has served tracked in _prefill_fns so the
        # bounded-compile contract is observable (tested); steady-state
        # prefill dispatch is one compiled-program launch instead of the
        # eager per-op tunnel that used to sit on TTFT (ROADMAP item 3)
        self._prefill_fn = self._build_prefill()
        self._prefill_fns = {}
        self._steps = 0
        self._decode_tokens = 0
        self.capture_logits = None   # tests: a list collects per-step
        # [S, V] decode logits (forces a host fetch; leave None in prod)
        self._peak_occupancy = 0.0
        self._thread = None
        self._stop_evt = threading.Event()
        self._wake = threading.Event()
        self._closed = False

    # ------------------------------------------------------------ A/B gate
    def _run_ab_gate(self):
        """Measure XLA vs Pallas at this engine's decode shape; 'auto'
        resolves to the winner (Pallas never wins off-TPU)."""
        H, Dh = self.cfg.num_heads, self.cfg.hidden_size // self.cfg.num_heads
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (self.max_slots, H, Dh),
                              self.kv.dtype)
        bt = np.zeros((self.max_slots, self.max_pages), np.int32)
        lens = np.full((self.max_slots,),
                       min(self.page_size, self.cfg.max_seq_len), np.int32)
        return _decode.ab_compare(q, self.kv.k[0], self.kv.v[0], bt, lens)

    # ----------------------------------------------------------- decode fn
    def _build_step(self):
        model, params = self.model, self._params
        L = self.cfg.num_layers
        attn_impl = self._attn_impl

        def step(arrays, tokens, positions, bt, k_pools, v_pools):
            with no_grad(), _swap_params(params, arrays):
                caches = [{"paged": True,
                           "k_pool": Tensor(k_pools[i]),
                           "v_pool": Tensor(v_pools[i]),
                           "block_tables": Tensor(bt),
                           "positions": Tensor(positions),
                           "attn_impl": attn_impl}
                          for i in range(L)]
                logits = model(Tensor(tokens[:, None]), caches=caches,
                               pos_offset=Tensor(positions))
                last = logits._data[:, -1]
                nxt = jnp.argmax(last, axis=-1).astype(jnp.int32)
                return (nxt, last,
                        [c["k_pool"]._data for c in caches],
                        [c["v_pool"]._data for c in caches])

        if not self._jit:
            return step
        # donation saves the pool double-buffer on TPU; CPU/older
        # backends warn and ignore it, so only ask where it works
        if _decode.on_tpu():
            return jax.jit(step, donate_argnums=(4, 5))
        return jax.jit(step)

    # ------------------------------------------------------------- prefill
    def _prefill_admitted(self, admitted):
        groups = {}
        for req in admitted:
            self.metrics.on_admit(req)
            sb = pick_bucket(len(req.effective_prompt()),
                             self.prefill_seq_buckets)
            groups.setdefault(sb, []).append(req)
        for sb, reqs in sorted(groups.items()):
            i = 0
            while i < len(reqs):
                chunk = reqs[i:i + self.max_slots]
                i += self.max_slots
                self._prefill_batch(chunk, sb)

    def _build_prefill(self):
        """The compiled prefill: the dense causal forward with params as
        real arguments (same no-giant-closure treatment as the decode
        step), returning logits + per-layer K/V for the pool writes.
        jax.jit specializes one program per (batch, seq) bucket shape."""
        model, params = self.model, self._params
        L = self.cfg.num_layers

        def prefill(arrays, ids):
            with no_grad(), _swap_params(params, arrays):
                caches = [{"k": None, "v": None} for _ in range(L)]
                logits = model(Tensor(ids), caches=caches)
                return (logits._data,
                        [c["k"]._data for c in caches],
                        [c["v"]._data for c in caches])

        return jax.jit(prefill) if self._jit else prefill

    def _prefill_batch(self, reqs, seq_bucket):
        """Dense causal forward at [batch_bucket, seq_bucket]; right
        padding is causal-safe (position i never attends j > i), so each
        row's first `len` K/V rows are exact. Jitted per bucket pair —
        prompts of different lengths share the bucket's one program."""
        n = len(reqs)
        nb = pick_bucket(n, self.prefill_batch_buckets)
        ids = np.zeros((nb, seq_bucket), np.int64)
        lens = []
        for i, req in enumerate(reqs):
            p = req.effective_prompt()
            ids[i, :len(p)] = p
            lens.append(len(p))
        self._prefill_fns.setdefault((nb, seq_bucket), self._prefill_fn)
        logits_arr, ks, vs = self._prefill_fn(self._param_arrays,
                                              jnp.asarray(ids))
        for i, req in enumerate(reqs):
            ln = lens[i]
            for layer in range(self.cfg.num_layers):
                self.kv.write_prefill(layer, ks[layer][i],
                                      vs[layer][i], req.pages, ln)
            req.num_cached = ln
            row = np.asarray(logits_arr[i, ln - 1])
            tok = _select_token(row, req)
            first = not req.generated
            req.emit(tok)
            if first:
                self.metrics.on_first_token(req)
            self.metrics.on_token(req)
            if req.hit_stop():
                self.scheduler.finish(req)
                self.metrics.on_finish(req)

    # ---------------------------------------------------------- decode step
    def _decode_once(self, active):
        S, maxp = self.max_slots, self.max_pages
        tokens = np.zeros(S, np.int32)
        positions = np.zeros(S, np.int32)
        bt = np.zeros((S, maxp), np.int32)
        any_sampling = False
        for slot, req in active.items():
            tokens[slot] = req.generated[-1]
            positions[slot] = req.num_cached
            bt[slot, :len(req.pages)] = req.pages
            any_sampling |= req.temperature > 0.0
        nxt, last, self.kv.k, self.kv.v = self._step_fn(
            self._param_arrays, jnp.asarray(tokens),
            jnp.asarray(positions), jnp.asarray(bt),
            list(self.kv.k), list(self.kv.v))
        nxt = np.asarray(nxt)
        logits_np = np.asarray(last) \
            if (any_sampling or self.capture_logits is not None) else None
        if self.capture_logits is not None:
            self.capture_logits.append(
                (dict((s, r.request_id) for s, r in active.items()),
                 logits_np))
        by_slot = {}
        for slot, req in active.items():
            if req.temperature > 0.0:
                by_slot[slot] = _select_token(logits_np[slot], req)
            else:
                by_slot[slot] = int(nxt[slot])
        finished = self.scheduler.complete_step(by_slot)
        for slot, req in active.items():
            tt = req.token_times
            self.metrics.on_token(
                req, tt[-1] - tt[-2] if len(tt) >= 2 else None)
        for req in finished:
            self.metrics.on_finish(req)
        self._decode_tokens += len(by_slot)
        return len(by_slot)

    # ------------------------------------------------------------ stepping
    def step(self):
        """One scheduler round: finish/admit/prefill, then ONE decode step
        over every active slot. -> decode tokens emitted (0 when idle).
        Admission rides the same round as decode, so in-flight requests
        never skip a step while a newcomer prefills."""
        if self._closed:
            raise EngineClosed("engine is closed")
        admitted = self.scheduler.schedule()
        if admitted:
            self._prefill_admitted(admitted)
        _, evicted = self.scheduler.ensure_decode_capacity()
        for req in evicted:
            self.metrics.on_evict(req)
        active = {slot: r for slot, r in self.scheduler.active.items()
                  if r.state == "active"}
        emitted = self._decode_once(active) if active else 0
        occ = self.kv.occupancy_pct()
        self._peak_occupancy = max(self._peak_occupancy, occ)
        self.metrics.sample_state(len(self.scheduler.active),
                                  self.scheduler.queue_depth(), occ)
        self._steps += 1
        return emitted

    def run_until_idle(self, max_steps=100000):
        steps = 0
        while self.scheduler.has_work():
            self.step()
            steps += 1
            if steps >= max_steps:
                raise RuntimeError(
                    f"run_until_idle exceeded {max_steps} steps")
        return steps

    # ------------------------------------------------------------- serving
    def submit(self, prompt_ids, max_new_tokens=16, eos_token_id=None,
               temperature=0.0, top_k=None, on_token=None, block=True,
               timeout=10.0):
        """Queue one request (backpressure: blocks up to ``timeout`` for
        queue space, then raises :class:`~.scheduler.QueueFull`)."""
        if self._closed:
            raise EngineClosed("engine is closed")
        req = GenerationRequest(prompt_ids, max_new_tokens=max_new_tokens,
                                eos_token_id=eos_token_id,
                                temperature=temperature, top_k=top_k,
                                on_token=on_token)
        self.scheduler.submit(req, block=block, timeout=timeout)
        self._wake.set()
        return req

    def generate(self, prompt_ids, timeout=120.0, **kw):
        """Synchronous helper: submit + drive (foreground when no serve
        thread is running) + wait. -> generated token list."""
        req = self.submit(prompt_ids, **kw)
        if self._thread is None:
            self.run_until_idle()
        return req.result(timeout=timeout)

    def start(self):
        """Background serve loop (idempotent)."""
        if self._thread is not None:
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(target=self._serve_loop,
                                        name="paddle-tpu-serving",
                                        daemon=True)
        self._thread.start()

    def _serve_loop(self):
        while not self._stop_evt.is_set():
            try:
                if self.scheduler.has_work():
                    self.step()
                else:
                    self._wake.wait(0.02)
                    self._wake.clear()
            except Exception as e:  # a broken step fails every waiter
                self.scheduler.close(error=e)
                break

    def stop(self, timeout=10.0):
        self._stop_evt.set()
        self._wake.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout)

    def close(self):
        """Stop the loop and fail everything still queued or in flight —
        same contract as ``BatchingPredictor.close``."""
        if self._closed:
            return
        self._closed = True
        self.stop()
        self.scheduler.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # --------------------------------------------------------------- stats
    def stats(self):
        return {
            "steps": self._steps,
            "decode_tokens": self._decode_tokens,
            "evictions": self.scheduler.total_evictions,
            "kv_occupancy_pct": round(self.kv.occupancy_pct(), 2),
            "kv_occupancy_peak_pct": round(self._peak_occupancy, 2),
            "active": len(self.scheduler.active),
            "queued": self.scheduler.queue_depth(),
            "attn_backend": self.attn_backend,
            "attn_ab": self.attn_ab,
        }
