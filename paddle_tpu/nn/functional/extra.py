"""Long-tail functional ops: losses, activations, unpooling, CTC/RNNT.

Reference: python/paddle/nn/functional/{loss,activation,pooling,common}.py
long tail (poisson_nll_loss:..., ctc_loss over warpctc
phi/kernels/impl/warpctc_kernel_impl.h, rnnt_loss over warprnnt,
hsigmoid_loss over matrix_bit_code.h SimpleCode). TPU-native: the dynamic
programs (CTC alpha recursion, RNNT lattice) run as lax.scan in log space
— one fused XLA loop instead of the reference's CUDA warp kernels.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor

__all__ = [
    "channel_shuffle", "maxout", "thresholded_relu", "rrelu", "zeropad2d",
    "pairwise_distance", "poisson_nll_loss",
    "multi_label_soft_margin_loss", "hinge_embedding_loss",
    "cosine_embedding_loss", "multi_margin_loss", "triplet_margin_loss",
    "triplet_margin_with_distance_loss", "soft_margin_loss",
    "gaussian_nll_loss", "ctc_loss", "rnnt_loss", "hsigmoid_loss",
    "bilinear", "adaptive_avg_pool3d", "adaptive_max_pool3d",
    "max_unpool1d", "max_unpool2d", "max_unpool3d",
]


def _reduce(x, reduction):
    if reduction == "mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    return x


# ---------------- activations / shapes ----------------

def channel_shuffle(x, groups, data_format="NCHW", name=None):
    """Reference: functional/common.py channel_shuffle."""
    def fwd(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            return a.reshape(n, groups, c // groups, h, w) \
                .swapaxes(1, 2).reshape(n, c, h, w)
        n, h, w, c = a.shape
        return a.reshape(n, h, w, groups, c // groups) \
            .swapaxes(3, 4).reshape(n, h, w, c)
    return apply("channel_shuffle", fwd, [x])


def maxout(x, groups, axis=1, name=None):
    """Reference: functional/activation.py maxout."""
    def fwd(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return a.reshape(new_shape).max(axis=ax + 1)
    return apply("maxout", fwd, [x])


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    """Reference: functional/activation.py thresholded_relu."""
    return apply("thresholded_relu",
                 lambda a: jnp.where(a > threshold, a, value), [x])


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    """Reference: functional/activation.py rrelu — random slope in
    training, mean slope in eval."""
    from ...core import random as _random
    if training:
        key = _random.next_key()

        def fwd(a):
            slope = jax.random.uniform(key, a.shape, jnp.float32,
                                       lower, upper).astype(a.dtype)
            return jnp.where(a >= 0, a, slope * a)
        return apply("rrelu", fwd, [x])
    slope = (lower + upper) / 2.0
    return apply("rrelu", lambda a: jnp.where(a >= 0, a, slope * a), [x])


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """Reference: functional/common.py zeropad2d — pad (left, right, top,
    bottom) with zeros."""
    l, r, t, b = (padding if isinstance(padding, (list, tuple))
                  else [padding] * 4)

    def fwd(a):
        if data_format == "NCHW":
            return jnp.pad(a, ((0, 0), (0, 0), (t, b), (l, r)))
        return jnp.pad(a, ((0, 0), (t, b), (l, r), (0, 0)))
    return apply("zeropad2d", fwd, [x])


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """Reference: functional/distance.py pairwise_distance."""
    return apply(
        "pairwise_distance",
        lambda a, b: jnp.linalg.norm(a - b + epsilon, ord=p, axis=-1,
                                     keepdims=keepdim), [x, y])


def bilinear(x1, x2, weight, bias=None, name=None):
    """Reference: functional/common.py bilinear — out[b,o] =
    x1[b,i] W[o,i,j] x2[b,j] + bias."""
    ins = [x1, x2, weight] + ([bias] if bias is not None else [])

    def fwd(a, b, w, *bb):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bb:
            out = out + bb[0]
        return out
    return apply("bilinear", fwd, ins)


# ---------------- losses ----------------

def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean", name=None):
    """Reference: functional/loss.py poisson_nll_loss."""
    def fwd(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y) - y + 0.5 * jnp.log(2 * jnp.pi * y)
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)
    return apply("poisson_nll_loss", fwd, [input, label])


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    """Reference: functional/loss.py multi_label_soft_margin_loss."""
    ins = [input, label] + ([weight] if weight is not None else [])

    def fwd(x, y, *w):
        term = y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x)
        if w:
            term = term * w[0]
        loss = -jnp.mean(term, axis=-1)
        return _reduce(loss, reduction)
    return apply("multi_label_soft_margin_loss", fwd, ins)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    """Reference: functional/loss.py hinge_embedding_loss."""
    def fwd(x, y):
        loss = jnp.where(y == 1.0, x, jnp.maximum(0.0, margin - x))
        return _reduce(loss, reduction)
    return apply("hinge_embedding_loss", fwd, [input, label])


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean", name=None):
    """Reference: functional/loss.py cosine_embedding_loss."""
    def fwd(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1),
            1e-12)
        loss = jnp.where(y == 1, 1.0 - cos,
                         jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply("cosine_embedding_loss", fwd, [input1, input2, label])


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """Reference: functional/loss.py multi_margin_loss."""
    ins = [input, label] + ([weight] if weight is not None else [])

    def fwd(x, y, *w):
        n, c = x.shape
        xy = jnp.take_along_axis(x, y[:, None].astype(jnp.int32), 1)
        m = jnp.maximum(0.0, margin - xy + x) ** p
        if w:
            m = m * jnp.take(w[0], y.astype(jnp.int32))[:, None]
        m = m * (1 - jax.nn.one_hot(y, c, dtype=m.dtype))
        loss = jnp.sum(m, -1) / c
        return _reduce(loss, reduction)
    return apply("multi_margin_loss", fwd, ins)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None):
    """Reference: functional/loss.py triplet_margin_loss."""
    def fwd(a, pos, neg):
        d_ap = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        d_an = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            d_pn = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            d_an = jnp.minimum(d_an, d_pn)
        loss = jnp.maximum(0.0, d_ap - d_an + margin)
        return _reduce(loss, reduction)
    return apply("triplet_margin_loss", fwd, [input, positive, negative])


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """Reference: functional/loss.py triplet_margin_with_distance_loss."""
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative,
                                   margin=margin, swap=swap,
                                   reduction=reduction)
    d_ap = distance_function(input, positive)
    d_an = distance_function(input, negative)
    if swap:
        d_pn = distance_function(positive, negative)
        d_an = d_an.minimum(d_pn) if hasattr(d_an, "minimum") else d_an

    def fwd(ap, an):
        return _reduce(jnp.maximum(0.0, ap - an + margin), reduction)
    return apply("triplet_margin_with_distance_loss", fwd, [d_ap, d_an])


def soft_margin_loss(input, label, reduction="mean", name=None):
    """Reference: functional/loss.py soft_margin_loss."""
    def fwd(x, y):
        return _reduce(jnp.log1p(jnp.exp(-y * x)), reduction)
    return apply("soft_margin_loss", fwd, [input, label])


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """Reference: functional/loss.py gaussian_nll_loss."""
    def fwd(x, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + (x - y) ** 2 / var)
        if full:
            loss = loss + 0.5 * jnp.log(jnp.asarray(2 * jnp.pi, x.dtype))
        return _reduce(loss, reduction)
    return apply("gaussian_nll_loss", fwd, [input, label, variance])


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    """Reference: functional/loss.py ctc_loss (warpctc_kernel_impl.h).
    log_probs [T, B, C] (log-softmaxed inside, reference semantics),
    labels [B, L]. The alpha recursion runs as one lax.scan over time in
    log space."""
    def fwd(lp, lab, in_len, lab_len):
        T, B, C = lp.shape
        L = lab.shape[1]
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        S = 2 * L + 1
        lab = lab.astype(jnp.int32)
        # extended label sequence: blank, l1, blank, l2, ... blank
        ext = jnp.full((B, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab)
        # allow skip (s-2 -> s) where ext[s] != blank and != ext[s-2]
        ext_prev2 = jnp.pad(ext, ((0, 0), (2, 0)),
                            constant_values=-1)[:, :S]
        can_skip = (ext != blank) & (ext != ext_prev2)
        neg_inf = jnp.asarray(-1e30, jnp.float32)

        def emit(t):
            # [B, S] log prob of emitting ext symbol at time t
            return jnp.take_along_axis(lp[t], ext, axis=1)

        alpha0 = jnp.full((B, S), neg_inf)
        alpha0 = alpha0.at[:, 0].set(emit(0)[:, 0])
        alpha0 = alpha0.at[:, 1].set(jnp.where(
            lab_len > 0, emit(0)[:, 1], neg_inf))

        def step(alpha, t):
            a1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                         constant_values=-1e30)[:, :S]
            a2 = jnp.pad(alpha, ((0, 0), (2, 0)),
                         constant_values=-1e30)[:, :S]
            a2 = jnp.where(can_skip, a2, neg_inf)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a1), a2)
            new = merged + emit(t)
            # past input_lengths, freeze alpha (emissions don't count)
            new = jnp.where((t < in_len)[:, None], new, alpha)
            return new, None

        alpha, _ = jax.lax.scan(step, alpha0,
                                jnp.arange(1, T, dtype=jnp.int32))
        # final: logaddexp of positions S-1 and S-2 at s = 2*lab_len, -1
        idx_last = 2 * lab_len
        a_last = jnp.take_along_axis(alpha, idx_last[:, None].astype(
            jnp.int32), 1)[:, 0]
        a_prev = jnp.take_along_axis(
            alpha, jnp.maximum(idx_last - 1, 0)[:, None].astype(jnp.int32),
            1)[:, 0]
        ll = jnp.logaddexp(a_last, jnp.where(lab_len > 0, a_prev, neg_inf))
        loss = -ll
        if norm_by_times:
            loss = loss / jnp.maximum(in_len.astype(jnp.float32), 1.0)
        if reduction == "mean":
            # reference/torch semantics: mean of loss / label_length
            return jnp.mean(loss / jnp.maximum(
                lab_len.astype(jnp.float32), 1.0))
        return _reduce(loss, reduction)

    return apply("ctc_loss", fwd,
                 [log_probs, labels, input_lengths, label_lengths])


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """Reference: functional/loss.py rnnt_loss (warprnnt). input
    [B, T, U+1, V] log-softmaxed inside; alpha over the (T, U) lattice via
    scan over T with an inner scan over U."""
    def fwd(logits, lab, in_len, lab_len):
        B, T, U1, V = logits.shape
        U = U1 - 1
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        lab = lab.astype(jnp.int32)
        neg_inf = jnp.asarray(-1e30, jnp.float32)
        # emit[b, t, u] = lp[b, t, u, lab[b, u]] for u < U
        emit = jnp.take_along_axis(
            lp[:, :, :U, :], lab[:, None, :, None], axis=3)[..., 0]
        blk = lp[..., blank]                       # [B, T, U+1]

        def t_step(alpha_prev, t):
            # alpha_prev: [B, U+1] at time t-1 (or init)
            from_blank = alpha_prev + blk[:, t - 1, :]

            def u_step(carry, u):
                # carry: alpha[t, u-1]; emit step within same t
                a = jnp.logaddexp(from_blank[:, u],
                                  carry + emit[:, t, u - 1])
                return a, a

            a0 = from_blank[:, 0]
            _, rest = jax.lax.scan(u_step, a0,
                                   jnp.arange(1, U1, dtype=jnp.int32))
            alpha_t = jnp.concatenate([a0[:, None], rest.T], axis=1)
            alpha_t = jnp.where((t < in_len)[:, None], alpha_t,
                                alpha_prev)
            return alpha_t, None

        # t = 0 row: only emissions along u
        def u0_step(carry, u):
            a = carry + emit[:, 0, u - 1]
            return a, a

        a00 = jnp.zeros((B,), jnp.float32)
        _, rest0 = jax.lax.scan(u0_step, a00,
                                jnp.arange(1, U1, dtype=jnp.int32))
        alpha0 = jnp.concatenate([a00[:, None], rest0.T], axis=1)
        alpha0 = jnp.where(
            jnp.arange(U1)[None, :] <= lab_len[:, None], alpha0, neg_inf)

        alpha, _ = jax.lax.scan(t_step, alpha0,
                                jnp.arange(1, T, dtype=jnp.int32))
        # ll = alpha[in_len-1, lab_len] + blank at (in_len-1, lab_len)
        t_last = jnp.maximum(in_len - 1, 0).astype(jnp.int32)
        a_fin = jnp.take_along_axis(
            alpha, lab_len[:, None].astype(jnp.int32), 1)[:, 0]
        blk_fin = blk[jnp.arange(B), t_last, lab_len.astype(jnp.int32)]
        loss = -(a_fin + blk_fin)
        if reduction == "mean":
            return jnp.mean(loss)
        return _reduce(loss, reduction)

    return apply("rnnt_loss", fwd,
                 [input, label, input_lengths, label_lengths])


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Reference: functional/loss.py hsigmoid_loss
    (matrix_bit_code.h SimpleCode complete-binary-tree default):
    node(j) = (label + num_classes) >> (j+1) - 1,
    bit(j) = ((label + num_classes) >> j) & 1."""
    if (path_table is None) != (path_code is None):
        raise ValueError(
            "hsigmoid_loss: path_table and path_code must be given "
            "together (reference CustomCode needs both)")
    custom = path_table is not None

    def _bce_over_path(x, w, bb, nodes, bits, valid):
        nodes_c = jnp.maximum(nodes, 0)
        wn = jnp.take(w, nodes_c, axis=0)                  # [B, D, in]
        logits = jnp.einsum("bdi,bi->bd", wn, x)
        if bb is not None:
            logits = logits + jnp.take(bb.reshape(-1), nodes_c)
        # P(bit) via sigmoid: loss = sum BCE(bit, logit) over valid nodes
        bce = jnp.maximum(logits, 0) - logits * bits.astype(jnp.float32) \
            + jnp.log1p(jnp.exp(-jnp.abs(logits)))
        return jnp.sum(jnp.where(valid, bce, 0.0), axis=1, keepdims=True)

    if custom:
        # reference matrix_bit_code.h CustomCode: per-sample node ids in
        # path_table, 0/1 codes in path_code, entries < 0 are padding
        ins = [input, path_table, path_code, weight] + (
            [bias] if bias is not None else [])

        def fwd_custom(x, ptab, pcode, w, *bb):
            nodes = ptab.astype(jnp.int32)
            bits = pcode.astype(jnp.int32)
            valid = nodes >= 0
            return _bce_over_path(x, w, bb[0] if bb else None, nodes,
                                  bits, valid)

        return apply("hsigmoid_loss", fwd_custom, ins)

    depth = int(np.ceil(np.log2(max(num_classes, 2))))
    ins = [input, label, weight] + ([bias] if bias is not None else [])

    def fwd(x, y, w, *bb):
        y = y.astype(jnp.int32).reshape(-1)
        code = y + num_classes
        js = jnp.arange(depth, dtype=jnp.int32)
        nodes = (code[:, None] >> (js + 1)[None, :]) - 1   # [B, D]
        bits = (code[:, None] >> js[None, :]) & 1          # [B, D]
        return _bce_over_path(x, w, bb[0] if bb else None, nodes, bits,
                              nodes >= 0)

    return apply("hsigmoid_loss", fwd, ins)


# ---------------- pooling ----------------

def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    """Reference: functional/pooling.py adaptive_avg_pool3d."""
    from .pooling import _adaptive_pool
    return _adaptive_pool(x, output_size, 3, "avg", data_format)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    from .pooling import _adaptive_pool
    assert not return_mask
    return _adaptive_pool(x, output_size, 3, "max", "NCDHW")


def _max_unpool(x, indices, ndim, kernel_size, stride, padding,
                output_size, data_format):
    """Scatter each pooled value to its argmax position (indices flat over
    the spatial dims, reference kernel semantics)."""
    ks = kernel_size if isinstance(kernel_size, (list, tuple)) \
        else [kernel_size] * ndim
    st = stride if stride is not None else ks
    st = st if isinstance(st, (list, tuple)) else [st] * ndim

    def fwd(a, idx):
        n, c = a.shape[0], a.shape[1]
        in_sp = a.shape[2:]
        if output_size is not None:
            out_sp = tuple(output_size[-ndim:])
        else:
            out_sp = tuple((in_sp[d] - 1) * st[d] + ks[d]
                           for d in range(ndim))
        flat_len = int(np.prod(out_sp))
        out = jnp.zeros((n, c, flat_len), a.dtype)
        flat_v = a.reshape(n, c, -1)
        flat_i = idx.reshape(n, c, -1).astype(jnp.int32)
        bidx = jnp.arange(n)[:, None, None]
        cidx = jnp.arange(c)[None, :, None]
        out = out.at[bidx, cidx, flat_i].set(flat_v)
        return out.reshape((n, c) + out_sp)

    return apply(f"max_unpool{ndim}d", fwd, [x, indices])


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """Reference: functional/pooling.py max_unpool1d."""
    return _max_unpool(x, indices, 1, kernel_size, stride, padding,
                       output_size, data_format)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """Reference: functional/pooling.py max_unpool2d."""
    return _max_unpool(x, indices, 2, kernel_size, stride, padding,
                       output_size, data_format)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    """Reference: functional/pooling.py max_unpool3d."""
    return _max_unpool(x, indices, 3, kernel_size, stride, padding,
                       output_size, data_format)
