"""paddle_tpu.nn.functional — functional NN ops.

Reference namespace: python/paddle/nn/functional/__init__.py.
"""
from ...ops import one_hot  # noqa: F401  (paddle exposes F.one_hot too)
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .extra import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .long_tail import *  # noqa: F401,F403

from . import (  # noqa: F401
    activation, common, conv, long_tail, loss, norm, pooling,
)
