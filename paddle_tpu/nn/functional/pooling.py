"""Pooling functionals over lax.reduce_window.

Reference: python/paddle/nn/functional/pooling.py → phi pool kernels. On TPU a
single reduce_window primitive covers max/avg pooling; adaptive pooling uses
exact bucket means (integral-image free, static python loop over output cells
is avoided via segment reductions when shapes divide evenly).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply

__all__ = [
    "max_pool1d", "max_pool2d", "max_pool3d", "avg_pool1d", "avg_pool2d",
    "avg_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_max_pool1d", "adaptive_max_pool2d",
]


def _ntuple(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == 1:
            return tuple(int(x) for x in v) * n
        assert len(v) == n
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _pool(x, nd, kind, kernel_size, stride, padding, ceil_mode, exclusive,
          data_format):
    channel_last = data_format[-1] == "C"
    ks = _ntuple(kernel_size, nd)
    st = _ntuple(stride if stride is not None else kernel_size, nd)
    pd = _ntuple(padding, nd)

    sp_axes = list(range(1, 1 + nd)) if channel_last else list(range(2, 2 + nd))
    ndim = nd + 2
    window = [1] * ndim
    strides = [1] * ndim
    pads = [(0, 0)] * ndim
    for i, ax in enumerate(sp_axes):
        window[ax] = ks[i]
        strides[ax] = st[i]
        lo = hi = pd[i]
        if ceil_mode:
            # extend high padding so the last partial window is included
            size = x.shape[ax]
            out = -(-(size + 2 * pd[i] - ks[i]) // st[i]) + 1  # ceil
            needed = (out - 1) * st[i] + ks[i] - size - pd[i]
            hi = max(hi, needed)
        pads[ax] = (lo, hi)

    if kind == "max":
        def fwd(a):
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else \
                jnp.iinfo(a.dtype).min
            return jax.lax.reduce_window(a, init, jax.lax.max, window, strides,
                                         pads)
        return apply(f"max_pool{nd}d", fwd, [x])

    def fwd(a):
        s = jax.lax.reduce_window(a.astype(jnp.float32), 0.0, jax.lax.add,
                                  window, strides, pads)
        if exclusive and (any(p[0] or p[1] for p in pads)):
            ones = jnp.ones(a.shape, jnp.float32)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                        strides, pads)
            return (s / cnt).astype(a.dtype)
        return (s / float(np.prod(ks))).astype(a.dtype)
    return apply(f"avg_pool{nd}d", fwd, [x])


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    assert not return_mask, "return_mask is not supported on TPU yet"
    return _pool(x, 1, "max", kernel_size, stride, padding, ceil_mode, True,
                 data_format)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    """Reference: python/paddle/nn/functional/pooling.py (max_pool2d)."""
    assert not return_mask, "return_mask is not supported on TPU yet"
    return _pool(x, 2, "max", kernel_size, stride, padding, ceil_mode, True,
                 data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    assert not return_mask, "return_mask is not supported on TPU yet"
    return _pool(x, 3, "max", kernel_size, stride, padding, ceil_mode, True,
                 data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, 1, "avg", kernel_size, stride, padding, ceil_mode,
                 exclusive, data_format)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    assert divisor_override is None, "divisor_override not supported"
    return _pool(x, 2, "avg", kernel_size, stride, padding, ceil_mode,
                 exclusive, data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    assert divisor_override is None, "divisor_override not supported"
    return _pool(x, 3, "avg", kernel_size, stride, padding, ceil_mode,
                 exclusive, data_format)


def _adaptive_starts_ends(in_size, out_size):
    starts = [(i * in_size) // out_size for i in range(out_size)]
    ends = [-(-((i + 1) * in_size) // out_size) for i in range(out_size)]
    return starts, ends


def _adaptive_pool(x, output_size, nd, kind, data_format):
    channel_last = data_format[-1] == "C"
    sp_axes = list(range(1, 1 + nd)) if channel_last else \
        list(range(2, 2 + nd))
    out = _ntuple(output_size, nd)
    in_sizes = [x.shape[ax] for ax in sp_axes]

    if all(i % o == 0 for i, o in zip(in_sizes, out)):
        # evenly divisible: reshape + reduce (one XLA fusion)
        def fwd(a):
            shape = list(a.shape)
            new_shape = []
            red_axes = []
            k = 0
            for ax in range(a.ndim):
                if ax in sp_axes:
                    i = sp_axes.index(ax)
                    new_shape += [out[i], in_sizes[i] // out[i]]
                    red_axes.append(len(new_shape) - 1)
                    k += 1
                else:
                    new_shape.append(shape[ax])
            r = a.reshape(new_shape)
            if kind == "avg":
                return r.mean(axis=tuple(red_axes))
            return r.max(axis=tuple(red_axes))
        return apply(f"adaptive_{kind}_pool{nd}d", fwd, [x])

    # general case: static loop over output cells (small out sizes in practice)
    def fwd(a):
        def pool_axis(arr, ax_pos, in_size, out_size):
            starts, ends = _adaptive_starts_ends(in_size, out_size)
            pieces = []
            for s, e in zip(starts, ends):
                sl = [slice(None)] * arr.ndim
                sl[ax_pos] = slice(s, e)
                seg = arr[tuple(sl)]
                red = seg.mean(axis=ax_pos, keepdims=True) if kind == "avg" \
                    else seg.max(axis=ax_pos, keepdims=True)
                pieces.append(red)
            return jnp.concatenate(pieces, axis=ax_pos)
        r = a
        for i, ax in enumerate(sp_axes):
            r = pool_axis(r, ax, in_sizes[i], out[i])
        return r
    return apply(f"adaptive_{kind}_pool{nd}d", fwd, [x])


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "avg", "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    assert not return_mask
    return _adaptive_pool(x, output_size, 1, "max", "NCL")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    assert not return_mask
    return _adaptive_pool(x, output_size, 2, "max", "NCHW")
