"""Long-tail functional ops (reference: python/paddle/nn/functional/ —
activation.py inplace variants, loss.py dice/log/npair/focal/margin
losses, common.py sequence_mask, input.py class_center_sample,
extra.py gather_tree, norm.py local_response_norm,
sparse_attention over phi sparse_attention kernels)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply
from ...core.tensor import Tensor

__all__ = [
    "thresholded_relu", "elu_", "hardtanh_", "leaky_relu_", "softmax_",
    "tanh_", "thresholded_relu_", "local_response_norm", "sequence_mask",
    "gather_tree", "dice_loss", "log_loss", "npair_loss",
    "sigmoid_focal_loss", "margin_cross_entropy", "class_center_sample",
    "sparse_attention",
]


def thresholded_relu(x, threshold=1.0, name=None):
    """Reference: F.thresholded_relu — x where x > threshold else 0."""
    return apply("thresholded_relu",
                 lambda a: jnp.where(a > threshold, a, 0.0), [x])


# -- inplace activation variants (reference: activation.py *_ ad_funcs;
# XLA arrays are immutable so inplace adopts the result, ops/inplace.py) --

def elu_(x, alpha=1.0, name=None):
    from .activation import elu
    return x._inplace(elu, alpha)


def hardtanh_(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    from .activation import hardtanh
    return x._inplace(hardtanh, min, max)


def leaky_relu_(x, negative_slope=0.01, name=None):
    from .activation import leaky_relu
    return x._inplace(leaky_relu, negative_slope)


def softmax_(x, axis=-1, dtype=None, name=None):
    from .activation import softmax
    return x._inplace(softmax, axis, dtype)


def tanh_(x, name=None):
    from .activation import tanh
    return x._inplace(tanh)


def thresholded_relu_(x, threshold=1.0, name=None):
    return x._inplace(thresholded_relu, threshold)


def local_response_norm(x, size=5, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    """Reference: F.local_response_norm (AlexNet LRN): divide by
    (k + alpha/size * sum of squares over a cross-channel window)^beta."""
    if data_format not in ("NCL", "NCHW", "NCDHW"):
        raise ValueError(
            f"local_response_norm supports channels-first formats, got "
            f"{data_format}")

    def f(a):
        sq = jnp.square(a)
        half = size // 2
        pads = [(0, 0)] * a.ndim
        pads[1] = (half, size - 1 - half)
        padded = jnp.pad(sq, pads)
        acc = jnp.zeros_like(a)
        for i in range(size):
            acc = acc + jax.lax.slice_in_dim(padded, i, i + a.shape[1],
                                             axis=1)
        div = jnp.power(k + alpha / size * acc, beta)
        return a / div

    return apply("local_response_norm", f, [x])


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """Reference: F.sequence_mask — mask[..., j] = j < x[...]."""
    from ...core.dtype import convert_dtype

    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    if maxlen is None:
        maxlen = int(np.asarray(arr).max())
    jdt = convert_dtype(dtype)

    def f(lens):
        rng = jnp.arange(maxlen, dtype=lens.dtype)
        return (rng < lens[..., None]).astype(jdt)

    return apply("sequence_mask", f, [x])


def gather_tree(ids, parents):
    """Reference: F.gather_tree (beam search backtrace): walk parent
    pointers from the last step so every beam's path is consistent.
    ids/parents: [T, B, beam]."""
    def f(idv, par):
        T = idv.shape[0]

        def step(carry, t):
            beams = carry  # [B, beam] current beam index per slot
            out = jnp.take_along_axis(idv[t], beams, axis=-1)
            nxt = jnp.take_along_axis(par[t], beams, axis=-1)
            return nxt, out

        last = jnp.broadcast_to(
            jnp.arange(idv.shape[2], dtype=idv.dtype),
            idv.shape[1:])
        _, rev = jax.lax.scan(step, last, jnp.arange(T - 1, -1, -1))
        return rev[::-1]

    return apply("gather_tree", f, [ids, parents])


# -- loss family ---------------------------------------------------------

def dice_loss(input, label, epsilon=1e-5, name=None):
    """Reference: F.dice_loss — 1 - 2|X∩Y| / (|X|+|Y|). input [N, ..., C]
    probabilities; label [N, ..., 1] class ids."""
    def f(inp, lab):
        lab_oh = jax.nn.one_hot(lab[..., 0], inp.shape[-1],
                                dtype=inp.dtype)
        reduce_axes = tuple(range(1, inp.ndim))
        inter = jnp.sum(inp * lab_oh, axis=reduce_axes)
        union = jnp.sum(inp, axis=reduce_axes) + \
            jnp.sum(lab_oh, axis=reduce_axes)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))

    return apply("dice_loss", f, [input, label])


def log_loss(input, label, epsilon=1e-4, name=None):
    """Reference: F.log_loss — negative log likelihood of probabilities."""
    def f(inp, lab):
        return -lab * jnp.log(inp + epsilon) \
            - (1 - lab) * jnp.log(1 - inp + epsilon)

    return apply("log_loss", f, [input, label])


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """Reference: F.npair_loss (Sohn 2016): softmax CE over
    anchor·positiveᵀ similarities with matching-label targets + L2."""
    def f(anc, pos, lab):
        l2 = jnp.sum(anc * anc) / anc.shape[0] + \
            jnp.sum(pos * pos) / pos.shape[0]
        sim = anc @ pos.T                          # [B, B]
        same = (lab[:, None] == lab[None, :]).astype(sim.dtype)
        targets = same / jnp.maximum(same.sum(-1, keepdims=True), 1.0)
        logp = jax.nn.log_softmax(sim, axis=-1)
        ce = -jnp.mean(jnp.sum(targets * logp, axis=-1))
        return ce + l2_reg * l2 * 0.25

    return apply("npair_loss", f, [anchor, positive, labels])


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25,
                       gamma=2.0, reduction="sum", name=None):
    """Reference: F.sigmoid_focal_loss (RetinaNet)."""
    has_norm = normalizer is not None

    def f(lg, lab, *rest):
        p = jax.nn.sigmoid(lg)
        ce = jnp.maximum(lg, 0) - lg * lab + \
            jnp.log1p(jnp.exp(-jnp.abs(lg)))
        p_t = p * lab + (1 - p) * (1 - lab)
        a_t = alpha * lab + (1 - alpha) * (1 - lab)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if rest:
            loss = loss / rest[0]
        if reduction == "sum":
            return jnp.sum(loss)
        if reduction == "mean":
            return jnp.mean(loss)
        return loss

    ins = [logit, label] + ([normalizer] if has_norm else [])
    return apply("sigmoid_focal_loss", f, ins)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """Reference: F.margin_cross_entropy (ArcFace/CosFace family):
    cos(m1·θ + m2) - m3 on the target logit, then scaled softmax CE.
    Single-group form (the reference's model-parallel group splits the
    class dim; under GSPMD the sharded matmul handles that upstream)."""
    def f(lg, lab):
        n = lg.shape[-1]
        cos = jnp.clip(lg, -1.0, 1.0)
        theta = jnp.arccos(cos)
        modified = jnp.cos(margin1 * theta + margin2) - margin3
        oh = jax.nn.one_hot(lab, n, dtype=lg.dtype)
        out = scale * (oh * modified + (1 - oh) * cos)
        logp = jax.nn.log_softmax(out, axis=-1)
        ce = -jnp.sum(oh * logp, axis=-1, keepdims=True)
        sm = jnp.exp(logp)
        if reduction == "mean":
            ce_r = jnp.mean(ce)
        elif reduction == "sum":
            ce_r = jnp.sum(ce)
        else:
            ce_r = ce
        return (ce_r, sm) if return_softmax else ce_r

    if return_softmax:
        return apply("margin_cross_entropy", f, [logits, label], nout=2)
    return apply("margin_cross_entropy", f, [logits, label])


def class_center_sample(label, num_classes, num_samples, group=None):
    """Reference: F.class_center_sample (PartialFC): keep all positive
    class centers plus a uniform sample of negatives; remap labels into
    the sampled index space. Host op (unique + sampling are inherently
    data-dependent)."""
    lab = np.asarray(label._data if isinstance(label, Tensor) else label)
    pos = np.unique(lab)
    if pos.size >= num_samples:
        sampled = pos
    else:
        neg = np.setdiff1d(np.arange(num_classes), pos, assume_unique=True)
        extra = np.random.permutation(neg)[:num_samples - pos.size]
        sampled = np.sort(np.concatenate([pos, extra]))
    remap = np.full((num_classes,), -1, np.int64)
    remap[sampled] = np.arange(sampled.size)
    return (Tensor(jnp.asarray(remap[lab])),
            Tensor(jnp.asarray(sampled.astype(np.int64))))


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Reference: F.sparse_attention (phi sparse_attention kernels) — the
    CSR (offset, columns) pattern selects which logits participate.
    Adapter over the BCOO sparse-mask attention (sparse/nn/functional.py):
    the CSR pattern is densified once; XLA fuses the masking."""
    off = np.asarray(sparse_csr_offset._data
                     if isinstance(sparse_csr_offset, Tensor)
                     else sparse_csr_offset)
    col = np.asarray(sparse_csr_columns._data
                     if isinstance(sparse_csr_columns, Tensor)
                     else sparse_csr_columns)
    S = query.shape[2]
    mask = np.zeros((S, S), np.float32)
    off2 = off.reshape(-1, off.shape[-1])[0]
    col2 = col.reshape(-1, col.shape[-1])[0]
    for r in range(S):
        mask[r, col2[off2[r]:off2[r + 1]]] = 1.0
    from ...sparse.nn.functional import attention as _att
    return _att(query, key, value, Tensor(jnp.asarray(mask)),
                key_padding_mask=key_padding_mask, attn_mask=attn_mask)
