"""Convolution functionals over lax.conv_general_dilated.

Reference: python/paddle/nn/functional/conv.py (conv2d → phi conv kernels /
cudnn). TPU-native: one XLA convolution primitive covers all cases; XLA lowers
it onto the MXU. Weight layouts match paddle: conv = [out_c, in_c/groups, *k],
conv_transpose = [in_c, out_c/groups, *k].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose"]


def _ntuple(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == 1:
            return tuple(v) * n
        assert len(v) == n, f"expected {n} values, got {v}"
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _norm_padding(padding, n):
    """Return lax-style [(lo, hi)] * n or the string 'SAME'/'VALID'."""
    if isinstance(padding, str):
        p = padding.upper()
        assert p in ("SAME", "VALID"), f"bad padding {padding}"
        return p
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if all(isinstance(p, int) for p in padding):
        if len(padding) == n:
            return [(p, p) for p in padding]
        if len(padding) == 2 * n:  # [lo0, hi0, lo1, hi1 ...] paddle flat form
            return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    # [[lo, hi], ...] possibly including batch/channel dims (paddle allows 4x2)
    pairs = [tuple(p) for p in padding]
    if len(pairs) == n + 2:
        pairs = pairs[2:]
    assert len(pairs) == n
    return pairs


def _dim_numbers(nd, channel_last):
    sp = "".join(chr(ord("0") + i) for i in range(nd))  # spatial dim labels
    lhs = ("N" + sp + "C") if channel_last else ("NC" + sp)
    out = lhs
    rhs = "OI" + sp
    return jax.lax.conv_dimension_numbers((0,) * (nd + 2), (0,) * (nd + 2),
                                          (lhs, rhs, out))


def _conv(x, weight, bias, stride, padding, dilation, groups, data_format, nd,
          name):
    channel_last = data_format[-1] == "C"
    stride = _ntuple(stride, nd)
    dilation = _ntuple(dilation, nd)
    pad = _norm_padding(padding, nd)
    dn = _dim_numbers(nd, channel_last)

    def fwd(a, w, *b):
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups,
            preferred_element_type=jnp.float32
            if a.dtype == jnp.bfloat16 else None)
        out = out.astype(a.dtype)
        if b:
            bshape = [1] * out.ndim
            bshape[-1 if channel_last else 1] = b[0].size
            out = out + b[0].reshape(bshape)
        return out

    ins = [x, weight] + ([bias] if bias is not None else [])
    return apply(f"conv{nd}d", fwd, ins)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 data_format, 1, name)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    """Reference: python/paddle/nn/functional/conv.py (conv2d)."""
    from ...core.enforce import check_conv2d
    check_conv2d(x.shape, weight.shape, groups, data_format)
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 data_format, 2, name)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 data_format, 3, name)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, groups,
                    dilation, data_format, nd, output_size, name):
    """Transposed conv as an input-dilated forward conv (the standard
    grad-of-conv identity), so XLA sees one fused convolution.

    paddle weight layout [in_c, out_c/groups, *k] is rearranged to the forward
    layout with spatial flip.
    """
    channel_last = data_format[-1] == "C"
    stride = _ntuple(stride, nd)
    dilation = _ntuple(dilation, nd)
    if isinstance(padding, str):
        raise NotImplementedError(
            "string padding for conv_transpose is not supported; pass ints")
    pad = _norm_padding(padding, nd)
    out_padding = _ntuple(output_padding, nd) if output_padding else (0,) * nd
    dn = _dim_numbers(nd, channel_last)
    in_c = weight.shape[0]
    out_cg = weight.shape[1]  # out_c // groups

    def fwd(a, w, *b):
        # [in_c, out_c/g, *k] -> flip spatial -> [out_c, in_c/g, *k]
        wf = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
        wf = wf.reshape((groups, in_c // groups, out_cg) + w.shape[2:])
        wf = jnp.moveaxis(wf, 2, 1)  # [g, out_c/g, in_c/g, *k]
        wf = wf.reshape((groups * out_cg, in_c // groups) + w.shape[2:])
        tpad = []
        for i in range(nd):
            k_eff = dilation[i] * (w.shape[2 + i] - 1)
            lo, hi = pad[i]
            tpad.append((k_eff - lo, k_eff - hi + out_padding[i]))
        out = jax.lax.conv_general_dilated(
            a, wf, window_strides=(1,) * nd, padding=tpad,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups)
        out = out.astype(a.dtype)
        if b:
            bshape = [1] * out.ndim
            bshape[-1 if channel_last else 1] = b[0].size
            out = out + b[0].reshape(bshape)
        return out

    ins = [x, weight] + ([bias] if bias is not None else [])
    out = apply(f"conv{nd}d_transpose", fwd, ins)
    if output_size is not None:
        want = _ntuple(output_size, nd)
        have = out.shape[2:] if not channel_last else out.shape[1:-1]
        if tuple(have) != want:
            raise ValueError(
                f"conv_transpose produced spatial shape {tuple(have)}, but "
                f"output_size={want}; adjust output_padding")
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, data_format, 1, output_size, name)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, data_format="NCHW",
                     output_size=None, name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, data_format, 2, output_size, name)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCDHW", output_size=None, name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, data_format, 3, output_size, name)
