"""Activation functionals (reference: python/paddle/nn/functional/activation.py).

All are single jnp expressions dispatched through the autograd tape; XLA fuses
them into adjacent matmuls/convs on TPU, replacing the reference's per-op
CUDA activation kernels (paddle/phi/kernels/gpu/activation_kernel.cu).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply

__all__ = [
    "relu", "relu_", "relu6", "gelu", "sigmoid", "silu", "swish", "tanh",
    "leaky_relu", "elu", "selu", "celu", "hardtanh", "hardsigmoid",
    "hardswish", "hardshrink", "softshrink", "tanhshrink", "softplus",
    "softsign", "mish", "log_sigmoid", "prelu", "glu", "softmax",
    "log_softmax", "gumbel_softmax",
]


def relu(x, name=None):
    return apply("relu", lambda a: jnp.maximum(a, 0), [x])


def relu_(x, name=None):
    return x._inplace(relu)


def relu6(x, name=None):
    return apply("relu6", lambda a: jnp.clip(a, 0, 6), [x])


def gelu(x, approximate=False, name=None):
    return apply("gelu", lambda a: jax.nn.gelu(a, approximate=approximate), [x])


def sigmoid(x, name=None):
    return apply("sigmoid", jax.nn.sigmoid, [x])


def silu(x, name=None):
    return apply("silu", jax.nn.silu, [x])


def swish(x, name=None):
    return apply("swish", jax.nn.silu, [x])


def tanh(x, name=None):
    return apply("tanh", jnp.tanh, [x])


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply("leaky_relu",
                 lambda a: jnp.where(a >= 0, a, negative_slope * a), [x])


def elu(x, alpha=1.0, name=None):
    return apply("elu", lambda a: jax.nn.elu(a, alpha), [x])


def selu(x,
         scale=1.0507009873554804934193349852946,
         alpha=1.6732632423543772848170429916717,
         name=None):
    return apply("selu",
                 lambda a: scale * jnp.where(a > 0, a,
                                             alpha * jnp.expm1(a)), [x])


def celu(x, alpha=1.0, name=None):
    return apply("celu", lambda a: jax.nn.celu(a, alpha), [x])


def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    return apply("hardtanh", lambda a: jnp.clip(a, min, max), [x])


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply("hardsigmoid",
                 lambda a: jnp.clip(a * slope + offset, 0.0, 1.0), [x])


def hardswish(x, name=None):
    return apply("hardswish",
                 lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, [x])


def hardshrink(x, threshold=0.5, name=None):
    return apply("hardshrink",
                 lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), [x])


def softshrink(x, threshold=0.5, name=None):
    return apply(
        "softshrink",
        lambda a: jnp.where(a > threshold, a - threshold,
                            jnp.where(a < -threshold, a + threshold, 0.0)),
        [x])


def tanhshrink(x, name=None):
    return apply("tanhshrink", lambda a: a - jnp.tanh(a), [x])


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(
        "softplus",
        lambda a: jnp.where(a * beta > threshold, a,
                            jnp.logaddexp(a * beta, 0.0) / beta), [x])


def softsign(x, name=None):
    return apply("softsign", lambda a: a / (1 + jnp.abs(a)), [x])


def mish(x, name=None):
    return apply("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)), [x])


def log_sigmoid(x, name=None):
    return apply("log_sigmoid", jax.nn.log_sigmoid, [x])


def prelu(x, weight, data_format="NCHW", name=None):
    def fwd(a, w):
        if w.size == 1:
            wb = w.reshape(())
        else:
            ch_axis = 1 if data_format[1] == "C" else len(a.shape) - 1
            shape = [1] * a.ndim
            shape[ch_axis] = w.size
            wb = w.reshape(shape)
        return jnp.where(a >= 0, a, wb * a)
    return apply("prelu", fwd, [x, weight])


def glu(x, axis=-1, name=None):
    def fwd(a):
        lhs, rhs = jnp.split(a, 2, axis=axis)
        return lhs * jax.nn.sigmoid(rhs)
    return apply("glu", fwd, [x])


def softmax(x, axis=-1, dtype=None, name=None):
    def fwd(a):
        if dtype is not None:
            from ...core.dtype import convert_dtype
            a = a.astype(convert_dtype(dtype))
        return jax.nn.softmax(a, axis=axis)
    return apply("softmax", fwd, [x])


def log_softmax(x, axis=-1, dtype=None, name=None):
    def fwd(a):
        if dtype is not None:
            from ...core.dtype import convert_dtype
            a = a.astype(convert_dtype(dtype))
        return jax.nn.log_softmax(a, axis=axis)
    return apply("log_softmax", fwd, [x])


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import random as _random
    key = _random.next_key()

    def fwd(a):
        g = -jnp.log(-jnp.log(
            jax.random.uniform(key, a.shape, jnp.float32, 1e-10, 1.0) + 1e-10))
        y = jax.nn.softmax((a + g.astype(a.dtype)) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            onehot = jnp.zeros_like(y).at[...].set(0)
            onehot = jnp.put_along_axis(onehot, idx, 1.0, axis=axis,
                                        inplace=False)
            y = onehot + y - jax.lax.stop_gradient(y)
        return y
    return apply("gumbel_softmax", fwd, [x])
