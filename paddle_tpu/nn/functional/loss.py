"""Loss functionals (reference: python/paddle/nn/functional/loss.py).

cross_entropy matches the reference semantics: fused softmax+NLL
(use_softmax=True), hard or soft labels, class weights, ignore_index and
label_smoothing, computed in f32 for bf16 inputs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "mse_loss", "l1_loss",
    "nll_loss", "binary_cross_entropy", "binary_cross_entropy_with_logits",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "square_error_cost",
]


def _reduce(val, reduction):
    if reduction == "mean":
        return val.mean()
    if reduction == "sum":
        return val.sum()
    return val


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    """Reference: python/paddle/nn/functional/loss.py (cross_entropy)."""
    from ...core.enforce import check_cross_entropy
    check_cross_entropy(input.shape, label.shape, soft_label, axis)
    n_classes = input.shape[axis]

    def fwd(logits, lab, *w):
        lf = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(lf, axis=axis) if use_softmax else \
            jnp.log(jnp.clip(lf, 1e-15, 1.0))
        if soft_label:
            soft = lab.astype(jnp.float32)
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -(soft * logp).sum(axis=axis)
            if reduction == "mean":
                return loss.mean()
            return _reduce(loss, reduction)
        li = lab
        if li.ndim == logp.ndim:  # [N, 1] style labels
            li = li.squeeze(axis)
        valid = li != ignore_index
        li_safe = jnp.where(valid, li, 0).astype(jnp.int32)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(li_safe, axis), axis=axis).squeeze(axis)
        if label_smoothing > 0:
            smooth_term = logp.mean(axis=axis)
            picked = (1 - label_smoothing) * picked \
                + label_smoothing * smooth_term
        loss = -picked
        if w:
            wc = w[0].astype(jnp.float32)[li_safe]
            loss = loss * wc
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            if w:
                denom = jnp.where(valid, w[0].astype(jnp.float32)[li_safe],
                                  0.0).sum()
            else:
                denom = valid.sum().astype(jnp.float32)
            return loss.sum() / jnp.maximum(denom, 1e-12)
        return _reduce(loss, reduction)

    ins = [input, label] + ([weight] if weight is not None else [])
    return apply("cross_entropy", fwd, ins)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    # paddle keeps the reduced axis as size-1
    loss = loss.unsqueeze(axis) if hasattr(loss, "unsqueeze") else loss
    if return_softmax:
        from .activation import softmax as _softmax
        return loss, _softmax(logits, axis=axis)
    return loss


def mse_loss(input, label, reduction="mean", name=None):
    def fwd(a, b):
        d = (a - b).astype(jnp.float32)
        return _reduce(d * d, reduction)
    return apply("mse_loss", fwd, [input, label])


def square_error_cost(input, label):
    def fwd(a, b):
        d = a - b
        return d * d
    return apply("square_error_cost", fwd, [input, label])


def l1_loss(input, label, reduction="mean", name=None):
    def fwd(a, b):
        return _reduce(jnp.abs(a - b), reduction)
    return apply("l1_loss", fwd, [input, label])


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    def fwd(logp, lab, *w):
        valid = lab != ignore_index
        li = jnp.where(valid, lab, 0).astype(jnp.int32)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(li, 1),
                                     axis=1).squeeze(1)
        loss = -picked
        if w:
            wc = w[0][li]
            loss = loss * wc
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = (w[0][li] * valid).sum() if w else valid.sum()
            return loss.sum() / jnp.maximum(denom.astype(jnp.float32), 1e-12)
        return _reduce(loss, reduction)
    ins = [input, label] + ([weight] if weight is not None else [])
    return apply("nll_loss", fwd, ins)


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    def fwd(p, y, *w):
        pf = jnp.clip(p.astype(jnp.float32), 1e-12, 1 - 1e-12)
        loss = -(y * jnp.log(pf) + (1 - y) * jnp.log1p(-pf))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    ins = [input, label] + ([weight] if weight is not None else [])
    return apply("bce", fwd, ins)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    def fwd(z, y, *extra):
        zf = z.astype(jnp.float32)
        yf = y.astype(jnp.float32)
        # numerically stable: max(z,0) - z*y + log(1+exp(-|z|))
        base = jnp.maximum(zf, 0) - zf * yf + jnp.log1p(jnp.exp(-jnp.abs(zf)))
        i = 0
        if pos_weight is not None:
            pw = extra[i]
            i += 1
            # standard reweighting of the positive term
            log_sig = jax.nn.log_sigmoid(zf)
            log_one_minus = jax.nn.log_sigmoid(-zf)
            base = -(pw * yf * log_sig + (1 - yf) * log_one_minus)
        if weight is not None:
            base = base * extra[i]
        return _reduce(base, reduction)
    ins = [logit, label]
    if pos_weight is not None:
        ins.append(pos_weight)
    if weight is not None:
        ins.append(weight)
    return apply("bce_with_logits", fwd, ins)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fwd(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)
    return apply("smooth_l1", fwd, [input, label])


def kl_div(input, label, reduction="mean", name=None):
    def fwd(logp, y):
        loss = y * (jnp.log(jnp.clip(y, 1e-12, None)) - logp)
        if reduction == "batchmean":
            return loss.sum() / logp.shape[0]
        return _reduce(loss, reduction)
    return apply("kl_div", fwd, [input, label])


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def fwd(a, b, y):
        loss = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce(loss, reduction)
    return apply("margin_ranking", fwd, [input, other, label])
