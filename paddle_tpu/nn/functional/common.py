"""Common functionals: linear, dropout, pad, embedding, attention.

Reference: python/paddle/nn/functional/{common,input}.py. linear keeps paddle's
weight layout [in_features, out_features] (x @ W + b), which is already the
MXU-friendly layout. Dropout draws from the framework RNG (core/random.py) so
it is deterministic under paddle.seed and stageable under jit via
trace_key_scope.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core import random as _random
from ...core.dispatch import apply

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "pad", "embedding",
    "cosine_similarity", "interpolate", "upsample", "unfold", "fold",
    "scaled_dot_product_attention", "alpha_dropout", "label_smooth",
    "pixel_shuffle", "pixel_unshuffle", "affine_grid", "grid_sample",
    "temporal_shift",
]


def linear(x, weight, bias=None, name=None):
    """x @ W + b with W: [in, out] (reference: F.linear, weight NOT transposed)."""
    from ...core.enforce import check_linear
    check_linear(x.shape, weight.shape,
                 bias.shape if bias is not None else None)

    def fwd(a, w, *b):
        out = jnp.matmul(a, w)
        if b:
            out = out + b[0]
        return out
    ins = [x, weight] + ([bias] if bias is not None else [])
    return apply("linear", fwd, ins)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    """Reference: python/paddle/nn/functional/common.py:967 (dropout)."""
    if p == 0.0 or (not training and mode == "upscale_in_train"):
        return x * 1 if not x.stop_gradient else x
    if p == 1.0 and training:
        return x * 0
    key = _random.next_key() if training else None

    def fwd(a):
        if not training:  # downscale_in_infer
            return a * (1 - p)
        shape = list(a.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1 - p, shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return apply("dropout", fwd, [x])


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0:
        return x * 1 if not x.stop_gradient else x
    alpha = -1.7580993408473766
    key = _random.next_key()

    def fwd(a):
        keep = jax.random.bernoulli(key, 1 - p, a.shape)
        q = 1 - p
        a_scale = (q + alpha ** 2 * q * p) ** -0.5
        b_shift = -a_scale * alpha * p
        return (a_scale * jnp.where(keep, a, alpha) + b_shift).astype(a.dtype)
    return apply("alpha_dropout", fwd, [x])


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    """paddle F.pad: `pad` is [lo, hi] per spatial dim (last-dims order) when
    len(pad) == 2*(ndim-2), else per-dim pairs for all dims."""
    nd = x.ndim

    def build_pairs():
        p = list(int(v) for v in pad)
        if len(p) == 2 * nd:  # all dims, flat
            return [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
        n_sp = len(p) // 2
        pairs = [(0, 0)] * nd
        channel_last = data_format[-1] == "C"
        sp_axes = list(range(1, 1 + n_sp)) if channel_last else \
            list(range(nd - n_sp, nd))
        # paddle order: last spatial dim first in `pad`? No: [left, right,
        # top, bottom] pads W then H → reversed spatial order
        for i, ax in enumerate(reversed(sp_axes)):
            pairs[ax] = (p[2 * i], p[2 * i + 1])
        return pairs

    pairs = build_pairs()

    def fwd(a):
        if mode == "constant":
            return jnp.pad(a, pairs, constant_values=value)
        jmode = {"reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        return jnp.pad(a, pairs, mode=jmode)
    return apply("pad", fwd, [x])


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Reference: python/paddle/nn/functional/input.py (embedding).
    Gather rows of weight; padding_idx rows get zero gradient."""
    from ...core.enforce import check_embedding
    check_embedding(x.dtype, weight.shape)

    def fwd(ids, w):
        out = jnp.take(w, ids.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return apply("embedding", fwd, [x, weight])


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def fwd(a, b):
        num = (a * b).sum(axis=axis)
        na = jnp.sqrt((a * a).sum(axis=axis))
        nb = jnp.sqrt((b * b).sum(axis=axis))
        return num / jnp.maximum(na * nb, eps)
    return apply("cosine_similarity", fwd, [x1, x2])


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    assert data_format in ("NCHW", "NCL", "NCDHW"), data_format
    n_sp = x.ndim - 2
    in_sp = x.shape[2:]
    if size is None:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else \
            [scale_factor] * n_sp
        size = [int(s * f) for s, f in zip(in_sp, sf)]
    elif isinstance(size, int):
        size = [size] * n_sp
    size = [int(s) for s in size]
    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def fwd(a):
        out_shape = tuple(a.shape[:2]) + tuple(size)
        return jax.image.resize(a, out_shape, method=jmode)
    return apply("interpolate", fwd, [x])


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format, name)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference: F.unfold). Output [N, C*kh*kw, L]."""
    kh, kw = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) \
        else kernel_sizes
    sh, sw = (strides, strides) if isinstance(strides, int) else strides
    ph, pw = (paddings, paddings) if isinstance(paddings, int) else paddings
    dh, dw = (dilations, dilations) if isinstance(dilations, int) else dilations

    def fwd(a):
        n, c, h, w = a.shape
        pads = [(ph, ph), (pw, pw)]  # spatial dims only
        patches = jax.lax.conv_general_dilated_patches(
            a, (kh, kw), (sh, sw), pads, rhs_dilation=(dh, dw),
            dimension_numbers=jax.lax.conv_dimension_numbers(
                a.shape, (1, c, kh, kw), ("NCHW", "OIHW", "NCHW")))
        # patches: [N, C*kh*kw, oh, ow]
        return patches.reshape(n, patches.shape[1], -1)
    return apply("unfold", fwd, [x])


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fwd(y, *p):
        k = y.shape[-1]
        if p:
            return (1 - epsilon) * y + epsilon * p[0]
        return (1 - epsilon) * y + epsilon / k
    ins = [label] + ([prior_dist] if prior_dist is not None else [])
    return apply("label_smooth", fwd, ins)


def _flash_eligible(query, key, attn_mask, dropout_p, training, is_causal):
    """Use the Pallas flash-attention kernel when the configuration maps onto
    it: TPU device, no explicit mask, no dropout, head_dim ≤ 128 and (causal
    or block-divisible keys) — AND the demotion gate agrees: under
    ``PADDLE_TPU_KERNELS=auto`` a measured A/B verdict (bench kernels leg /
    explicit ab_gate) at this or a nearby shape decides; with no verdict
    the incumbent-winner default keeps the kernel serving (a measured LOSS
    demotes it)."""
    from ...framework.flags import get_flags
    if not get_flags("FLAGS_use_flash_attention")["FLAGS_use_flash_attention"]:
        return False
    if attn_mask is not None or (dropout_p > 0 and training):
        return False
    if query.shape[-1] > 128 or query.ndim != 4:
        return False
    import jax as _jax

    from ...core.device import _platform_of
    if _platform_of(_jax.devices()[0]) != "tpu":
        return False
    from ...ops.pallas import _common as _gate
    return _gate.pallas_default(
        "flash_attention", _gate.shape_sig(query, key), allow_nearest=True)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Reference: paddle.nn.functional.scaled_dot_product_attention
    (flash_attn kernel, phi/kernels/gpu/flash_attn_kernel.cu). Layout
    [batch, seq, heads, head_dim]. The Pallas flash-attention kernel
    (ops/pallas/flash_attention.py) backs the eligible cases; the XLA
    fused chain is the fallback."""
    if _flash_eligible(query, key, attn_mask, dropout_p, training, is_causal):
        from ...ops.pallas.flash_attention import flash_attention_bshd
        return apply("flash_attention",
                     lambda q, k, v: flash_attention_bshd(
                         q, k, v, causal=is_causal), [query, key, value])
    dk = _random.next_key() if (dropout_p > 0 and training) else None

    def fwd(q, k, v, *m):
        qf = q.astype(jnp.float32)
        kf = k.astype(jnp.float32)
        scale = 1.0 / np.sqrt(q.shape[-1])
        # [B, S, H, D] -> [B, H, S, D]
        qt = jnp.swapaxes(qf, 1, 2)
        kt = jnp.swapaxes(kf, 1, 2)
        vt = jnp.swapaxes(v.astype(jnp.float32), 1, 2)
        scores = jnp.einsum("bhsd,bhtd->bhst", qt, kt) * scale
        if is_causal:
            s, t = scores.shape[-2], scores.shape[-1]
            causal = jnp.tril(jnp.ones((s, t), bool))
            scores = jnp.where(causal, scores, -1e30)
        if m:
            mask = m[0]
            if mask.dtype == jnp.bool_:
                scores = jnp.where(mask, scores, -1e30)
            else:
                scores = scores + mask.astype(jnp.float32)
        probs = jax.nn.softmax(scores, axis=-1)
        if dk is not None:
            keep = jax.random.bernoulli(dk, 1 - dropout_p, probs.shape)
            probs = jnp.where(keep, probs / (1 - dropout_p), 0.0)
        out = jnp.einsum("bhst,bhtd->bhsd", probs, vt)
        return jnp.swapaxes(out, 1, 2).astype(q.dtype)

    ins = [query, key, value] + ([attn_mask] if attn_mask is not None else [])
    return apply("scaled_dot_product_attention", fwd, ins)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    """Reference: nn/functional/vision.py pixel_shuffle (phi
    pixel_shuffle_kernel): rearranges [N, C*r^2, H, W] -> [N, C, H*r, W*r].
    """
    r = int(upscale_factor)

    def f(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        n, c, h, w = a.shape
        oc = c // (r * r)
        a = a.reshape(n, oc, r, r, h, w)
        a = jnp.transpose(a, (0, 1, 4, 2, 5, 3))
        a = a.reshape(n, oc, h * r, w * r)
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 2, 3, 1))
        return a

    return apply("pixel_shuffle", f, [x])


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = int(downscale_factor)

    def f(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        n, c, h, w = a.shape
        a = a.reshape(n, c, h // r, r, w // r, r)
        a = jnp.transpose(a, (0, 1, 3, 5, 2, 4))
        a = a.reshape(n, c * r * r, h // r, w // r)
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 2, 3, 1))
        return a

    return apply("pixel_unshuffle", f, [x])


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Reference: nn/functional/vision.py affine_grid. theta [N, 2, 3];
    out_shape [N, C, H, W] -> grid [N, H, W, 2] (x, y in [-1, 1])."""
    N, C, H, W = [int(d) for d in out_shape]

    def f(th):
        if align_corners:
            xs = jnp.linspace(-1.0, 1.0, W)
            ys = jnp.linspace(-1.0, 1.0, H)
        else:
            xs = (jnp.arange(W) * 2 + 1) / W - 1.0
            ys = (jnp.arange(H) * 2 + 1) / H - 1.0
        gx, gy = jnp.meshgrid(xs, ys)              # [H, W]
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # [H, W, 3]
        return jnp.einsum("hwk,nck->nhwc", base.astype(th.dtype), th)

    return apply("affine_grid", f, [theta])


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Reference: nn/functional/vision.py grid_sample (phi grid_sample).
    x [N, C, H, W]; grid [N, Ho, Wo, 2] normalized coords."""

    if mode not in ("bilinear", "nearest"):
        raise NotImplementedError(f"grid_sample mode {mode!r} "
                                  "(bilinear/nearest supported)")
    if padding_mode not in ("zeros", "border"):
        raise NotImplementedError(
            f"grid_sample padding_mode {padding_mode!r} "
            "(zeros/border supported)")

    def f(a, g):
        n, c, h, w = a.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            fx = (gx + 1.0) * (w - 1) / 2.0
            fy = (gy + 1.0) * (h - 1) / 2.0
        else:
            fx = ((gx + 1.0) * w - 1.0) / 2.0
            fy = ((gy + 1.0) * h - 1.0) / 2.0

        def gather(yy, xx):
            """a[n, :, yy, xx] with out-of-bounds handling -> [N,Ho,Wo,C]"""
            inside = ((xx >= 0) & (xx <= w - 1) & (yy >= 0)
                      & (yy <= h - 1))
            xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            batch = jnp.arange(n)[:, None, None]
            vals = a[batch, :, yc, xc]             # [N, Ho, Wo, C]
            if padding_mode == "zeros":
                vals = jnp.where(inside[..., None], vals, 0.0)
            return vals

        if mode == "nearest":
            out = gather(jnp.round(fy), jnp.round(fx))
        else:  # bilinear
            x0, y0 = jnp.floor(fx), jnp.floor(fy)
            x1, y1 = x0 + 1, y0 + 1
            wa = (x1 - fx) * (y1 - fy)
            wb = (fx - x0) * (y1 - fy)
            wc = (x1 - fx) * (fy - y0)
            wd = (fx - x0) * (fy - y0)
            out = (gather(y0, x0) * wa[..., None]
                   + gather(y0, x1) * wb[..., None]
                   + gather(y1, x0) * wc[..., None]
                   + gather(y1, x1) * wd[..., None])
        return jnp.transpose(out, (0, 3, 1, 2))    # [N, C, Ho, Wo]

    return apply("grid_sample", f, [x, grid])


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im, the inverse of unfold (reference: F.fold over phi
    fold_kernel). x: [N, C*kh*kw, L] -> [N, C, out_h, out_w]; overlapping
    patch contributions accumulate (one scatter-add, like istft's WOLA)."""
    oh_, ow_ = (output_sizes, output_sizes) if isinstance(
        output_sizes, int) else output_sizes
    kh, kw = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) \
        else kernel_sizes
    sh, sw = (strides, strides) if isinstance(strides, int) else strides
    ph, pw = (paddings, paddings) if isinstance(paddings, int) else paddings
    dh, dw = (dilations, dilations) if isinstance(dilations, int) \
        else dilations

    def fwd(a):
        n, ckk, L = a.shape
        c = ckk // (kh * kw)
        nh = (oh_ + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        nw = (ow_ + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        assert nh * nw == L, (f"L={L} inconsistent with output_sizes "
                              f"({nh}x{nw} patches expected)")
        a = a.reshape(n, c, kh, kw, nh, nw)
        # padded-canvas positions of every (patch, offset) sample
        py = (jnp.arange(nh)[:, None] * sh
              + jnp.arange(kh)[None, :] * dh)     # [nh, kh]
        px = (jnp.arange(nw)[:, None] * sw
              + jnp.arange(kw)[None, :] * dw)     # [nw, kw]
        Hp, Wp = oh_ + 2 * ph, ow_ + 2 * pw
        flat_pos = (py[:, :, None, None] * Wp
                    + px[None, None, :, :])       # [nh, kh, nw, kw]
        vals = jnp.transpose(a, (0, 1, 4, 2, 5, 3))  # [n, c, nh, kh, nw, kw]
        out = jnp.zeros((n, c, Hp * Wp), a.dtype).at[
            :, :, flat_pos.reshape(-1)].add(
            vals.reshape(n, c, -1))
        out = out.reshape(n, c, Hp, Wp)
        return out[:, :, ph:ph + oh_, pw:pw + ow_]

    return apply("fold", fwd, [x])


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM temporal shift (reference: F.temporal_shift over phi
    temporal_shift_kernel): shift the first shift_ratio channels one step
    back in time, the next block one step forward; zero-pad the ends."""

    if shift_ratio > 0.5:
        raise ValueError(
            f"temporal_shift shift_ratio ({shift_ratio}) must be <= 0.5 "
            "(back + forward shifted blocks cannot exceed the channels)")

    def fwd(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        nt, c, h, w = a.shape
        n = nt // seg_num
        a = a.reshape(n, seg_num, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        back = jnp.concatenate(
            [a[:, 1:, :c1], jnp.zeros_like(a[:, :1, :c1])], axis=1)
        fwd_ = jnp.concatenate(
            [jnp.zeros_like(a[:, :1, c1:c2]), a[:, :-1, c1:c2]], axis=1)
        out = jnp.concatenate([back, fwd_, a[:, :, c2:]], axis=2)
        out = out.reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return apply("temporal_shift", fwd, [x])
