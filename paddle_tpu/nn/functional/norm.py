"""Normalization functionals (reference: python/paddle/nn/functional/norm.py).

XLA fuses the mean/var/scale chain into one kernel on TPU, replacing the
reference's fused CUDA kernels (phi/kernels/gpu/batch_norm_kernel.cu,
fusion/gpu/fused_layernorm_kernel.cu). Statistics are computed in f32 even for
bf16 inputs (TPU numerics practice).
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.dispatch import apply

__all__ = ["layer_norm", "batch_norm", "group_norm", "instance_norm",
           "rms_norm", "normalize"]


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(normalized_shape)
    axes = tuple(range(-n_axes, 0))

    def fwd(a, *wb):
        af = a.astype(jnp.float32)
        mean = af.mean(axis=axes, keepdims=True)
        var = af.var(axis=axes, keepdims=True)
        out = (af - mean) / jnp.sqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32)
        return out.astype(a.dtype)

    ins = [x] + [t for t in (weight, bias) if t is not None]
    return apply("layer_norm", fwd, ins)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm over the last axis (reference analog:
    python/paddle/incubate/nn/functional/fused_rms_norm.py)."""
    def fwd(a, *w):
        af = a.astype(jnp.float32)
        ms = jnp.mean(af * af, axis=-1, keepdims=True)
        out = af / jnp.sqrt(ms + epsilon)
        if w:
            out = out * w[0].astype(jnp.float32)
        return out.astype(a.dtype)
    ins = [x] + ([weight] if weight is not None else [])
    return apply("rms_norm", fwd, ins)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None):
    """Reference: python/paddle/nn/functional/norm.py:124 (batch_norm).

    paddle momentum semantics: running = momentum * running + (1-m) * batch.
    Running stats are updated in place on the buffer tensors (outside the
    tape), matching the reference's mutable mean/variance outputs.
    """
    ch_axis = 1 if data_format[1] == "C" or data_format in ("NC", "NCL") else -1
    if data_format[-1] == "C" and len(data_format) > 2:
        ch_axis = -1
    red_axes = tuple(i for i in range(x.ndim) if i != (ch_axis % x.ndim))
    use_batch_stats = training and not use_global_stats

    if use_batch_stats:
        xf = x._data.astype(jnp.float32)
        batch_mean = xf.mean(axis=red_axes)
        batch_var = xf.var(axis=red_axes)
        # in-place running-stat update (no tape), paddle momentum convention
        running_mean._data = (momentum * running_mean._data.astype(jnp.float32)
                              + (1 - momentum) * batch_mean).astype(
                                  running_mean._data.dtype)
        running_var._data = (momentum * running_var._data.astype(jnp.float32)
                             + (1 - momentum) * batch_var).astype(
                                 running_var._data.dtype)

    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis % x.ndim]

    def fwd(a, *wb):
        af = a.astype(jnp.float32)
        if use_batch_stats:
            mean = af.mean(axis=red_axes)
            var = af.var(axis=red_axes)
        else:
            mean = wb[-2].astype(jnp.float32)
            var = wb[-1].astype(jnp.float32)
        out = (af - mean.reshape(shape)) / jnp.sqrt(
            var.reshape(shape) + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32).reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32).reshape(shape)
        return out.astype(a.dtype)

    ins = [x] + [t for t in (weight, bias) if t is not None]
    if not use_batch_stats:
        ins += [running_mean, running_var]
    return apply("batch_norm", fwd, ins)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    assert data_format == "NCHW", "group_norm supports NCHW"
    C = x.shape[1]
    assert C % num_groups == 0

    def fwd(a, *wb):
        n = a.shape[0]
        af = a.astype(jnp.float32).reshape((n, num_groups, C // num_groups)
                                           + tuple(a.shape[2:]))
        axes = tuple(range(2, af.ndim))
        mean = af.mean(axis=axes, keepdims=True)
        var = af.var(axis=axes, keepdims=True)
        out = ((af - mean) / jnp.sqrt(var + epsilon)).reshape(a.shape)
        shape = [1, C] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32).reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32).reshape(shape)
        return out.astype(a.dtype)

    ins = [x] + [t for t in (weight, bias) if t is not None]
    return apply("group_norm", fwd, ins)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    assert data_format == "NCHW"

    def fwd(a, *wb):
        af = a.astype(jnp.float32)
        axes = tuple(range(2, a.ndim))
        mean = af.mean(axis=axes, keepdims=True)
        var = af.var(axis=axes, keepdims=True)
        out = (af - mean) / jnp.sqrt(var + eps)
        shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32).reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32).reshape(shape)
        return out.astype(a.dtype)

    ins = [x] + [t for t in (weight, bias) if t is not None]
    return apply("instance_norm", fwd, ins)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fwd(a):
        if p == 2:
            norm = jnp.sqrt(jnp.sum(a * a, axis=axis, keepdims=True))
        else:
            norm = jnp.sum(jnp.abs(a) ** p, axis=axis,
                           keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(norm, epsilon)
    return apply("normalize", fwd, [x])
